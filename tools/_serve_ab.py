"""Open-loop served-load driver for the serving runtime (ISSUE 7 + 11).

Open-loop means arrivals do NOT wait for the system: request i arrives at
its scheduled offset (exponential inter-arrival at `rate` req/s) whether or
not the engine is keeping up — the only honest load model for "heavy
traffic from millions of users" (a closed loop self-throttles and hides
queueing collapse). Per-request stamps (arrival, first token, completion)
feed the shared tools/_timing.py percentile protocol, so p50/p99 here and
in the bench.py `serving` block are the same arithmetic.

ISSUE 11 adds the multi-tenant workload: `--shared-prefix` draws each
request's system prompt zipf-distributed from a small set (the
many-users-few-templates shape of production traffic), runs the sweep at
10x the r8 request rates, and `--ab` interleaves a PR 7-equivalent
baseline arm (prefix cache off, no speculation) over the SAME seeded
arrival trace — served tok/s up + prefill-tokens-computed down is the
acceptance bar, printed per rate.

    python tools/_serve_ab.py                       # default rate sweep
    python tools/_serve_ab.py --rates 4,16,64 --requests 64
    python tools/_serve_ab.py --shared-prefix --ab  # the ISSUE 11 verdict
    python tools/_serve_ab.py --pool-pages 64       # pressure the pool
    python tools/_serve_ab.py --fleet               # the ISSUE 16 fleet
                                                    # campaign (4 arms)
    python tools/_serve_ab.py --disagg              # the ISSUE 19 disagg
                                                    # campaign (co-located
                                                    # vs prefill/decode
                                                    # split vs mid-handoff
                                                    # kill), gated via
                                                    # gate.py --disagg over
                                                    # DISAGG_r*.json

Each rate prints one JSON line; the last line is the sweep summary.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from collections import deque

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from tools import _timing  # noqa: E402


def synth_workload(n_requests: int, vocab_size: int, seed: int,
                   prompt_lens=(4, 24), max_new: int = 8,
                   rate: float = 8.0) -> list:
    """[(arrival_offset_s, prompt, max_new)] — seeded, so a rate's workload
    replays identically across runs/arms."""
    rng = np.random.default_rng(seed)
    arrivals = np.cumsum(rng.exponential(1.0 / rate, n_requests))
    lo, hi = prompt_lens
    out = []
    for i in range(n_requests):
        plen = int(rng.integers(lo, hi + 1))
        prompt = rng.integers(1, vocab_size, plen).tolist()
        out.append((float(arrivals[i]), prompt, int(max_new)))
    return out


def synth_shared_prefix_workload(n_requests: int, vocab_size: int, seed: int,
                                 n_sys_prompts: int = 8, sys_len: int = 16,
                                 user_lens=(2, 8), max_new: int = 8,
                                 rate: float = 8.0,
                                 zipf_a: float = 1.2) -> list:
    """The multi-tenant mix: every request = one of `n_sys_prompts` shared
    system prompts (zipf-ranked — a few templates carry most traffic, the
    tail stays cold) + a short unique user suffix. Seeded like
    synth_workload, so the prefix-cache arm and the baseline arm replay the
    IDENTICAL arrival trace."""
    rng = np.random.default_rng(seed)
    sys_prompts = [rng.integers(1, vocab_size, sys_len).tolist()
                   for _ in range(n_sys_prompts)]
    ranks = np.arange(1, n_sys_prompts + 1, dtype=np.float64) ** -zipf_a
    probs = ranks / ranks.sum()
    arrivals = np.cumsum(rng.exponential(1.0 / rate, n_requests))
    lo, hi = user_lens
    out = []
    for i in range(n_requests):
        which = int(rng.choice(n_sys_prompts, p=probs))
        suffix = rng.integers(1, vocab_size,
                              int(rng.integers(lo, hi + 1))).tolist()
        out.append((float(arrivals[i]), sys_prompts[which] + suffix,
                    int(max_new)))
    return out


def _drive(engine, workload, max_steps: int):
    """Replay one seeded arrival trace through the engine; returns the
    measured pass's request ids and wall time."""
    pending = deque(sorted(workload))
    rids = []
    t0 = time.perf_counter()
    steps = 0
    while pending or engine.has_work():
        now = time.perf_counter() - t0
        while pending and pending[0][0] <= now:
            _, prompt, max_new = pending.popleft()
            rids.append(engine.submit(prompt, max_new))
        if engine.has_work():
            engine.step()
        elif pending:
            time.sleep(min(0.002, max(0.0, pending[0][0] - now)))
        steps += 1
        if steps > max_steps:
            raise RuntimeError(f"open loop did not drain in {max_steps} "
                               f"iterations")
    return rids, time.perf_counter() - t0


def run_open_loop(engine, workload, max_steps: int = 200_000,
                  warmup: bool = False) -> dict:
    """Drive one engine through one workload; returns the serving metrics
    block (served tokens/s, p50/p99 request + first-token latency, pool
    occupancy, prefix-cache + speculative-decode counters, and the
    zero-leak page/refcount accounting).

    warmup=True measures the COMPILE-FREE steady state: the trace replays
    (up to 4 passes) until one pass triggers zero fresh XLA compiles — a
    single stray sub-second CPU compile inside a sub-second measured pass
    otherwise decides the verdict, not the engines. Queue dynamics shift
    batch-bucket signatures between passes, so one discarded pass is not
    enough; the jit_compile_counter hook (PR 2) says when the cache is
    actually saturated. The prefix cache stays warm across passes — the
    sustained-serving regime a production engine lives in, and the only one
    where arms with different compile footprints compare honestly."""
    from paddle_tpu import observability as obs
    from paddle_tpu.pipeline import jit_compile_counter

    # scope the registry's serving series to THIS run: sequential bench
    # arms share the one process-wide registry, and the telemetry block
    # below must describe this engine's measured pass only
    obs.reset("serving.")
    passes = 8 if warmup else 1
    n_compiles = 0
    clean_streak = 0
    if warmup:
        # the decode (batch, pages) signature a step hits is load-timing
        # dependent — precompile the whole lattice so no pass can get a
        # stray XLA compile from an unluckily-deep (or -shallow) queue
        engine.warmup_decode(max(len(p) + mn for _, p, mn in workload))
    for att in range(passes):
        with jit_compile_counter() as compiles:
            rids, wall = _drive(engine, workload, max_steps)
        n_compiles = compiles.count
        if not warmup:
            break
        # accept the SECOND consecutive compile-free pass: the first one
        # still pays for the compile passes' side effects (allocator and
        # dispatch caches, OS frequency state) and reads 2-5x slow
        clean_streak = clean_streak + 1 if n_compiles == 0 else 0
        if clean_streak >= 2:
            break
        if att < passes - 1:
            engine.reset_stats()
            # discarded pass: drop its request records (their stamps are
            # never read) so repeated warmup passes don't grow the engine
            engine.prune_finished()

    reqs = [engine.requests[r] for r in rids]
    done = [r for r in reqs if r.state == "finished"]
    lat = [r.t_done - r.arrival_t for r in done]
    ttft = [r.t_first_token - r.arrival_t for r in done
            if r.t_first_token is not None]
    served_tokens = sum(r.n_generated for r in done)
    st = engine.stats
    ss = engine.stats_snapshot()  # every derived rate divide-guarded
    leaked = ss["leaked_pages"]
    obs.gauge_set("serving.leaked_pages", leaked)
    engine.flush_prefix_cache()
    # after drain + flush only a refcount bug can keep pages off-list
    refcount_leaks = engine.pool.num_pages - engine.pool.free_count
    out = {
        "requests": len(reqs),
        "finished": len(done),
        "aborted": sum(1 for r in reqs if r.state == "aborted"),
        "served_tokens": served_tokens,
        "wall_s": round(wall, 4),
        "served_tokens_per_sec": round(served_tokens / wall, 2) if wall else 0.0,
        "request_latency": _timing.latency_stats(lat),
        "first_token_latency": _timing.latency_stats(ttft),
        "kv_pool_occupancy_mean": round(ss["occupancy_mean"], 4),
        "kv_pool_occupancy_peak": round(
            st["peak_pages_in_use"] / engine.pool.num_pages, 4),
        "kv_pages_leaked": leaked,
        "refcount_leaks": refcount_leaks,
        "decode_steps": st["decode_steps"],
        "prefills": st["prefills"],
        "preemptions": st["preemptions"],
        "decode_compile_buckets": len(st["decode_signatures"]),
        "prefill_compile_buckets": len(st["prefill_signatures"]),
        "measured_pass_compiles": n_compiles,
        # prefix caching (ISSUE 11): how much prefill the cache absorbed
        "prefill_tokens_computed": st["prefill_tokens_computed"],
        "prefix_hit_tokens": st["prefix_hit_tokens"],
        "prefix_cache_hit_rate": round(ss["prefix_cache_hit_rate"], 4),
        "prefix_full_hits": st["prefix_full_hits"],
        "cow_copies": st["cow_copies"],
        # speculative decoding (ISSUE 11): accepted-token rate
        "spec_steps": st["spec_steps"],
        "spec_accept_rate": round(ss["spec_accept_rate"], 4),
        "tokens_per_decode_step": round(ss["tokens_per_decode_step"], 3),
    }
    out["telemetry"] = _registry_view(obs.snapshot())
    return out


def _registry_view(snap: dict) -> dict:
    """The registry's read of the run just measured (ISSUE 13): the same
    TTFT/queue/occupancy numbers as the stamp-based block above, but read
    back through the one snapshot() every surface now lands in — the
    acceptance check that the serving path is actually registry-backed."""
    def _ms(name, key):
        h = snap.get("histograms", {}).get(name)
        v = h.get(key) if h else None
        return round(v * 1e3, 3) if v is not None else None

    return {
        "ttft_ms_p50": _ms("serving.ttft_s", "p50"),
        "ttft_ms_p99": _ms("serving.ttft_s", "p99"),
        "queue_ms_p50": _ms("serving.queue_s", "p50"),
        "queue_ms_p99": _ms("serving.queue_s", "p99"),
        "request_ms_p50": _ms("serving.request_s", "p50"),
        "request_ms_p99": _ms("serving.request_s", "p99"),
        "pool_occupancy": snap.get("gauges", {}).get(
            "serving.pool_occupancy"),
        "registry_decode_steps": snap.get("counters", {}).get(
            "serving.decode_steps", 0),
        "registry_cow_copies": snap.get("counters", {}).get(
            "serving.cow_copies", 0),
    }


def _drive_overload(engine, workload, max_steps: int):
    """The reject-tolerant open loop (ISSUE 14): identical to _drive except
    a submit bounced by admission control (AdmissionRejected) is counted and
    dropped instead of crashing the driver — under deliberate overload the
    bounce IS the behavior being measured. Returns (admitted_rids,
    rejected_count, wall_s)."""
    from paddle_tpu.serving import AdmissionRejected

    pending = deque(sorted(workload))
    rids, rejected = [], 0
    t0 = time.perf_counter()
    steps = 0
    while pending or engine.has_work():
        now = time.perf_counter() - t0
        while pending and pending[0][0] <= now:
            _, prompt, max_new = pending.popleft()
            try:
                rids.append(engine.submit(prompt, max_new))
            except AdmissionRejected:
                rejected += 1
        if engine.has_work():
            engine.step()
        elif pending:
            time.sleep(min(0.002, max(0.0, pending[0][0] - now)))
        steps += 1
        if steps > max_steps:
            raise RuntimeError(f"overload loop did not drain in {max_steps} "
                               f"iterations")
    return rids, rejected, time.perf_counter() - t0


def run_overload_arm(engine, workload, max_steps: int = 200_000,
                     fault_plan: str | None = None) -> dict:
    """One arm of the ISSUE 14 overload block: drive the trace through the
    reject-tolerant loop after the run_open_loop warmup protocol (compile
    the signature lattice, replay until two consecutive compile-free
    passes), and report GOODPUT — tokens of *finished* requests per second
    — plus the shed/reject/recovery accounting. Shed, rejected and expired
    requests contribute zero goodput by construction; an engine that saves
    itself by shedding scores honestly, one that thrashes does not.

    fault_plan, when set, replays the trace ONE more time after warmup
    under that resilience fault plan (faults are kept out of the warmup
    passes so the plan's bounded hit budget lands entirely in the measured
    pass)."""
    from paddle_tpu import observability as obs
    from paddle_tpu.pipeline import jit_compile_counter
    from paddle_tpu.resilience.faults import fault_scope

    obs.reset("serving.")
    engine.warmup_decode(max(len(p) + mn for _, p, mn in workload))
    clean_streak = 0
    for att in range(8):
        with jit_compile_counter() as compiles:
            rids, rejected, wall = _drive_overload(engine, workload,
                                                   max_steps)
        clean_streak = clean_streak + 1 if compiles.count == 0 else 0
        if clean_streak >= 2:
            break
        if att < 7:
            engine.reset_stats()
            engine.prune_finished()
    n_compiles = compiles.count
    if fault_plan:
        engine.reset_stats()
        engine.prune_finished()
        with fault_scope(fault_plan):
            with jit_compile_counter() as compiles:
                rids, rejected, wall = _drive_overload(engine, workload,
                                                       max_steps)
        n_compiles = compiles.count

    reqs = [engine.requests[r] for r in rids]
    done = [r for r in reqs if r.state == "finished"]
    ttft = [r.t_first_token - r.arrival_t for r in done
            if r.t_first_token is not None]
    goodput_tokens = sum(r.n_generated for r in done)
    st = engine.stats
    ss = engine.stats_snapshot()
    leaked = ss["leaked_pages"]
    engine.flush_prefix_cache()
    refcount_leaks = engine.pool.num_pages - engine.pool.free_count
    return {
        "offered": len(reqs) + rejected,
        "admitted": len(reqs),
        "finished": len(done),
        "rejected": rejected,
        "shed": st["shed"],
        "deadline_exceeded": st["deadline_exceeded"],
        "goodput_tokens": goodput_tokens,
        "wall_s": round(wall, 4),
        "goodput_tok_s": (round(goodput_tokens / wall, 2) if wall else 0.0),
        "admitted_ttft": _timing.latency_stats(ttft),
        "ladder_climbs": {r: st["ladder." + r] for r in
                          ("spec_off", "lookahead_shrink", "cache_evict",
                           "shed")},
        "recovery_passes": st["recovery.passes"],
        "step_retries": st["step_retries"],
        "quarantined": st["recovery.quarantined"],
        "kv_pages_leaked": leaked,
        "refcount_leaks": refcount_leaks,
        "measured_pass_compiles": n_compiles,
        # regime signals for the control sweep (ISSUE 20): what the pass
        # actually saw, so every knob arm of one regime records one key
        "prefix_cache_hit_rate": round(ss["prefix_cache_hit_rate"], 4),
        "kv_pool_occupancy_mean": round(ss["occupancy_mean"], 4),
    }


OVERLOAD_FAULT_PLAN = ("rand:p=0.05,seed=7,max=6,"
                       "sites=serving_step_fail|serving_pool_corrupt|"
                       "serving_deadline")


def overload_block(on_tpu: bool, seed: int = 0) -> dict:
    """The bench.py `serving.overload` block (ISSUE 14): the shared-prefix
    zipf mix replayed through THREE arms —

      unloaded          the r8-regime arrival rate, no admission floors;
                        the goodput yardstick
      overload          the SAME trace compressed to 10x the rate against
                        an engine with the shed floors + degradation
                        ladder armed
      overload_faulted  the overload arm under a bounded rand: plan over
                        the three serving fault sites (supervisor retries,
                        pool-rebuild recovery, forced deadline expiry)

    tools/gate.py hard-fails page/refcount leaks in ANY arm, overload
    goodput below 0.7x unloaded, faulted goodput below 0.7x overload, and
    an unbounded admitted-request p99 TTFT."""
    from paddle_tpu.serving import ServingEngine

    cfg, _, user_lens = ab_config(on_tpu, shared_prefix=True)
    if on_tpu:
        eng_kw = dict(page_size=16, pool_pages=2048, max_inflight=16)
        n_req, max_new, base_rate = 64, 16, 32.0
    else:
        # max_new is sized so the 10x arm's offered load actually exceeds
        # the tiny model's service rate — otherwise the queue never grows
        # and the shed floors are dead code in the measurement
        eng_kw = dict(page_size=4, pool_pages=64, max_inflight=4)
        n_req, max_new, base_rate = 32, 12, 8.0
    sys_len = (8 if on_tpu else 6) * eng_kw["page_size"]
    eng_kw.update(prefix_cache=True, draft_k=0, seed=seed)
    shed_kw = dict(shed_queue_depth=8, shed_occupancy=0.95, degrade_after=2)

    def wl(rate):
        return synth_shared_prefix_workload(
            n_req, cfg.vocab_size, seed=seed, n_sys_prompts=8,
            sys_len=sys_len, user_lens=user_lens, max_new=max_new,
            rate=rate)

    arms = {
        "unloaded": run_overload_arm(
            ServingEngine(cfg, **eng_kw), wl(base_rate)),
        "overload": run_overload_arm(
            ServingEngine(cfg, **eng_kw, **shed_kw), wl(10 * base_rate)),
        "overload_faulted": run_overload_arm(
            ServingEngine(cfg, **eng_kw, **shed_kw, audit_every=1,
                          step_retries=2),
            wl(10 * base_rate), fault_plan=OVERLOAD_FAULT_PLAN),
    }
    un, ov, fa = (arms["unloaded"], arms["overload"],
                  arms["overload_faulted"])

    def _ratio(a, b):
        return round(a / max(b, 1e-9), 3)

    p99_un = un["admitted_ttft"]["p99_ms"]
    p99_ov = ov["admitted_ttft"]["p99_ms"]
    return {
        "arms": arms,
        "rate_req_s": 10 * base_rate,
        "goodput_vs_unloaded": _ratio(ov["goodput_tok_s"],
                                      un["goodput_tok_s"]),
        "faulted_vs_overload": _ratio(fa["goodput_tok_s"],
                                      ov["goodput_tok_s"]),
        "ttft_p99_ratio": (_ratio(p99_ov, p99_un)
                           if p99_un and p99_ov else None),
        "shed_rate": _ratio(ov["shed"] + ov["rejected"], ov["offered"]),
        "config": (f"shared-prefix zipf1.2 sys{sys_len} "
                   f"r{base_rate:g}->r{10 * base_rate:g} n{n_req}"),
    }


def _drive_fleet(fr, workload, max_steps: int = 400_000,
                 kill_at_frac: float | None = None,
                 drain_at_frac: float | None = None):
    """Open-loop driver over a FleetRouter: same arrival honesty as _drive,
    but submits route through fleet placement and progress comes from
    step()/poll(). Optionally sigkills the most-loaded replica (silently —
    the router must DISCOVER it) or begins a drain once `frac` of the
    requests have finished. Returns (fids, wall_s, event_rid)."""
    from paddle_tpu.serving.fleet import FLEET_TERMINAL

    pending = deque(sorted(workload))
    fids = []
    event_rid = None
    threaded = fr.pump == "threads"
    t0 = time.perf_counter()
    steps = 0
    n_total = len(workload)

    def _n_done():
        return sum(1 for f in fids
                   if fr.requests[f].state in FLEET_TERMINAL)

    while pending or any(fr.requests[f].state not in FLEET_TERMINAL
                         for f in fids):
        now = time.perf_counter() - t0
        while pending and pending[0][0] <= now:
            _, prompt, max_new = pending.popleft()
            fids.append(fr.submit(prompt, max_new))
        # the event trigger runs DURING the arrival stream (not after it:
        # requests complete between arrivals, so by the time the queue is
        # empty ~everything is finished and nothing would be mid-stream)
        if event_rid is None:
            done_frac = _n_done() / max(n_total, 1)
            if kill_at_frac is not None and done_frac >= kill_at_frac:
                # the kill must be MEANINGFUL: land on a replica whose
                # in-flight requests have already streamed tokens, so the
                # replay/dedup path actually engages (a victim still in
                # prefill replays nothing and proves nothing). The router
                # ledger lags the engine by the outbox, so require a stream
                # nearer its start than its end — otherwise the engine may
                # already have finished it and only an empty queued request
                # would fail over. Defer until such a moment; near the end
                # give up and take the most-loaded so the arm always dies.
                def _mid_decode(r):
                    return sum(len(q.delivered) for q in fr.requests.values()
                               if q.replica == r.rid
                               and q.state not in FLEET_TERMINAL
                               and 1 <= len(q.delivered)
                               <= q.max_new_tokens // 2)
                alive = [r for r in fr.replicas if r.alive]
                victim = max(alive, key=lambda r: (_mid_decode(r), r.load()),
                             default=None)
                if victim is not None and (_mid_decode(victim) >= 4
                                           or done_frac >= 0.75):
                    victim.sigkill()  # silent: heartbeat discovery only
                    event_rid = victim.rid
            elif drain_at_frac is not None and done_frac >= drain_at_frac:
                cands = [r for r in fr.replicas if r.state == "healthy"]
                if len(cands) > 1:
                    event_rid = max(cands, key=lambda r: r.load()).rid
                    fr.drain(event_rid)
        progressed = fr.poll() if threaded else fr.step()
        if not progressed:
            time.sleep(0.0005)
        steps += 1
        if steps > max_steps:
            raise RuntimeError(f"fleet open loop did not settle in "
                               f"{max_steps} iterations")
    return fids, time.perf_counter() - t0, event_rid


def _fleet_arm_metrics(fr, fids, wall: float) -> dict:
    """Per-arm accounting off the router's ledger + stamps: delivered
    tokens/s, lost/duplicate counts (the hard zeros the gate enforces),
    TTFT percentiles, and zero-leak checks on every non-dead engine (a
    SIGKILLed replica's pool is gone with its host — auditing it would be
    reading freed memory)."""
    reqs = [fr.requests[f] for f in fids]
    done = [r for r in reqs if r.state == "finished"]
    ttft = [r.t_first - r.t_submit for r in done if r.t_first is not None]
    lat = [r.t_done - r.t_submit for r in done if r.t_done is not None]
    tokens = sum(len(r.delivered) for r in done)
    leaked = sum(rep.engine.leaked_pages() for rep in fr.replicas
                 if rep.state != "dead")
    return {
        "requests": len(reqs),
        "finished": len(done),
        "lost": sum(1 for r in reqs if r.state == "failed"),
        "shed": sum(1 for r in reqs if r.state == "shed"),
        "delivered_tokens": tokens,
        "wall_s": round(wall, 4),
        "tok_s": round(tokens / wall, 2) if wall else 0.0,
        "ttft": _timing.latency_stats(ttft),
        "request_latency": _timing.latency_stats(lat),
        "deaths": fr.stats["deaths"],
        "failovers": fr.stats["failovers"],
        "handoffs": fr.stats["handoffs"],
        "retires": fr.stats["retires"],
        "replayed_tokens": fr.stats["replayed_tokens"],
        "dedup_tokens": fr.stats["dedup_tokens"],
        "duplicate_tokens": (fr.stats["replayed_tokens"]
                             - fr.stats["dedup_tokens"]),
        "replay_divergence": fr.stats["replay_divergence"],
        "affinity_hits": fr.stats["affinity_hits"],
        "affinity_misses": fr.stats["affinity_misses"],
        "kv_pages_leaked": leaked,
    }


def _fleet_warm(fr, workload) -> None:
    """The fleet analog of run_open_loop's warmup: precompile each
    replica's decode lattice, then replay the trace (arrivals collapsed)
    until two consecutive compile-free passes so the measured arm times
    engines, not XLA. Health checking is suspended for the duration — a
    replica joins the heartbeat-checked pool only once warmed (a worker
    thread blocked seconds inside a legitimate compile must not read as a
    death; production fleets gate readiness the same way)."""
    from paddle_tpu.pipeline import jit_compile_counter

    horizon = max(len(p) + mn for _, p, mn in workload)
    for rep in fr.replicas:
        if rep.role != "prefill":  # a prefill-stage engine never decodes
            rep.engine.warmup_decode(horizon)
    saved_deadline = fr.monitor.deadline_s
    fr.monitor.deadline_s = 1e9
    try:
        clean = 0
        for _ in range(8):
            with jit_compile_counter() as compiles:
                fids = [fr.submit(p, mn) for _, p, mn in workload]
                fr.run_until_idle()
            clean = clean + 1 if compiles.count == 0 else 0
            if clean >= 2:
                break
        assert all(fr.state(f) == "finished" for f in fids)
    finally:
        for rep in fr.replicas:
            if rep.alive:
                fr.monitor.beat(rep.name)  # fresh stamps before re-arming
        fr.monitor.deadline_s = saved_deadline
    fr.reset_stats()


def fleet_block(on_tpu: bool, seed: int = 0, n_replicas: int = 4) -> dict:
    """The ISSUE 16 acceptance campaign — four arms over the same seeded
    trace:

      single   1 replica, the scaling yardstick
      fleet4   n_replicas healthy replicas, threaded pumps (the serving
               topology); tok/s over `single` is the scaling ratio
      kill     same fleet, the most-loaded replica SIGKILLed (silently)
               mid-pass once ~25% of requests finished — zero lost
               requests, zero duplicate tokens, p99 TTFT within 2x of the
               healthy arm is the gate line
      drain    same fleet, drain-and-retire of the most-loaded replica
               mid-pass — zero shed, the retire must complete

    Records `cores`: on a box with fewer cores than replicas the threaded
    arms timeshare one silicon and the >=3x scaling floor is physically
    meaningless, so tools/gate.py switches to a CPU-overhead floor there
    (the multichip precedent)."""
    from paddle_tpu.serving import FleetRouter, ServingEngine

    cfg, prompt_lens, _ = ab_config(on_tpu, shared_prefix=False)
    if on_tpu:
        eng_kw = dict(page_size=16, pool_pages=1024, max_inflight=16)
        n_req, max_new, rate = 64, 16, 32.0
    else:
        eng_kw = dict(page_size=4, pool_pages=64, max_inflight=4)
        # max_new long enough that decodes span many pumps: the kill arm
        # needs a mid-stream victim (see _drive_fleet) for replay to engage
        n_req, max_new, rate = 24, 24, 16.0
    eng_kw.update(prefix_cache=True, draft_k=0, seed=seed)

    def factory():
        return ServingEngine(cfg, **eng_kw)

    wl = synth_workload(n_req, cfg.vocab_size, seed=seed,
                        prompt_lens=prompt_lens, max_new=max_new, rate=rate)
    # heartbeat tight enough that the kill arm's discovery lands inside the
    # measured pass, wide enough that a loaded-box scheduling stall on a
    # threaded pump is not read as death (warmup keeps compiles out)
    hb = 0.5

    def run_arm(n, pump, **drive_kw):
        with FleetRouter(factory, n_replicas=n, heartbeat_s=hb,
                         pump=pump) as fr:
            _fleet_warm(fr, wl)
            fids, wall, rid = _drive_fleet(fr, wl, **drive_kw)
            if drive_kw.get("drain_at_frac") is not None and rid is not None:
                # the drive settles when requests do; spin until the retire
                # itself is observed (it needs a few more polls)
                deadline = time.perf_counter() + 30.0
                while (fr.stats["retires"] == 0
                       and time.perf_counter() < deadline):
                    fr.poll() if pump == "threads" else fr.step()
                    time.sleep(0.001)
            out = _fleet_arm_metrics(fr, fids, wall)
            out["event_rid"] = rid
            return out

    pump = "threads"
    arms = {
        "single": run_arm(1, pump),
        "fleet4": run_arm(n_replicas, pump),
        # the kill arm pumps INLINE: on the threaded pump the router ledger
        # lags the engine by the outbox (under the GIL the whole decode can
        # finish before the ledger shows one token), so only the inline pump
        # can deterministically land the SIGKILL on a mid-stream victim —
        # which is the entire point of the arm. Discovery semantics are pump-
        # agnostic: the heartbeat deadline, not the pump, declares death.
        "kill": run_arm(n_replicas, "inline", kill_at_frac=0.25),
        "drain": run_arm(n_replicas, pump, drain_at_frac=0.25),
    }

    def _ratio(a, b):
        return round(a / max(b, 1e-9), 3)

    p99_h = arms["fleet4"]["ttft"]["p99_ms"]
    p99_k = arms["kill"]["ttft"]["p99_ms"]
    return {
        "arms": arms,
        "n_replicas": n_replicas,
        "cores": os.cpu_count(),
        "heartbeat_s": hb,
        "scaling_vs_single": _ratio(arms["fleet4"]["tok_s"],
                                    arms["single"]["tok_s"]),
        "kill_ttft_p99_ratio": (_ratio(p99_k, p99_h)
                                if p99_h and p99_k else None),
        "kill_lost": arms["kill"]["lost"],
        "kill_duplicate_tokens": arms["kill"]["duplicate_tokens"],
        "drain_shed": arms["drain"]["shed"],
        "drain_retired": arms["drain"]["retires"],
        "config": f"n{n_req} max_new{max_new} r{rate:g} seed{seed}",
    }


def disagg_block(on_tpu: bool, seed: int = 0) -> dict:
    """The ISSUE 19 acceptance campaign — three arms over the same seeded
    trace, ALL on the inline pump (disaggregated fleets only pump inline,
    so the co-located yardstick must too: one pump discipline, and TTFT
    deltas measure the topology, not threading):

      coloc    4 co-located mixed replicas, each on its own pool — the
               yardstick the split is judged against
      disagg   2 prefill + 2 decode replicas over ONE shared PagedKVPool
               (every request crosses a transactional KV handoff); the
               gate line is bounded p99 TTFT vs coloc and hard zeros on
               lost/duplicates/leaks
      kill     same split topology under a mid-handoff failure double:
               one "prepared" handoff dropped on the router floor (the
               lease reaper must reclaim + replay it) AND a mid-stream
               SIGKILL of the most-loaded replica — zero lost, zero
               duplicates, >= 1 reaped lease, no lease left PREPARED,
               a clean shared-pool audit

    The disagg arms size the SHARED pool at 4x the per-engine pool of the
    coloc arm: same aggregate KV capacity, so pool pressure is comparable
    and the TTFT delta isolates the handoff cost."""
    from paddle_tpu.resilience.faults import fault_scope
    from paddle_tpu.serving import FleetRouter, ServingEngine
    from paddle_tpu.serving.fleet import disagg_fleet_factory

    cfg, prompt_lens, _ = ab_config(on_tpu, shared_prefix=False)
    if on_tpu:
        eng_kw = dict(page_size=16, pool_pages=1024, max_inflight=16)
        n_req, max_new, rate = 64, 16, 32.0
    else:
        eng_kw = dict(page_size=4, pool_pages=64, max_inflight=4)
        n_req, max_new, rate = 24, 24, 16.0
    eng_kw.update(prefix_cache=True, draft_k=0, seed=seed)
    wl = synth_workload(n_req, cfg.vocab_size, seed=seed,
                        prompt_lens=prompt_lens, max_new=max_new, rate=rate)
    hb = 0.5
    roles = ["prefill", "prefill", "decode", "decode"]

    def run_arm(split: bool, plan: str | None = None,
                kill_at_frac: float | None = None, ttl=None):
        if split:
            fac = disagg_fleet_factory(
                cfg, **{**eng_kw, "pool_pages": 4 * eng_kw["pool_pages"]})
            router_kw = {"roles": list(roles), "lease_ttl_s": ttl}
        else:
            def fac():  # noqa: ANN202 — same engine recipe, private pools
                return ServingEngine(cfg, **eng_kw)
            router_kw = {}
        with FleetRouter(fac, n_replicas=4, heartbeat_s=hb,
                         pump="inline", **router_kw) as fr:
            _fleet_warm(fr, wl)
            if plan is not None:
                with fault_scope(plan):
                    fids, wall, rid = _drive_fleet(
                        fr, wl, kill_at_frac=kill_at_frac)
            else:
                fids, wall, rid = _drive_fleet(
                    fr, wl, kill_at_frac=kill_at_frac)
            out = _fleet_arm_metrics(fr, fids, wall)
            out["event_rid"] = rid
            if fr.handoff is not None:
                out["handoff"] = dict(fr.handoff.stats)
                out["prefill_dispatches"] = fr.stats["prefill_dispatches"]
                out["handoff_replays"] = fr.stats["handoff.replays"]
                out["handoff_dropped"] = fr.stats["handoff.dropped"]
                out["leases_left_prepared"] = fr.handoff.active()
                out["pool_audit_problems"] = list(
                    fr.handoff.pool.check_consistency(None))
            return out

    arms = {
        "coloc": run_arm(split=False),
        "disagg": run_arm(split=True),
        # the drop fires on the 2nd prepared event (the 1st is often the
        # very first request, whose replay timing is compile-shadowed)
        "kill": run_arm(split=True, plan="disagg_handoff_drop:2",
                        kill_at_frac=0.25, ttl=0.3),
    }

    def _ratio(a, b):
        return round(a / max(b, 1e-9), 3)

    p99_c = arms["coloc"]["ttft"]["p99_ms"]
    p99_d = arms["disagg"]["ttft"]["p99_ms"]
    kill = arms["kill"]
    return {
        "campaign": "disagg",
        "arms": arms,
        "roles": roles,
        "cores": os.cpu_count(),
        "heartbeat_s": hb,
        "disagg_ttft_p99_ratio": (_ratio(p99_d, p99_c)
                                  if p99_c and p99_d else None),
        "disagg_tok_s_ratio": _ratio(arms["disagg"]["tok_s"],
                                     arms["coloc"]["tok_s"]),
        "kill_lost": kill["lost"],
        "kill_duplicate_tokens": kill["duplicate_tokens"],
        "kill_reaped_leases": kill["handoff"]["reaped"],
        "kill_handoff_replays": kill["handoff_replays"],
        "leaked_pages": sum(a["kv_pages_leaked"] for a in arms.values()),
        "leases_left_prepared": sum(a.get("leases_left_prepared", 0)
                                    for a in arms.values()),
        "audit_problems": sum(len(a.get("pool_audit_problems", []))
                              for a in arms.values()),
        "config": f"n{n_req} max_new{max_new} r{rate:g} seed{seed}",
    }


def _control_geometry(on_tpu: bool):
    """(eng_base, n_req, base_rate, hand_mi) — the PR 13 overload-bench
    engine geometry, shared verbatim by the knob sweep and the control
    A/B so the sweep's rows describe exactly the machine the bench
    judges proposals on."""
    if on_tpu:
        return dict(page_size=16, pool_pages=2048), 64, 32.0, 16
    return dict(page_size=4, pool_pages=64), 32, 8.0, 4


def _control_hand_knobs(hand_mi: int):
    """The PR 13 bench configs as knob spellings: the no-floor unloaded
    reference and the shed-floored overload reference. These are the arms
    the learned tier must beat (or tie) — and the fallback every gated
    proposal resolves to."""
    un = {"mi": hand_mi, "dk": 0, "pc": 1, "sp": 0,
          "sq": 0, "so": 0, "da": 4, "pd": 0}
    ov = {"mi": hand_mi, "dk": 0, "pc": 1, "sp": 0,
          "sq": 8, "so": 95, "da": 2, "pd": 0}
    return un, ov


class _ArmPool:
    """One live engine per construction-only knob combo (pc, sp); the
    actuatable knobs move between arms through the engine's own staged
    config path (propose_config + idle adoption). Two birds: every arm
    after the first rides warm XLA caches (a cold CPU engine pays ~30 s
    of compiles for a sub-second measured pass), and the sweep itself
    exercises the actuator it is collecting data for."""

    def __init__(self, cfg, eng_base: dict, seed: int):
        self._cfg, self._base, self._seed = cfg, dict(eng_base), seed
        self._engines: dict = {}

    def engine_for(self, knobs: dict):
        from paddle_tpu.serving import ServingEngine
        from paddle_tpu.serving import control as sv_control

        key = (knobs["pc"], knobs["sp"])
        eng = self._engines.get(key)
        if eng is None:
            kw = dict(self._base)
            kw.update(sv_control.engine_kwargs(knobs))
            eng = self._engines[key] = ServingEngine(
                self._cfg, seed=self._seed, **kw)
        else:
            eng.propose_config(
                {f: knobs[f] for f in sv_control.ACTUATABLE}, source="sweep")
            eng.maybe_adopt_config()
            eng.prune_finished()
            # drop retained prefix pages from earlier arms/regimes: a
            # reused engine otherwise drags the last regime's shared
            # prefixes into this one's pool, and on the small CPU pool
            # that residue alone trips the occupancy shed floor — every
            # so>0 arm would measure a starved pool, not its knobs (the
            # warmup replay re-warms THIS workload's prefixes before the
            # measured pass, exactly like the bench's fresh engines)
            if eng.prefix_cache is not None:
                eng.prefix_cache.flush()
        got = sv_control.knob_key(sv_control.engine_knobs(eng))
        want = sv_control.knob_key(dict(knobs, pd=0))
        if got != want:
            raise RuntimeError(f"arm-pool actuation drifted: {got} != {want}")
        return eng


def _regime_sig(wl, rate: float, hand_block: dict) -> dict:
    """Regime signals for one sweep workload: intent (arrival rate,
    length percentiles, output budget) from the seeded trace, runtime
    signals (prefix hit, occupancy, queueing proxy, shed headroom) from
    the hand-reference pass — so every knob arm of the regime records
    under ONE store key, which is what lets the ridge rank arms."""
    from paddle_tpu.serving import control as sv_control

    shed_frac = ((hand_block["shed"] + hand_block["rejected"])
                 / max(hand_block["offered"], 1))
    hr = 1.0 if shed_frac == 0 else (0.5 if shed_frac < 0.3 else 0.0)
    p50_ttft_s = (hand_block["admitted_ttft"].get("p50_ms") or 0.0) / 1e3
    return sv_control.workload_signals(
        wl, rate,
        hit=hand_block.get("prefix_cache_hit_rate", 0.0),
        occ=hand_block.get("kv_pool_occupancy_mean", 0.0),
        q=int(round(rate * p50_ttft_s)),  # Little's law queue proxy
        hr=hr)


def sweep_knobs_block(on_tpu: bool, seed: int = 0, store: str | None = None,
                      n_arms: int = 6) -> dict:
    """The ISSUE 20 knob sweep: measure every sweep arm's goodput across
    a 12-regime grid (arrival-rate multiple x output budget x shared-
    prefix length) and append one store row per (regime, arm). The grid
    CONTAINS the PR 13 bench regimes (mult 1 and 10 at max_new 12,
    sys_len 6 pages), so the trained envelope covers the traffic the
    control A/B later judges proposals on — a prediction there is an
    interpolation, never an extrapolation the envelope gate must kill."""
    from paddle_tpu import flags as pt_flags
    from paddle_tpu.serving import control as sv_control

    cfg, _, user_lens = ab_config(on_tpu, shared_prefix=True)
    eng_base, n_req, base_rate, hand_mi = _control_geometry(on_tpu)
    ps = eng_base["page_size"]
    hand_un, hand_ov = _control_hand_knobs(hand_mi)
    # the shed-floored hand config leads (it is the sig reference pass);
    # the no-floor hand config always measures too
    arms = sv_control.sweep_arms(n_arms, seed=seed, include=hand_ov)
    if not any(sv_control.knob_key(a) == sv_control.knob_key(hand_un)
               for a in arms):
        arms.insert(1, hand_un)
    pool = _ArmPool(cfg, eng_base, seed)
    old_rec = str(pt_flags.get_flag("tuning_record"))
    pt_flags.set_flags({"tuning_record": "on"})
    regimes, rows = [], 0
    try:
        for mult in (1, 3, 10):
            for max_new in (6, 12):
                for sys_pages in (3, 6):
                    rate = base_rate * mult
                    wl = synth_shared_prefix_workload(
                        n_req, cfg.vocab_size, seed=seed, n_sys_prompts=8,
                        sys_len=sys_pages * ps, user_lens=user_lens,
                        max_new=max_new, rate=rate)
                    sig = None
                    by_arm = {}
                    for knobs in arms:
                        blk = run_overload_arm(pool.engine_for(knobs), wl)
                        if sig is None:  # first arm is the hand reference
                            sig = _regime_sig(wl, rate, blk)
                        gp = blk["goodput_tok_s"]
                        by_arm[sv_control.knob_key(knobs)] = round(gp, 2)
                        if gp > 0 and sv_control.record_row(
                                sig, knobs, gp, source="sweep", tool=True,
                                path=store,
                                extras={"sweep_seed": seed}):
                            rows += 1
                    reg = {"regime": sv_control.regime_key(sig),
                           "rate": rate, "max_new": max_new,
                           "sys_len": sys_pages * ps,
                           "goodput_by_arm": by_arm}
                    regimes.append(reg)
                    print(json.dumps(reg), flush=True)
    finally:
        pt_flags.set_flags({"tuning_record": old_rec})
    return {
        "campaign": "control_sweep",
        "store": os.path.abspath(store) if store
        else sv_control.store_path(),
        "rows_recorded": rows,
        "n_regimes": len(regimes),
        "arms": [sv_control.knob_key(a) for a in arms],
        "regimes": regimes,
        "config": f"shared-prefix n{n_req} r{base_rate:g}x(1,3,10) seed{seed}",
    }


def _goodput_pass(engine, workload) -> float:
    """One already-warm measured pass: goodput tokens per wall second."""
    engine.reset_stats()
    engine.prune_finished()
    rids, _rej, wall = _drive_overload(engine, workload, 200_000)
    done = [engine.requests[r] for r in rids
            if engine.requests[r].state == "finished"]
    tok = sum(r.n_generated for r in done)
    return tok / wall if wall > 0 else 0.0


def control_block(on_tpu: bool, seed: int = 0,
                  store: str | None = None) -> dict:
    """The ISSUE 20 acceptance campaign. Trains the serving.control group
    from the sweep store, then replays the PR 13 overload bench as a
    hand-vs-learned A/B per arm:

      unloaded          r8, no floors — the learned proposal must NOT
                        regress this arm (tie band in the gate)
      overload          10x with shed floors — learned must meet or beat
      overload_faulted  10x under the bounded fault plan — same bar

    plus the shadow-overhead A/B (PR 12 methodology: same warm engine,
    same trace, mode off vs shadow interleaved, best-of-N per mode) on
    the compute-bound overload trace — the arrival-limited unloaded
    trace would hide any overhead in its idle sleeps.

    Redirect to CONTROL_r*.json for gate.py --control."""
    import tempfile as _tempfile

    from paddle_tpu import flags as pt_flags
    from paddle_tpu import tuning as _tuning
    from paddle_tpu.serving import ServingEngine
    from paddle_tpu.serving import control as sv_control
    from paddle_tpu.tuning import learned

    store_abs = os.path.abspath(store) if store else sv_control.store_path()
    recs = list(learned.iter_records(store_abs))
    ctrl_recs = [r for r in recs if r.get("op") == sv_control.CONTROL_OP]
    if not ctrl_recs:
        raise SystemExit(f"[control] no serving.control rows in "
                         f"{store_abs!r} — run --sweep-knobs first")
    model = learned.train_model(recs, seed=seed)
    dev = _tuning.device_kind()
    group = model.get("groups", {}).get(f"{sv_control.CONTROL_OP}|{dev}")
    if group is None:
        raise SystemExit(f"[control] training produced no serving.control|"
                         f"{dev} group (need >= 6 regime keys, >= 3 "
                         f"samples per arm)")

    cfg, _, user_lens = ab_config(on_tpu, shared_prefix=True)
    eng_base, n_req, base_rate, hand_mi = _control_geometry(on_tpu)
    ps = eng_base["page_size"]
    hand_un, hand_ov = _control_hand_knobs(hand_mi)
    max_new = 12

    def wl(rate):
        return synth_shared_prefix_workload(
            n_req, cfg.vocab_size, seed=seed, n_sys_prompts=8,
            sys_len=6 * ps, user_lens=user_lens, max_new=max_new, rate=rate)

    def run_cfg(knobs, workload, extras, plan):
        kw = dict(eng_base)
        kw.update(sv_control.engine_kwargs(knobs))
        kw.update(extras)
        return run_overload_arm(ServingEngine(cfg, seed=seed, **kw),
                                workload, fault_plan=plan)

    bench = {
        "unloaded": dict(rate=base_rate, hand=hand_un, extras={}, plan=None),
        "overload": dict(rate=10 * base_rate, hand=hand_ov, extras={},
                         plan=None),
        "overload_faulted": dict(
            rate=10 * base_rate, hand=hand_ov,
            extras=dict(audit_every=1, step_retries=2),
            plan=OVERLOAD_FAULT_PLAN),
    }
    saved = {k: pt_flags.get_flag(k) for k in
             ("serve_control_mode", "serve_control_model",
              "serve_control_epoch_s")}
    tmp_model = os.path.join(_tempfile.mkdtemp(prefix="serve_control_"),
                             "control_model.json")
    learned.save_model(model, tmp_model)
    arms_out = {}
    try:
        pt_flags.set_flags({"serve_control_mode": "shadow"})
        for name, a in bench.items():
            w = wl(a["rate"])
            hand_blk = run_cfg(a["hand"], w, a["extras"], a["plan"])
            sig = _regime_sig(w, a["rate"], hand_blk)
            proposal, info = sv_control.propose(sig, model=model)
            if (sv_control.knob_key(proposal)
                    == sv_control.knob_key(a["hand"])):
                # identical config: re-measuring would only add noise
                learned_blk = hand_blk
            else:
                learned_blk = run_cfg(proposal, w, a["extras"], a["plan"])
            arm = {
                "hand": hand_blk,
                "learned": learned_blk,
                "hand_knobs": sv_control.knob_key(a["hand"]),
                "proposal": sv_control.knob_key(proposal),
                "tier": info.get("tier"),
                "sig": {k: round(float(v), 4) for k, v in sig.items()},
                "regime": sv_control.regime_key(sig),
                "ratio": round(learned_blk["goodput_tok_s"]
                               / max(hand_blk["goodput_tok_s"], 1e-9), 3),
            }
            for k in ("reason", "rank_acc", "predicted_s_per_tok"):
                if k in info:
                    arm[k] = info[k]
            arms_out[name] = arm
            print(json.dumps({name: {"ratio": arm["ratio"],
                                     "tier": arm["tier"],
                                     "proposal": arm["proposal"]}}),
                  flush=True)

        # shadow-overhead A/B on the compute-bound overload trace, with a
        # real model on the flag path and epochs short enough to fire
        # inside a pass — shadow pays observe+propose, never an apply.
        # 0.5 s epochs are a 10x stress over the 5 s default: a ceiling
        # cleared here holds with an order of magnitude to spare
        pt_flags.set_flags({"serve_control_model": tmp_model,
                            "serve_control_epoch_s": 0.5})
        sv_control.invalidate_model_cache()
        kw = dict(eng_base)
        kw.update(sv_control.engine_kwargs(hand_ov))
        eng = ServingEngine(cfg, seed=seed, **kw)
        w10 = wl(10 * base_rate)
        run_overload_arm(eng, w10)  # warm compiles + caches
        best = {"off": 0.0, "shadow": 0.0}
        for _ in range(7):
            for m in ("off", "shadow"):
                pt_flags.set_flags({"serve_control_mode": m})
                best[m] = max(best[m], _goodput_pass(eng, w10))
        overhead = max(0.0, (1.0 - best["shadow"]
                             / max(best["off"], 1e-9)) * 100.0)
        shadow = {"shadow_overhead_pct": round(overhead, 2),
                  "goodput_off": round(best["off"], 2),
                  "goodput_shadow": round(best["shadow"], 2)}
    finally:
        pt_flags.set_flags(saved)
        sv_control.invalidate_model_cache()

    blocks = [a[s] for a in arms_out.values() for s in ("hand", "learned")]
    return {
        "campaign": "control",
        "seed": seed,
        "store": store_abs,
        "store_rows": len(ctrl_recs),
        "model": {"device": dev,
                  "holdout": group["holdout"],
                  "n_train_keys": group["n_train_keys"],
                  "n_holdout_keys": len(group["holdout_keys"]),
                  "arms": sorted(group["arms"])},
        "arms": arms_out,
        "learned_vs_hand": {n: a["ratio"] for n, a in arms_out.items()},
        "shadow": shadow,
        "leaked_pages": sum(b["kv_pages_leaked"] for b in blocks),
        "refcount_leaks": sum(b["refcount_leaks"] for b in blocks),
        "config": (f"shared-prefix sys{6 * ps} r{base_rate:g}->"
                   f"r{10 * base_rate:g} n{n_req} mn{max_new} seed{seed}"),
    }


def ab_config(on_tpu: bool, shared_prefix: bool):
    """(cfg, prompt_lens, user_lens) for the sweep. The shared-prefix CPU
    config is deliberately LESS tiny than decoder_tiny: at decoder_tiny
    scale every program costs ~0.5 ms of dispatch regardless of tokens, so
    prefill savings are invisible — this config makes the 128-token-bucket
    classic prefill ~2.4x the cost of the 8-token suffix window, which is
    the (much starker) shape of the TPU regime."""
    from paddle_tpu.serving import DecoderConfig, decoder_tiny

    if on_tpu:
        cfg = DecoderConfig(vocab_size=30522, hidden_size=512, num_layers=6,
                            num_heads=8, ffn_size=2048, max_position=1024)
        return cfg, (16, 128), (8, 64)
    if shared_prefix:
        cfg = DecoderConfig(vocab_size=997, hidden_size=64, num_layers=3,
                            num_heads=4, ffn_size=256, max_position=256)
        return cfg, (4, 24), (2, 8)
    return decoder_tiny(), (4, 24), (2, 8)


def _mk_engine(cfg, args, prefix_cache=None, draft_k=None):
    from paddle_tpu.serving import ServingEngine

    return ServingEngine(
        cfg, page_size=args.page_size, pool_pages=args.pool_pages,
        max_inflight=args.max_inflight, policy=args.policy, seed=args.seed,
        prefix_cache=(args.prefix_cache if prefix_cache is None
                      else prefix_cache),
        draft_k=(args.draft_k if draft_k is None else draft_k),
        tp=args.tp)


def main():
    import jax

    on_tpu = jax.devices()[0].platform == "tpu"
    ap = argparse.ArgumentParser()
    ap.add_argument("--rates", default=None,
                    help="comma list of arrival rates (req/s); default "
                         "4,16,64 TPU / 8,32 CPU, 10x that with "
                         "--shared-prefix")
    ap.add_argument("--requests", type=int, default=64 if on_tpu else 16)
    ap.add_argument("--max-new", type=int, default=32 if on_tpu else 6)
    ap.add_argument("--page-size", type=int, default=None)
    ap.add_argument("--pool-pages", type=int, default=None)
    ap.add_argument("--max-inflight", type=int, default=None)
    ap.add_argument("--policy", default=None)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--shared-prefix", action="store_true",
                    help="zipf-distributed system-prompt reuse mix at 10x "
                         "rates (the ISSUE 11 workload)")
    ap.add_argument("--sys-prompts", type=int, default=8)
    ap.add_argument("--sys-len", type=int, default=None,
                    help="shared system-prompt length (default: 8 pages "
                         "TPU / 6 pages CPU)")
    ap.add_argument("--zipf", type=float, default=1.2)
    ap.add_argument("--prefix-cache", type=int, default=None,
                    help="1/0 force the prefix cache (default: flag)")
    ap.add_argument("--draft-k", type=int, default=None,
                    help="speculative draft length (default: flag)")
    ap.add_argument("--tp", type=int, default=None,
                    help="tensor-parallel degree (default: flag)")
    ap.add_argument("--ab", action="store_true",
                    help="also run the PR 7 baseline arm (prefix cache "
                         "off, draft 0) on the same trace and print the "
                         "comparison")
    ap.add_argument("--overload", action="store_true",
                    help="run the ISSUE 14 three-arm overload block "
                         "(unloaded / 10x with shedding / 10x under "
                         "faults) and print its JSON")
    ap.add_argument("--fleet", action="store_true",
                    help="run the ISSUE 16 four-arm fleet block (single / "
                         "healthy fleet / mid-pass SIGKILL / drain-and-"
                         "retire) and print its JSON")
    ap.add_argument("--replicas", type=int, default=4,
                    help="fleet size for --fleet (default 4)")
    ap.add_argument("--disagg", action="store_true",
                    help="run the ISSUE 19 three-arm disaggregation block "
                         "(co-located / prefill-decode split / mid-handoff "
                         "kill) and print its JSON (redirect to "
                         "DISAGG_r*.json for gate.py --disagg)")
    ap.add_argument("--sweep-knobs", action="store_true",
                    help="run the ISSUE 20 knob sweep (12 traffic regimes "
                         "x the control arm lattice) and append one "
                         "measurement-store row per (regime, arm)")
    ap.add_argument("--control", action="store_true",
                    help="run the ISSUE 20 control A/B: train the "
                         "serving.control group from the sweep store, "
                         "replay the overload bench hand-vs-learned, "
                         "measure shadow overhead (redirect to "
                         "CONTROL_r*.json for gate.py --control)")
    ap.add_argument("--control-store", default=None,
                    help="measurement store for --sweep-knobs/--control "
                         "(default: the tuning store / "
                         "FLAGS_serve_control_store)")
    ap.add_argument("--control-arms", type=int, default=6,
                    help="sweep arm count for --sweep-knobs (default 6; "
                         "the two hand references always measure)")
    args = ap.parse_args()
    if args.prefix_cache is not None:
        args.prefix_cache = bool(args.prefix_cache)
    if args.overload:
        print(json.dumps(overload_block(on_tpu, seed=args.seed)),
              flush=True)
        return
    if args.fleet:
        print(json.dumps(fleet_block(on_tpu, seed=args.seed,
                                     n_replicas=args.replicas)),
              flush=True)
        return
    if args.disagg:
        print(json.dumps(disagg_block(on_tpu, seed=args.seed)), flush=True)
        return
    if args.sweep_knobs:
        print(json.dumps(sweep_knobs_block(on_tpu, seed=args.seed,
                                           store=args.control_store,
                                           n_arms=args.control_arms)),
              flush=True)
        return
    if args.control:
        print(json.dumps(control_block(on_tpu, seed=args.seed,
                                       store=args.control_store)),
              flush=True)
        return

    cfg, prompt_lens, user_lens = ab_config(on_tpu, args.shared_prefix)

    base_rates = "4,16,64" if on_tpu else "8,32"
    if args.rates is None:
        # ISSUE 11: the shared-prefix sweep runs at 10x the r8 rates
        args.rates = (",".join(str(10 * float(r))
                               for r in base_rates.split(","))
                      if args.shared_prefix else base_rates)
    import paddle_tpu as pt

    ps = args.page_size or int(pt.flags.get_flag("serving_page_size"))
    # whole pages (page-granular sharing) and comfortably under max_position
    sys_len = (args.sys_len if args.sys_len is not None
               else (8 * ps if on_tpu else 6 * ps))

    summary = {}
    for rate in [float(r) for r in args.rates.split(",") if r]:
        if args.shared_prefix:
            wl = synth_shared_prefix_workload(
                args.requests, cfg.vocab_size, args.seed,
                n_sys_prompts=args.sys_prompts, sys_len=sys_len,
                user_lens=user_lens, max_new=args.max_new, rate=rate,
                zipf_a=args.zipf)
        else:
            wl = synth_workload(args.requests, cfg.vocab_size, args.seed,
                                prompt_lens=prompt_lens,
                                max_new=args.max_new, rate=rate)
        # steady-state measurement under --ab/--shared-prefix: both arms
        # pre-warm compiles + cache on one discarded pass of the trace
        warm = args.ab or args.shared_prefix
        out = run_open_loop(_mk_engine(cfg, args), wl, warmup=warm)
        out["rate_req_s"] = rate
        out["warmup"] = warm

        from paddle_tpu import tuning as _tuning
        from paddle_tpu.tuning.learned import store as _learned_store

        def _rec(arm_name, block):
            # serving passes measure one wall window, not iterated steps;
            # the store row carries seconds-per-served-token so serving
            # data reads on the same axis as the step timings
            tps = block.get("served_tokens_per_sec") or 0
            if tps > 0 and _learned_store.recording_enabled(tool=True):
                _learned_store.record(
                    "ab.serving",
                    f"workload=serve rate={rate} reqs={args.requests}",
                    "-", _tuning.device_kind(), arm_name,
                    windows_s=[1.0 / tps], source="ab",
                    extras={"wall_s": block.get("wall_s")})

        _rec("tuned", out)
        if args.ab:
            base = run_open_loop(
                _mk_engine(cfg, args, prefix_cache=False, draft_k=0), wl,
                warmup=warm)
            _rec("baseline", base)
            out["baseline"] = {
                "served_tokens_per_sec": base["served_tokens_per_sec"],
                "prefill_tokens_computed": base["prefill_tokens_computed"],
                "request_latency": base["request_latency"],
                "kv_pages_leaked": base["kv_pages_leaked"],
                "refcount_leaks": base["refcount_leaks"],
            }
            out["vs_baseline_tok_s"] = round(
                out["served_tokens_per_sec"]
                / max(base["served_tokens_per_sec"], 1e-9), 3)
            out["prefill_tokens_saved"] = (
                base["prefill_tokens_computed"]
                - out["prefill_tokens_computed"])
        print(json.dumps(out), flush=True)
        summary[str(rate)] = out["served_tokens_per_sec"]
    print(json.dumps({"sweep": "serve_ab", "served_tok_s_by_rate": summary}),
          flush=True)


if __name__ == "__main__":
    main()
