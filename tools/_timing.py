"""Shared measurement protocol for the tools/ A/B harnesses and tools/tune.py.

One home for the timing loop that was copy-pasted across _rn_igemm.py /
_pipeline_ab.py / _bert_flash_ab.py, and the statistics the sweeper's
keep-or-retire verdicts are made of:

  * `timed_windows` — bench.py's exact window protocol (async-dispatched
    iters ended by a host drain read) so tool numbers stay comparable to
    bench artifacts;
  * `measure` — warmup + windows + summary stats (median-of-windows is the
    sweep estimator: robust to one-sided interference bursts where a mean
    is not, and less optimistic than min for verdicts that persist in a DB);
  * `interference_band` — relative window spread; a sweep whose band
    swamps the margin must not hand out a verdict;
  * `ab_verdict` — keep / retire / tie for a candidate vs baseline median
    under a band (gate.py's 5% interference band is the floor).
"""
from __future__ import annotations

import time

import numpy as np

__all__ = ["timed_windows", "time_call", "median", "interference_band",
           "measure", "ab_verdict", "DEFAULT_BAND", "percentile",
           "latency_stats"]

# gate.py's interference band: margins inside it are machine noise, not a
# measured win (PERF.md r4 — single bursts on the shared box outlast a
# timed pass)
DEFAULT_BAND = 0.05


def timed_windows(run_once, drain, iters: int, passes: int) -> list[float]:
    """bench.py's window protocol: `passes` windows of `iters`
    async-dispatched steps each, ended by a host drain read; returns the
    per-step seconds of every window so callers can keep the spread."""
    windows = []
    for _ in range(passes):
        t0 = time.perf_counter()
        for _ in range(iters):
            run_once()
        np.asarray(drain())
        windows.append((time.perf_counter() - t0) / iters)
    return windows


def time_call(fn) -> tuple[float, object]:
    """Wall-time one call (epoch-granularity arms, e.g. _pipeline_ab's
    whole-pass loops). Returns (seconds, fn's return value)."""
    t0 = time.perf_counter()
    out = fn()
    return time.perf_counter() - t0, out


def median(xs) -> float:
    return float(np.median(np.asarray(list(xs), dtype=np.float64)))


def interference_band(windows) -> float:
    """Relative spread (max-min)/median of the windows: 0.0 = perfectly
    quiet box. Compare against the verdict band — a sweep measured in a
    spread wider than its decision margin is reporting noise."""
    ws = np.asarray(list(windows), dtype=np.float64)
    if ws.size < 2:
        return 0.0
    med = float(np.median(ws))
    return float((ws.max() - ws.min()) / med) if med > 0 else 0.0


def measure(run_once, drain, iters: int, passes: int,
            warmup: int = 1) -> dict:
    """Warmup (compile + cache settle, un-timed) then `timed_windows`,
    summarized: median_s is the verdict estimator, min_s the steady-state
    throughput estimate (the bench.py convention), band the spread."""
    for _ in range(max(0, warmup)):
        run_once()
    np.asarray(drain())
    windows = timed_windows(run_once, drain, iters, passes)
    return {
        "median_s": median(windows),
        "min_s": float(min(windows)),
        "windows_s": [round(w, 6) for w in windows],
        "band": round(interference_band(windows), 4),
    }


def percentile(xs, q: float) -> float:
    """Linear-interpolated percentile (q in [0, 100])."""
    return float(np.percentile(np.asarray(list(xs), dtype=np.float64), q))


def latency_stats(seconds) -> dict:
    """Per-request latency summary for the serving load harnesses
    (tools/_serve_ab.py, the bench.py `serving` block): p50/p99 are THE
    serving SLO spellings, mean/max ride along for forensics. All ms."""
    xs = [float(s) for s in seconds]
    if not xs:
        return {"n": 0, "p50_ms": None, "p99_ms": None, "mean_ms": None,
                "max_ms": None}
    return {
        "n": len(xs),
        "p50_ms": round(1e3 * percentile(xs, 50), 3),
        "p99_ms": round(1e3 * percentile(xs, 99), 3),
        "mean_ms": round(1e3 * float(np.mean(xs)), 3),
        "max_ms": round(1e3 * max(xs), 3),
    }


def ab_verdict(base_s: float, cand_s: float,
               band: float = DEFAULT_BAND) -> str:
    """keep  — candidate beats baseline by more than the band;
    retire — candidate loses by more than the band;
    tie    — inside the band: no measured verdict, the caller keeps its
             analytic prior (a tie must never overwrite a model that has
             reasons with a coin flip that does not)."""
    if cand_s < (1.0 - band) * base_s:
        return "keep"
    if cand_s > (1.0 + band) * base_s:
        return "retire"
    return "tie"
