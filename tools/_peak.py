"""Achievable matmul rate with bench-style async dispatch + asarray drain."""
import time
import jax, jax.numpy as jnp, numpy as np

for dt_name, dtype in [("bf16", jnp.bfloat16), ("f32", jnp.float32)]:
    N = 8192
    a = jnp.full((N, N), 0.5, dtype)
    b = (jnp.eye(N, dtype=jnp.float32) * 1.0).astype(dtype)
    @jax.jit
    def step(s, b):
        for _ in range(5):
            s = s @ b
        return s
    s = step(a, b)
    np.asarray(s[0, 0])  # warm compile + drain
    t0 = time.perf_counter()
    s2 = s
    for _ in range(20):
        s2 = step(s2, b)
    np.asarray(s2[0, 0])
    dt = (time.perf_counter() - t0) / (20 * 5)
    print(f"{dt_name} {N}^3 matmul: {dt*1e3:.2f} ms, {2*N**3/dt/1e12:.1f} TF/s", flush=True)
