"""Pre-snapshot gate: the round may not end on a red suite (VERDICT r3 #3).

Runs the full pytest suite plus the single-chip compile check and exits
non-zero on ANY failure, printing the failing node ids. Run it before every
end-of-round snapshot commit:

    python tools/gate.py          # full gate (suite + graft entry)
    python tools/gate.py --fast   # suite only
"""
from __future__ import annotations

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_suite() -> int:
    print("[gate] running test suite ...", flush=True)
    r = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/", "-q", "--tb=line"],
        cwd=REPO)
    if r.returncode != 0:
        print("[gate] FAIL: test suite is red — do not snapshot", flush=True)
    return r.returncode


def run_entry() -> int:
    print("[gate] compile-checking __graft_entry__.entry() ...", flush=True)
    code = ("import __graft_entry__ as g; fn, args = g.entry(); "
            "import jax; jax.eval_shape(fn, *args); print('entry ok')")
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO)
    if r.returncode != 0:
        print("[gate] FAIL: graft entry does not compile", flush=True)
    return r.returncode


def main() -> int:
    rc = run_suite()
    if "--fast" not in sys.argv:
        rc = rc or run_entry()
    if rc == 0:
        print("[gate] OK — green suite, safe to snapshot")
    return rc


if __name__ == "__main__":
    sys.exit(main())
