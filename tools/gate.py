"""Pre-snapshot gate: the round may not end on a red suite (VERDICT r3 #3).

Runs the full pytest suite plus the single-chip compile check and exits
non-zero on ANY failure, printing the failing node ids. Also inspects the
newest BENCH_r*.json artifact: a DeepFM end-to-end/device-path ratio below
0.9 means the async feed/dispatch pipeline regressed (the end-to-end path is
leaving device throughput on the table) and fails the gate. Run it before
every end-of-round snapshot commit:

    python tools/gate.py                   # full gate (suite + entry + bench)
    python tools/gate.py --fast            # suite only
    python tools/gate.py --bench FILE.json # check one bench artifact only
    python tools/gate.py --multichip [F]   # multichip campaign artifact only
                                           # (scaling-efficiency floor, loss
                                           # parity drift, overlap A/B)
    python tools/gate.py --chaos           # chaos smoke only (`-m chaos`:
                                           # fault-injection + SIGKILL-
                                           # trainer liveness subset)
    python tools/gate.py --kernels         # Pallas kernel-registry lint
                                           # only (reference + equivalence
                                           # test + tuner key per kernel)
    python tools/gate.py --obs [F.json]    # telemetry block only (registry
                                           # overhead ceiling, metric-name
                                           # schema drift, missing block)
    python tools/gate.py --costmodel       # learned cost model only: the
                                           # committed model must beat the
                                           # analytic prior on its holdout
                                           # keys, and the newest bench's
                                           # learned fallback rate must stay
                                           # under the ceiling
    python tools/gate.py --fleet [F.json]  # serving-fleet campaign artifact
                                           # only (SIGKILL arm hard zeros,
                                           # scaling floor, drain-and-retire,
                                           # bounded kill-arm TTFT)
    python tools/gate.py --disagg [F.json] # disaggregated-serving campaign
                                           # artifact only (handoff hard
                                           # zeros, bounded split-arm TTFT
                                           # vs co-located, >= 1 reaped
                                           # lease + replay in the kill arm)
"""
from __future__ import annotations

import glob
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# below this, train_from_dataset is losing >10% of the measured device-path
# throughput to the host pipeline — the regression the prefetch/async-window
# subsystem exists to prevent (ISSUE 2 acceptance line)
DEEPFM_RATIO_FLOOR = 0.9

# the in-graph health sentinel (FLAGS_guard_numerics) must stay ~free: above
# this, the guard itself is the perf bug (ISSUE 4 acceptance line)
GUARD_OVERHEAD_CEIL_PCT = 2.0

# ResNet-50 is the round-6 campaign metric (ISSUE 5): flag any artifact whose
# resnet50 vs_target falls more than the interference band below the previous
# round's — a conv-lowering/BN regression, not box noise (single bursts move
# one window, not the best-of-3 protocol, PERF.md r4/r5)
RESNET_VS_TARGET_DROP = 0.95

# a consult-mode bench whose workload resolved mostly off the swept DB is
# running untuned — the DB is stale for these shapes (re-sweep with
# tools/tune.py) or keyed for another device (ISSUE 6 acceptance line).
# Since the learned tier (ISSUE 15) a model prediction counts as tuned too:
# the floor applies to tuned_rate ((db + learned) / decisions) when the
# artifact carries it, hit_rate on older snapshots.
TUNER_HIT_RATE_FLOOR = 0.5

# learned cost model (ISSUE 15): the committed artifact must keep ranking
# arms on its recorded holdout keys well enough to be worth a policy tier —
# below this floor (or below the analytic prior it is supposed to beat),
# the model is stale for the committed dataset; retrain with
# tools/costmodel.py train. The floor sits under the committed model's
# measured 1.0 so box-to-box eval noise does not flap the gate.
COSTMODEL_RANK_ACC_FLOOR = 0.75
COSTMODEL_DATA = "COSTMODEL_DATA_cpu.jsonl"
COSTMODEL_MODEL = "COSTMODEL_cpu.json"

# a consult/explore bench whose learned tier mostly fell through its
# confidence gate is carrying a model that no longer covers the workload's
# shapes (feature envelope drift, accuracy collapse) — above this fallback
# rate the tier is dead weight; retrain on a fresher measurement store.
LEARNED_FALLBACK_CEIL = 0.9

# serving runtime (ISSUE 7): flag an artifact whose open-loop served
# tokens/s falls more than this factor below the previous round's — the
# open-loop workload is seeded/identical every round, so a drop this size
# is a scheduler/kernel regression, not arrival noise. Leaked KV pages are
# a hard fail at any count: the pool never reclaims them.
SERVING_TOK_S_DROP = 0.8

# multi-tenant serving (ISSUE 11): when the shared-prefix mix runs, the
# prefix cache must actually be absorbing prefill — a hit rate below this
# floor on the zipf system-prompt workload means the page-granular index
# is broken (mis-keyed blocks, over-eager eviction), since the workload is
# built to reuse 8 templates. Refcount leaks (pages still off the free
# list after drain + cache flush) are a hard fail at any count in ANY arm.
PREFIX_HIT_RATE_FLOOR = 0.5

# serving resilience (ISSUE 14): under the 10x overload arm the engine must
# KEEP its goodput (finished-request tokens/s) by shedding — below this
# fraction of the unloaded arm's goodput, admission control is thrashing
# instead of protecting. Same floor for the faulted arm vs the overload
# arm: supervised recovery (retries, pool rebuild, replay) must cost
# bounded work, not eat the engine. Leaks hard-fail at any count in ANY
# arm — shed/expire/recovery are exactly the paths that lose pages.
OVERLOAD_GOODPUT_FLOOR = 0.7
# admitted requests' p99 TTFT under overload may not blow past this
# multiple of the unloaded arm's: shedding exists precisely so the work
# that IS admitted still sees bounded latency (unbounded queueing is the
# collapse mode the floors are armed against)
OVERLOAD_TTFT_CEIL_RATIO = 50.0

# tiered embedding engine (ISSUE 10): parameter parity vs the dense-lookup
# oracle is a hard correctness invariant — the tiered path is a data-movement
# refactor, any drift beyond float associativity means a lost update
# (write-back / install / scatter bug), never noise.
EMB_PARITY_ATOL = 1e-4
# hit-rate floor for the seeded zipf-1.5 workload: the hot-ID cache exists to
# keep the skewed head resident, and the workload replays identically every
# round, so a drop below this is an admission/eviction regression. Warns on
# the first artifact carrying the block, gates thereafter (the ISSUE 10
# phase-in rule).
EMB_HIT_RATE_FLOOR = 0.5

# multichip scaling campaign (ISSUE 8, `gate.py --multichip`). Parity first:
# every parallel arm must land on the single-device parameter trajectory —
# drift above this is a wrong collective, not noise (measured drifts sit at
# ~3e-4, pure cross-regime float reordering).
MC_PARITY_DRIFT = 5e-3
# scaling floors. On a host-platform virtual mesh every "device" shares one
# silicon, so ideal speedup_vs_single is ~1.0 and the number measures pure
# partitioning/collective overhead; the dp shard_map arm measures ~0.13 on
# the shared box, so 0.05 trips only on a real scheduling regression. On
# real chips per-device efficiency is the honest floor.
MC_CPU_SPEEDUP_FLOOR = 0.05
MC_EFFICIENCY_FLOOR = 0.5

# unified telemetry layer (ISSUE 13): the registry rides every hot loop
# (async dispatch drain, serving scheduler), so its measured cost over the
# legacy accumulators must stay ~free — same ceiling as the health sentinel
OBS_OVERHEAD_CEIL_PCT = 2.0

# serving fleet (ISSUE 16, `gate.py --fleet` over FLEET_r*.json). The hard
# zeros are unconditional: a SIGKILL mid-decode may lose NO requests and
# deliver NO duplicate tokens (the router ledger is exactly-once), the
# drain arm may shed nothing, and no surviving engine may leak a page.
# Scaling: 1 -> N replicas must deliver >= FLEET_SCALING_FLOOR x tok/s —
# but only where the box has at least one core per replica; on a smaller
# box the threaded replicas timeshare one silicon and the honest floor is
# "the fleet machinery costs bounded overhead" (the multichip CPU-mesh
# precedent), FLEET_CPU_OVERHEAD_FLOOR of the single arm.
FLEET_SCALING_FLOOR = 3.0
FLEET_CPU_OVERHEAD_FLOOR = 0.7
# the kill arm's p99 TTFT may not blow past this multiple of the healthy
# fleet arm's: discovery + replay must cost a heartbeat deadline, not a
# queueing collapse (ISSUE 16 acceptance line). Death discovery is bounded
# below by the configured heartbeat deadline — a fixed constant, not a
# performance property — so the ceiling is applied AFTER granting the kill
# arm an explicit detection budget of FLEET_DETECT_BUDGET_BEATS heartbeat
# intervals (deadline + check cadence + replay dispatch + requeue behind
# the survivor's admission window). On hardware where
# step time dominates the heartbeat the budget is negligible and the pure
# ratio governs; on a CPU box with ~10ms TTFTs it keeps the check honest
# instead of impossible.
FLEET_TTFT_CEIL_RATIO = 2.0
FLEET_DETECT_BUDGET_BEATS = 4.0

# disaggregated serving (ISSUE 19, `gate.py --disagg` over DISAGG_r*.json).
# Hard zeros as for the fleet: no lost requests, no duplicate tokens, no
# leaked pages, no lease left PREPARED, a clean shared-pool audit — and the
# kill arm must have exercised the machinery (>= 1 reaped lease, >= 1
# handoff replay). The split arm's p99 TTFT is bounded against co-located,
# but a bare ratio would be dishonest: the split halves the DECODE capacity
# by construction, so under open-loop load the first token queues for a
# decode slot while the co-located yardstick (all 4 replicas decoding)
# stays nearly unloaded. The ceiling therefore grants a queueing budget
# proportional to the arm's own measured wall — the scale of one
# generation wave through the halved decode stage — on top of the pure
# ratio. A genuine pathology (handoffs stalling to the lease TTL, commits
# lost and re-reaped) blows past wall-scale TTFT and still fails.
DISAGG_TTFT_CEIL_RATIO = 3.0
DISAGG_QUEUE_BUDGET_WALL_FRAC = 0.5

# learned serving control (ISSUE 20, `gate.py --control` over
# CONTROL_r*.json from tools/_serve_ab.py --control). The learned proposal
# must actually ENGAGE (tier "learned" on every bench arm — a model that
# cannot clear its own confidence gate on its own training regimes proves
# nothing), must meet-or-beat the hand config on the overloaded arms, and
# may not regress the unloaded arm beyond the near-tie band (the same 5%
# the A/B verdicts use). Shadow mode rides the serving hot path, so its
# measured cost shares the telemetry layer's ~free ceiling. The control
# group's holdout rank accuracy floor mirrors the kernel tier's: below it
# the confidence gate would (rightly) refuse every proposal. When the
# committed sweep dataset is present, the gate also retrains from it and
# requires the artifact's proposals to reproduce exactly — the training
# path is seeded-deterministic, so a mismatch means the artifact and
# dataset drifted apart.
CONTROL_WIN_FLOOR = 1.0
CONTROL_TIE_BAND = 0.05
CONTROL_RANK_ACC_FLOOR = 0.6
CONTROL_DATA = "CONTROL_DATA_cpu.jsonl"


def run_suite() -> int:
    print("[gate] running test suite ...", flush=True)
    r = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/", "-q", "--tb=line"],
        cwd=REPO)
    if r.returncode != 0:
        print("[gate] FAIL: test suite is red — do not snapshot", flush=True)
    return r.returncode


def run_chaos() -> int:
    """The fast chaos subset: every `chaos`-marked test (seeded fault-plan
    survival + the kill-trainer-mid-round eviction/rejoin scenario)."""
    print("[gate] running chaos smoke (-m chaos) ...", flush=True)
    r = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/", "-q", "-m", "chaos",
         "--tb=line"],
        cwd=REPO)
    if r.returncode != 0:
        print("[gate] FAIL: chaos smoke is red — the resilience/liveness "
              "runtime regressed", flush=True)
    return r.returncode


def check_kernel_registry() -> int:
    """Pallas kernel-workbench lint (ISSUE 9): every registered kernel must
    carry (1) a callable XLA reference, (2) a shape gate, (3) a tuning-DB
    decision op with a real key speller, and (4) an equivalence test that
    actually exists in tests/ — an unmeasured or unreferenced kernel cannot
    land silently (the keep-or-retire contract made structural)."""
    sys.path.insert(0, REPO)
    from paddle_tpu import tuning
    from paddle_tpu.ops.pallas_kernels import all_kernels

    # decision op -> the tuning key speller that proves the op is wired
    key_spellers = {
        "attention": tuning.attention_key,
        "epilogue": tuning.epilogue_key,
        "conv2d": tuning.conv_key,
        "xent": tuning.xent_key,
    }
    test_defs = []
    for path in glob.glob(os.path.join(REPO, "tests", "*.py")):
        with open(path) as f:
            test_defs.append(f.read())
    blob = "\n".join(test_defs)
    rc = 0
    for name, spec in sorted(all_kernels().items()):
        problems = []
        if not callable(spec.reference):
            problems.append("no XLA reference")
        if not callable(spec.supported):
            problems.append("no supported() shape gate")
        if spec.decision_op not in key_spellers:
            problems.append(
                f"decision_op {spec.decision_op!r} has no tuning key "
                f"speller (known: {sorted(key_spellers)})")
        test = spec.equivalence_test or ""
        if not test or f"def {test}" not in blob:
            problems.append(
                f"equivalence test {test!r} not defined under tests/")
        if problems:
            print(f"[gate] FAIL: pallas kernel '{name}': "
                  + "; ".join(problems), flush=True)
            rc = 1
        else:
            print(f"[gate] kernel registry: '{name}' ok "
                  f"(op={spec.decision_op}, test={test})", flush=True)
    return rc


def _check_kernel_ab(data: dict, label: str) -> int:
    """ISSUE 9 acceptance: a kernel arm that ENGAGED (its Pallas kernel
    actually carried the op) and lost to its kernel-off baseline beyond the
    interference band fails the gate — a kept kernel must keep earning its
    verdict end-to-end every round. Un-engaged arms (CPU rounds: dispatch
    degraded to XLA) are informational only."""
    rc = 0
    ab = data.get("bert_s128_shortattn_ab")
    if isinstance(ab, dict) and ab.get("verdict"):
        print(f"[gate] bench {label}: s128 short-attn A/B xla "
              f"{ab.get('xla_tok_s')} vs pallas {ab.get('pallas_tok_s')} "
              f"tok/s ({ab.get('verdict')}, engaged {ab.get('engaged')}, "
              f"band {ab.get('band')})", flush=True)
        if ab.get("engaged") and ab.get("verdict") == "retire":
            print("[gate] FAIL: the engaged pallas_short128 attention arm "
                  "lost to XLA beyond the interference band — retire the "
                  "swept keep (tools/tune.py --what attention) or fix the "
                  "kernel before snapshotting", flush=True)
            rc = 1
    rn = data.get("resnet50_lever_ab")
    if isinstance(rn, dict) and rn.get("epilogue_verdict"):
        print(f"[gate] bench {label}: resnet epilogue arm "
              f"{rn.get('epilogue_img_s')} img/s vs levered "
              f"{rn.get('levered_img_s')} ({rn.get('epilogue_verdict')}, "
              f"engaged {rn.get('epilogue_engaged')}, "
              f"band {rn.get('epilogue_band')})", flush=True)
        if rn.get("epilogue_engaged") and \
                rn.get("epilogue_verdict") == "retire":
            print("[gate] FAIL: the engaged fused-epilogue arm lost to its "
                  "kernel-off baseline beyond the interference band — "
                  "retire the swept keeps (tools/tune.py --what epilogue) "
                  "or fix the kernel before snapshotting", flush=True)
            rc = 1
    return rc


def run_entry() -> int:
    print("[gate] compile-checking __graft_entry__.entry() ...", flush=True)
    code = ("import __graft_entry__ as g; fn, args = g.entry(); "
            "import jax; jax.eval_shape(fn, *args); print('entry ok')")
    r = subprocess.run([sys.executable, "-c", code], cwd=REPO)
    if r.returncode != 0:
        print("[gate] FAIL: graft entry does not compile", flush=True)
    return r.returncode


def _bench_metrics(text: str) -> dict | None:
    """Extract bench.py's metrics dict from an artifact: either the raw JSON
    line bench.py prints, or the driver's wrapper object (whose "parsed"
    field — or the stdout "tail" — carries that line)."""
    try:
        data = json.loads(text)
    except ValueError:
        data = None
    if isinstance(data, dict):
        if data.get("metric"):
            return data
        if isinstance(data.get("parsed"), dict) and data["parsed"].get("metric"):
            return data["parsed"]
        text = data.get("tail", "")
    for ln in reversed(text.splitlines()):
        ln = ln.strip()
        if ln.startswith("{") and '"metric"' in ln:
            try:
                return json.loads(ln)
            except ValueError:
                continue
    return None


def _check_resnet_regression(data: dict, prev_path: str | None,
                             label: str) -> int:
    """Fail when the newest artifact's `resnet50` vs_target dropped more
    than the interference band below the previous artifact's (ISSUE 5 round
    6). Artifacts without the per-workload vs_target dict are skipped."""
    cur = (data.get("vs_target") or {}).get("resnet50")
    if cur is None or prev_path is None:
        return 0
    try:
        with open(prev_path) as f:
            prev = _bench_metrics(f.read())
    except (OSError, ValueError):
        return 0
    prev_v = ((prev or {}).get("vs_target") or {}).get("resnet50")
    if prev_v is None:
        return 0
    ab = data.get("resnet50_lever_ab")
    print(f"[gate] bench {label}: resnet50 vs_target {cur} "
          f"(prev {prev_v}{', lever A/B ' + str(ab) if ab else ''})",
          flush=True)
    if cur < RESNET_VS_TARGET_DROP * prev_v:
        print(f"[gate] FAIL: resnet50 vs_target regressed {prev_v} -> {cur} "
              f"(> {100 * (1 - RESNET_VS_TARGET_DROP):.0f}% drop) — check "
              f"resnet50_lever_ab and resnet50_windows_img_s for which arm "
              f"moved before blaming the conv lowering", flush=True)
        return 1
    return 0


def _check_tuner_coverage(data: dict, label: str) -> int:
    """Flag a consult-mode bench run whose workloads resolved mostly off
    the swept DB (ISSUE 6): decisions fell through to the analytic prior /
    default, i.e. the workload ran untuned. Artifacts without the tuning
    block (pre-tuner) and off-mode runs are skipped; a workload that made
    zero tunable decisions has nothing to tune and passes."""
    tun = data.get("tuning")
    if not isinstance(tun, dict) or tun.get("mode") not in ("consult",
                                                            "explore"):
        return 0
    rc = 0
    for wl, stats in sorted((tun.get("workloads") or {}).items()):
        n = stats.get("decisions") or 0
        # tuned_rate ((db + learned) / decisions) supersedes hit_rate once
        # the learned tier exists: a confident model prediction is a tuned
        # decision, not a fall-through. Old artifacts only carry hit_rate.
        rate = stats.get("tuned_rate")
        if rate is None:
            rate = stats.get("hit_rate")
        if n == 0 or rate is None:
            continue
        print(f"[gate] bench {label}: tuner {wl} tuned-rate {rate} "
              f"({stats.get('db_hits', 0)} db + "
              f"{stats.get('learned', 0)} learned of {n} decisions)",
              flush=True)
        if rate < TUNER_HIT_RATE_FLOOR:
            print(f"[gate] FAIL: workload '{wl}' ran mostly untuned under "
                  f"FLAGS_tuning_mode={tun.get('mode')} (tuned-rate {rate} "
                  f"< {TUNER_HIT_RATE_FLOOR}) — the DB "
                  f"({tun.get('db') or 'unset'}) is stale/mis-keyed for "
                  f"these shapes; re-sweep with tools/tune.py or run with "
                  f"tuning off", flush=True)
            rc = 1
    lr = tun.get("learned")
    if isinstance(lr, dict) and (lr.get("attempts") or 0) > 0:
        frate = lr.get("fallback_rate")
        print(f"[gate] bench {label}: learned tier fallback-rate {frate} "
              f"({lr.get('fallbacks', 0)}/{lr.get('attempts', 0)} attempts; "
              f"reasons {lr.get('fallback_reasons') or {}})", flush=True)
        if frate is not None and frate > LEARNED_FALLBACK_CEIL:
            print(f"[gate] FAIL: the learned tier fell through its "
                  f"confidence gate on {frate:.0%} of attempts "
                  f"(> {LEARNED_FALLBACK_CEIL:.0%}) — the model "
                  f"({tun.get('model') or 'unset'}) no longer covers this "
                  f"workload's shapes; retrain with tools/costmodel.py "
                  f"train on a fresher measurement store", flush=True)
            rc = 1
    return rc


def _check_shared_prefix(sv: dict, label: str) -> int:
    """Multi-tenant serving gate (ISSUE 11): over the shared-prefix zipf
    mix, refcount/page leaks hard-fail in EVERY arm (an abort path that
    frees a page another request still maps corrupts silently — the leak
    counter is the only cheap tripwire), and the prefix-cache arm's hit
    rate must clear PREFIX_HIT_RATE_FLOOR."""
    sp = sv.get("shared_prefix")
    if not isinstance(sp, dict):
        return 0
    rc = 0
    arms = sp.get("arms") or {}
    for arm, row in arms.items():
        for field in ("kv_pages_leaked", "refcount_leaks"):
            n = row.get(field)
            if n:
                print(f"[gate] FAIL: shared-prefix arm '{arm}' reports "
                      f"{field}={n} — a refcount path (share/release/COW/"
                      f"evict) is freeing or orphaning pages it must not",
                      flush=True)
                rc = 1
    hit = (arms.get("prefix") or {}).get("prefix_cache_hit_rate")
    spec = (arms.get("prefix_spec") or {}).get("spec_accept_rate")
    print(f"[gate] bench {label}: shared-prefix vs_baseline "
          f"{sp.get('vs_baseline_tok_s')}x tok/s, prefill tokens saved "
          f"{sp.get('prefill_tokens_saved')}, hit rate {hit}, "
          f"spec accept {spec}", flush=True)
    if hit is not None and hit < PREFIX_HIT_RATE_FLOOR:
        print(f"[gate] FAIL: prefix-cache hit rate {hit} < "
              f"{PREFIX_HIT_RATE_FLOOR} on the zipf shared-prefix mix — "
              f"the page-granular index is not matching the templates it "
              f"was built to share (key drift or over-eager eviction)",
              flush=True)
        rc = 1
    return rc


def _check_overload(sv: dict, label: str) -> int:
    """Serving-resilience gate (ISSUE 14) over the three-arm overload
    block: page/refcount leaks hard-fail in every arm, overload goodput
    must clear OVERLOAD_GOODPUT_FLOOR of the unloaded arm (and the faulted
    arm the same floor of the overload arm), and admitted-request p99 TTFT
    must stay within OVERLOAD_TTFT_CEIL_RATIO of unloaded. Artifacts
    predating the block are skipped."""
    ov = sv.get("overload")
    if not isinstance(ov, dict):
        return 0
    rc = 0
    arms = ov.get("arms") or {}
    for arm, row in sorted(arms.items()):
        for field in ("kv_pages_leaked", "refcount_leaks"):
            n = row.get(field)
            if n:
                print(f"[gate] FAIL: overload arm '{arm}' reports "
                      f"{field}={n} — a shed/expire/recovery path is "
                      f"freeing or orphaning pages it must not", flush=True)
                rc = 1
    g_ratio = ov.get("goodput_vs_unloaded")
    f_ratio = ov.get("faulted_vs_overload")
    t_ratio = ov.get("ttft_p99_ratio")
    print(f"[gate] bench {label}: overload goodput {g_ratio}x unloaded, "
          f"faulted {f_ratio}x overload, shed rate {ov.get('shed_rate')}, "
          f"admitted ttft p99 ratio {t_ratio}, recoveries "
          f"{(arms.get('overload_faulted') or {}).get('recovery_passes')}",
          flush=True)
    if g_ratio is not None and g_ratio < OVERLOAD_GOODPUT_FLOOR:
        print(f"[gate] FAIL: overload goodput is {g_ratio}x the unloaded "
              f"arm (floor {OVERLOAD_GOODPUT_FLOOR}) — the shed floors / "
              f"degradation ladder are thrashing the engine instead of "
              f"protecting it (check shed_rate and ladder_climbs in the "
              f"block)", flush=True)
        rc = 1
    if f_ratio is not None and f_ratio < OVERLOAD_GOODPUT_FLOOR:
        print(f"[gate] FAIL: the faulted overload arm delivers {f_ratio}x "
              f"the fault-free overload arm (floor {OVERLOAD_GOODPUT_FLOOR})"
              f" — supervised recovery (retries, pool rebuild, replay) is "
              f"costing unbounded work", flush=True)
        rc = 1
    if t_ratio is not None and t_ratio > OVERLOAD_TTFT_CEIL_RATIO:
        print(f"[gate] FAIL: admitted-request p99 TTFT under overload is "
              f"{t_ratio}x the unloaded arm (ceiling "
              f"{OVERLOAD_TTFT_CEIL_RATIO}) — admission control is letting "
              f"the queue collapse instead of shedding", flush=True)
        rc = 1
    return rc


def _check_serving(data: dict, prev_path: str | None, label: str) -> int:
    """Serving-block gate (ISSUE 7): zero KV-page leak is a hard invariant;
    served tokens/s may not drop below SERVING_TOK_S_DROP of the previous
    artifact's (both artifacts must carry the block — pre-serving rounds
    are skipped)."""
    sv = data.get("serving")
    if not isinstance(sv, dict):
        return 0
    leaked = sv.get("kv_pages_leaked")
    cur = sv.get("served_tokens_per_sec")
    lat = sv.get("request_latency") or {}
    print(f"[gate] bench {label}: serving {cur} tok/s, p50 "
          f"{lat.get('p50_ms')} ms, p99 {lat.get('p99_ms')} ms, occupancy "
          f"peak {sv.get('kv_pool_occupancy_peak')}, leaked pages {leaked}",
          flush=True)
    if leaked:
        print(f"[gate] FAIL: the KV pool leaked {leaked} pages after the "
              f"open-loop run drained — a request path (finish/abort/"
              f"preempt) is not returning pages to the free list",
              flush=True)
        return 1
    if sv.get("refcount_leaks"):
        print(f"[gate] FAIL: {sv['refcount_leaks']} pages still off the "
              f"free list after drain + prefix-cache flush — a refcount "
              f"path (share/release/COW/evict) lost track of a holder",
              flush=True)
        return 1
    rc = _check_shared_prefix(sv, label)
    if rc:
        return rc
    rc = _check_overload(sv, label)
    if rc:
        return rc
    if cur is None or prev_path is None:
        return 0
    try:
        with open(prev_path) as f:
            prev = _bench_metrics(f.read())
    except (OSError, ValueError):
        return 0
    prev_v = ((prev or {}).get("serving") or {}).get("served_tokens_per_sec")
    if prev_v is None:
        return 0
    if cur < SERVING_TOK_S_DROP * prev_v:
        print(f"[gate] FAIL: served tokens/s regressed {prev_v} -> {cur} "
              f"(> {100 * (1 - SERVING_TOK_S_DROP):.0f}% drop on the seeded "
              f"open-loop workload) — check decode_compile_buckets and "
              f"preemptions before blaming the attention kernel",
              flush=True)
        return 1
    return 0


def _check_embedding(data: dict, prev_path: str | None, label: str) -> int:
    """Embedding-cache gate (ISSUE 10): the `deepfm_giant` block's parity
    drift vs the dense-lookup oracle hard-fails above EMB_PARITY_ATOL; the
    cache hit-rate floor WARNS when the previous artifact predates the
    block (first landing) and FAILS once a prior artifact carries it."""
    blk = data.get("deepfm_giant")
    if not isinstance(blk, dict):
        return 0
    rc = 0
    parity = blk.get("parity_max_abs_diff")
    hit = blk.get("cache_hit_rate")
    print(f"[gate] bench {label}: deepfm_giant {blk.get('examples_per_sec')}"
          f" ex/s, hit-rate {hit}, parity drift {parity}, host tier "
          f"{blk.get('host_tier_bytes')} B vs budget "
          f"{blk.get('hbm_budget_mb')} MB", flush=True)
    if parity is None or parity > EMB_PARITY_ATOL:
        print(f"[gate] FAIL: tiered-embedding parameter parity drift "
              f"{parity} exceeds {EMB_PARITY_ATOL} vs the dense-lookup "
              f"oracle — an install/write-back/scatter path is losing "
              f"updates (check evictions vs writebacks in the block before "
              f"blaming the optimizer)", flush=True)
        rc = 1
    if hit is not None and hit < EMB_HIT_RATE_FLOOR:
        prev_has_block = False
        if prev_path is not None:
            try:
                with open(prev_path) as f:
                    prev = _bench_metrics(f.read())
                prev_has_block = isinstance((prev or {}).get("deepfm_giant"),
                                            dict)
            except (OSError, ValueError):
                pass
        if prev_has_block:
            print(f"[gate] FAIL: deepfm_giant cache hit-rate {hit} fell "
                  f"below {EMB_HIT_RATE_FLOOR} on the seeded zipf workload "
                  f"— the admission/eviction policy regressed (the id "
                  f"stream is identical every round)", flush=True)
            rc = 1
        else:
            print(f"[gate] WARN: deepfm_giant cache hit-rate {hit} < "
                  f"{EMB_HIT_RATE_FLOOR} on the block's first artifact — "
                  f"recorded as the baseline; this gates from the next "
                  f"round", flush=True)
    return rc


def check_multichip(path: str | None = None) -> int:
    """`--multichip`: gate the newest MULTICHIP_r*.json campaign artifact
    (ISSUE 8) the way check_bench gates BENCH — loss/parameter parity drift
    is a hard correctness fail, the per-axis scaling floor catches a
    partitioning/collective regression, and an overlap-on arm that LOSES to
    its overlap-off baseline by more than the interference band means the
    bucketing/schedule machinery regressed. Pre-campaign artifacts (parity
    dryrun only, no `scaling` block) are skipped so old snapshots stay
    green."""
    arts = sorted(glob.glob(os.path.join(REPO, "MULTICHIP_r*.json")))
    if path is None:
        if not arts:
            print("[gate] WARN: no MULTICHIP_r*.json artifact", flush=True)
            return 0
        path = arts[-1]
    label = os.path.basename(path)
    try:
        with open(path) as f:
            data = _bench_metrics(f.read())
    except (OSError, ValueError) as e:
        print(f"[gate] WARN: cannot read multichip artifact {path}: {e}",
              flush=True)
        return 0
    if not isinstance(data, dict) or "scaling" not in data:
        print(f"[gate] WARN: {label} predates the measured campaign "
              f"(no scaling block) — skipped", flush=True)
        return 0
    rc = 0
    for arm, drift in sorted((data.get("parity") or {}).items()):
        if drift is None:
            continue
        print(f"[gate] multichip {label}: parity[{arm}] drift {drift}",
              flush=True)
        if drift > MC_PARITY_DRIFT:
            print(f"[gate] FAIL: '{arm}' diverged from the single-device "
                  f"parameter trajectory (drift {drift} > {MC_PARITY_DRIFT})"
                  f" — a wrong collective/schedule, not interference noise",
                  flush=True)
            rc = 1
    cpu = str(data.get("platform", "cpu")).lower() != "tpu"
    for axis, row in sorted((data.get("scaling") or {}).items()):
        speed = row.get("speedup_vs_single")
        eff = row.get("efficiency")
        print(f"[gate] multichip {label}: {axis} {row.get('tokens_per_sec')}"
              f" tok/s, speedup {speed}, efficiency {eff} "
              f"(n={row.get('n_devices')}, band {row.get('band')})",
              flush=True)
        if cpu and speed is not None and speed < MC_CPU_SPEEDUP_FLOOR:
            print(f"[gate] FAIL: {axis} speedup_vs_single {speed} < "
                  f"{MC_CPU_SPEEDUP_FLOOR} on the virtual CPU mesh — the "
                  f"partitioned step collapsed (check the arm's band before "
                  f"blaming the collective layout)", flush=True)
            rc = 1
        if not cpu and eff is not None and eff < MC_EFFICIENCY_FLOOR:
            print(f"[gate] FAIL: {axis} scaling efficiency {eff} < "
                  f"{MC_EFFICIENCY_FLOOR} on real chips — the axis is not "
                  f"earning its devices", flush=True)
            rc = 1
    for arm, ab in sorted((data.get("overlap_ab") or {}).items()):
        print(f"[gate] multichip {label}: overlap {arm} off "
              f"{ab.get('off_tok_s')} -> on {ab.get('on_tok_s')} tok/s "
              f"({ab.get('verdict')}, band {ab.get('band')})", flush=True)
        if ab.get("verdict") == "retire":
            if arm == "dp_zero1":
                # ZeRO-1 is an opt-in MEMORY lever (FLAGS_zero1 default
                # off): its contract is opt-state HBM / |dp|, and on shared
                # silicon the extra scatter/gather ops are honest cost —
                # record the measured loss, don't block the snapshot
                print(f"[gate] WARN: zero1 measured slower than bucketed "
                      f"allreduce on this platform (expected on a virtual "
                      f"CPU mesh; the lever buys memory, not host FLOPs)",
                      flush=True)
                continue
            print(f"[gate] FAIL: overlap arm '{arm}' LOSES to its "
                  f"overlap-off baseline by more than the interference band "
                  f"— the overlap machinery itself regressed", flush=True)
            rc = 1
    return rc


def check_fleet(path: str | None = None) -> int:
    """`--fleet`: gate the newest (or given) FLEET_r*.json campaign
    artifact (ISSUE 16, tools/_serve_ab.py --fleet). Hard zeros first —
    lost requests / duplicate tokens under the mid-pass SIGKILL, shed
    requests under drain-and-retire, leaked pages on any surviving engine
    — then the scaling floor (CPU-adjusted when the box has fewer cores
    than replicas) and the kill arm's bounded p99 TTFT. The kill arm must
    actually have exercised the machinery: >= 1 discovered death and >= 1
    replayed token, or the artifact measured nothing."""
    arts = sorted(glob.glob(os.path.join(REPO, "FLEET_r*.json")))
    if path is None:
        if not arts:
            print("[gate] WARN: no FLEET_r*.json artifact", flush=True)
            return 0
        path = arts[-1]
    label = os.path.basename(path)
    try:
        with open(path) as f:
            text = f.read()
        data = json.loads(text)
    except (OSError, ValueError) as e:
        print(f"[gate] WARN: cannot read fleet artifact {path}: {e}",
              flush=True)
        return 0
    if not isinstance(data, dict) or "arms" not in data:
        print(f"[gate] WARN: {label} carries no fleet arms — skipped",
              flush=True)
        return 0
    rc = 0
    arms = data.get("arms") or {}
    for arm, row in sorted(arms.items()):
        if row.get("kv_pages_leaked"):
            print(f"[gate] FAIL: fleet arm '{arm}' leaked "
                  f"{row['kv_pages_leaked']} KV pages on a surviving "
                  f"engine — a failover/drain path lost pages", flush=True)
            rc = 1
        if row.get("replay_divergence"):
            print(f"[gate] FAIL: fleet arm '{arm}' recorded "
                  f"{row['replay_divergence']} diverging replayed tokens "
                  f"under greedy — batch-composition invariance broke",
                  flush=True)
            rc = 1
    kill = arms.get("kill") or {}
    print(f"[gate] fleet {label}: single {arms.get('single', {}).get('tok_s')}"
          f" -> fleet {arms.get('fleet4', {}).get('tok_s')} tok/s "
          f"(x{data.get('scaling_vs_single')}, {data.get('n_replicas')} "
          f"replicas on {data.get('cores')} cores); kill arm lost "
          f"{data.get('kill_lost')}, dup {data.get('kill_duplicate_tokens')}"
          f", ttft p99 x{data.get('kill_ttft_p99_ratio')}; drain shed "
          f"{data.get('drain_shed')}, retired {data.get('drain_retired')}",
          flush=True)
    if data.get("kill_lost"):
        print(f"[gate] FAIL: the SIGKILL arm LOST {data['kill_lost']} "
              f"requests — failover replay must finish every in-flight "
              f"request on a survivor", flush=True)
        rc = 1
    if data.get("kill_duplicate_tokens"):
        print(f"[gate] FAIL: the SIGKILL arm delivered "
              f"{data['kill_duplicate_tokens']} duplicate tokens — the "
              f"router ledger's exactly-once dedup regressed", flush=True)
        rc = 1
    if not kill.get("deaths") or not kill.get("replayed_tokens"):
        print(f"[gate] FAIL: the kill arm discovered "
              f"{kill.get('deaths')} deaths / replayed "
              f"{kill.get('replayed_tokens')} tokens — the fault never "
              f"engaged, the artifact measured nothing", flush=True)
        rc = 1
    if data.get("drain_shed"):
        print(f"[gate] FAIL: drain-and-retire shed {data['drain_shed']} "
              f"requests — a planned migration must hand work off, not "
              f"drop it", flush=True)
        rc = 1
    if not data.get("drain_retired"):
        print("[gate] FAIL: the drain arm never observed the retire — "
              "the DRAINING replica did not empty out", flush=True)
        rc = 1
    scaling = data.get("scaling_vs_single")
    cores = data.get("cores") or 0
    n_rep = data.get("n_replicas") or 1
    if scaling is not None:
        if cores >= n_rep and scaling < FLEET_SCALING_FLOOR:
            print(f"[gate] FAIL: 1 -> {n_rep} replicas scaled tok/s only "
                  f"{scaling}x (floor {FLEET_SCALING_FLOOR}) with "
                  f"{cores} cores available — the router/pump layer is "
                  f"serializing the fleet", flush=True)
            rc = 1
        elif cores < n_rep and scaling < FLEET_CPU_OVERHEAD_FLOOR:
            print(f"[gate] FAIL: on {cores} core(s) the {n_rep}-replica "
                  f"fleet delivers {scaling}x the single replica (floor "
                  f"{FLEET_CPU_OVERHEAD_FLOOR}) — fleet overhead is eating "
                  f"the engine, beyond honest timesharing", flush=True)
            rc = 1
    kill_p99 = ((kill.get("ttft") or {}).get("p99_ms"))
    healthy_p99 = (((arms.get("fleet4") or {}).get("ttft") or {})
                   .get("p99_ms"))
    if kill_p99 is not None and healthy_p99 is not None:
        detect_ms = FLEET_DETECT_BUDGET_BEATS * 1000.0 \
            * float(data.get("heartbeat_s") or 0.0)
        ceil_ms = FLEET_TTFT_CEIL_RATIO * healthy_p99 + detect_ms
        if kill_p99 > ceil_ms:
            print(f"[gate] FAIL: the kill arm's p99 TTFT is {kill_p99}ms vs "
                  f"a ceiling of {FLEET_TTFT_CEIL_RATIO}x the healthy fleet "
                  f"arm ({healthy_p99}ms) + a {detect_ms:g}ms detection "
                  f"budget — death discovery/replay is stalling admitted "
                  f"traffic beyond the heartbeat deadline it must cost",
                  flush=True)
            rc = 1
    return rc


def check_disagg(path: str | None = None) -> int:
    """`--disagg`: gate the newest (or given) DISAGG_r*.json campaign
    artifact (ISSUE 19, tools/_serve_ab.py --disagg). Hard zeros across
    every arm — lost requests, duplicate tokens, leaked pages, leases left
    PREPARED, shared-pool audit problems — then the split arm's bounded
    p99 TTFT vs co-located (ratio + queueing budget, see the constants)
    and proof the kill arm exercised the orphan-recovery machinery:
    >= 1 reaped lease and >= 1 handoff replay."""
    arts = sorted(glob.glob(os.path.join(REPO, "DISAGG_r*.json")))
    if path is None:
        if not arts:
            print("[gate] WARN: no DISAGG_r*.json artifact", flush=True)
            return 0
        path = arts[-1]
    label = os.path.basename(path)
    try:
        with open(path) as f:
            data = json.loads(f.read())
    except (OSError, ValueError) as e:
        print(f"[gate] WARN: cannot read disagg artifact {path}: {e}",
              flush=True)
        return 0
    if not isinstance(data, dict) or "arms" not in data:
        print(f"[gate] WARN: {label} carries no disagg arms — skipped",
              flush=True)
        return 0
    rc = 0
    arms = data.get("arms") or {}
    for arm, row in sorted(arms.items()):
        for key, what in (
                ("lost", "lost requests"),
                ("duplicate_tokens", "duplicate delivered tokens"),
                ("kv_pages_leaked", "leaked KV pages"),
                ("replay_divergence", "diverging replayed tokens"),
                ("leases_left_prepared", "leases left PREPARED")):
            if row.get(key):
                print(f"[gate] FAIL: disagg arm '{arm}' recorded "
                      f"{row[key]} {what} — the handoff protocol must "
                      f"hold its hard zeros", flush=True)
                rc = 1
        if row.get("pool_audit_problems"):
            print(f"[gate] FAIL: disagg arm '{arm}' left a dirty "
                  f"shared-pool audit: {row['pool_audit_problems'][:4]}",
                  flush=True)
            rc = 1
    kill = arms.get("kill") or {}
    print(f"[gate] disagg {label}: coloc "
          f"{arms.get('coloc', {}).get('tok_s')} -> split "
          f"{arms.get('disagg', {}).get('tok_s')} tok/s "
          f"(x{data.get('disagg_tok_s_ratio')}); ttft p99 "
          f"x{data.get('disagg_ttft_p99_ratio')}; kill arm lost "
          f"{data.get('kill_lost')}, dup "
          f"{data.get('kill_duplicate_tokens')}, reaped "
          f"{data.get('kill_reaped_leases')} lease(s), "
          f"{data.get('kill_handoff_replays')} replay(s)", flush=True)
    if not kill.get("handoff", {}).get("reaped"):
        print("[gate] FAIL: the mid-handoff kill arm reaped no lease — "
              "the orphan-recovery path never engaged, the artifact "
              "measured nothing", flush=True)
        rc = 1
    if not data.get("kill_handoff_replays"):
        print("[gate] FAIL: the kill arm replayed no handoff — a reaped "
              "lease must turn into a replay, not a lost request",
              flush=True)
        rc = 1
    coloc_p99 = ((arms.get("coloc") or {}).get("ttft") or {}).get("p99_ms")
    for arm in ("disagg", "kill"):
        row = arms.get(arm) or {}
        p99 = (row.get("ttft") or {}).get("p99_ms")
        wall_ms = 1000.0 * float(row.get("wall_s") or 0.0)
        if p99 is None or coloc_p99 is None:
            continue
        ceil_ms = (DISAGG_TTFT_CEIL_RATIO * coloc_p99
                   + DISAGG_QUEUE_BUDGET_WALL_FRAC * wall_ms)
        if p99 > ceil_ms:
            print(f"[gate] FAIL: the '{arm}' arm's p99 TTFT is {p99}ms vs "
                  f"a ceiling of {DISAGG_TTFT_CEIL_RATIO}x the co-located "
                  f"arm ({coloc_p99}ms) + a "
                  f"{DISAGG_QUEUE_BUDGET_WALL_FRAC:g}x-wall queueing "
                  f"budget ({wall_ms:g}ms wall) — handoffs are stalling "
                  f"first tokens beyond decode-slot queueing", flush=True)
            rc = 1
    return rc


def check_control(path: str | None = None) -> int:
    """`--control`: gate the newest (or given) CONTROL_r*.json artifact
    (ISSUE 20, tools/_serve_ab.py --control). Hard zeros on leaks across
    every measured engine; tier "learned" on every bench arm; overloaded
    arms meet-or-beat the hand config; the unloaded arm inside the
    near-tie band; shadow overhead under the telemetry ceiling; the
    trained group's holdout rank accuracy above the confidence floor.
    When CONTROL_DATA_cpu.jsonl is committed, retrain from it and require
    the artifact's proposals to reproduce."""
    arts = sorted(glob.glob(os.path.join(REPO, "CONTROL_r*.json")))
    if path is None:
        if not arts:
            print("[gate] WARN: no CONTROL_r*.json artifact", flush=True)
            return 0
        path = arts[-1]
    label = os.path.basename(path)
    try:
        with open(path) as f:
            data = json.loads(f.read())
    except (OSError, ValueError) as e:
        print(f"[gate] WARN: cannot read control artifact {path}: {e}",
              flush=True)
        return 0
    if not isinstance(data, dict) or "arms" not in data:
        print(f"[gate] WARN: {label} carries no control arms — skipped",
              flush=True)
        return 0
    rc = 0
    if data.get("leaked_pages") or data.get("refcount_leaks"):
        print(f"[gate] FAIL: control campaign leaked "
              f"{data.get('leaked_pages')} page(s) / "
              f"{data.get('refcount_leaks')} refcount(s) — an actuated "
              f"engine must hold the same hard zeros as a hand one",
              flush=True)
        rc = 1
    arms = data.get("arms") or {}
    for arm, row in sorted(arms.items()):
        ratio, tier = row.get("ratio"), row.get("tier")
        print(f"[gate] control {label}: arm '{arm}' tier {tier}, learned "
              f"{(row.get('learned') or {}).get('goodput_tok_s')} vs hand "
              f"{(row.get('hand') or {}).get('goodput_tok_s')} goodput "
              f"tok/s (x{ratio}), proposal [{row.get('proposal')}]",
              flush=True)
        if tier != "learned":
            print(f"[gate] FAIL: arm '{arm}' fell back to the hand tier "
                  f"({row.get('reason')}) — the model cannot clear its own "
                  f"confidence gate on a regime it was trained on; the "
                  f"sweep is too thin or the envelope too narrow",
                  flush=True)
            rc = 1
        if ratio is None:
            continue
        floor = ((1.0 - CONTROL_TIE_BAND) if arm == "unloaded"
                 else CONTROL_WIN_FLOOR)
        if ratio < floor:
            what = ("regressed the unloaded arm"
                    if arm == "unloaded" else "lost to the hand config")
            print(f"[gate] FAIL: the learned proposal {what} on '{arm}' "
                  f"(x{ratio} < {floor:g}) — a controller that serves "
                  f"fewer goodput tokens than the flags it replaces is a "
                  f"regression", flush=True)
            rc = 1
    acc = ((data.get("model") or {}).get("holdout") or {}).get("rank_acc")
    if acc is None or acc < CONTROL_RANK_ACC_FLOOR:
        print(f"[gate] FAIL: serving.control holdout rank accuracy {acc} "
              f"is under the {CONTROL_RANK_ACC_FLOOR:.0%} confidence floor "
              f"— the committed model would refuse (or mis-rank) live "
              f"proposals; widen the sweep", flush=True)
        rc = 1
    pct = (data.get("shadow") or {}).get("shadow_overhead_pct")
    if pct is None or pct > OBS_OVERHEAD_CEIL_PCT:
        print(f"[gate] FAIL: shadow-mode controller costs {pct}% of "
              f"overload goodput (> {OBS_OVERHEAD_CEIL_PCT}%) — the "
              f"observe/propose epoch landed on the serving hot path",
              flush=True)
        rc = 1
    else:
        print(f"[gate] control {label}: shadow overhead {pct}% "
              f"(<= {OBS_OVERHEAD_CEIL_PCT}%), holdout rank-acc {acc}",
              flush=True)
    rc = _control_retrain_check(data, label) or rc
    return rc


def _control_retrain_check(data: dict, label: str) -> int:
    """Determinism half of --control: retrain from the committed sweep
    dataset and require every artifact proposal to reproduce. Training is
    seeded (sorted keys, seeded permutation, closed-form ridge), so a
    mismatch is drift between the committed dataset and artifact, not
    noise."""
    data_path = os.path.join(REPO, CONTROL_DATA)
    if not os.path.exists(data_path):
        print(f"[gate] WARN: {CONTROL_DATA} not committed — skipping the "
              f"control retrain-determinism check", flush=True)
        return 0
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from paddle_tpu import flags as pt_flags
    from paddle_tpu.serving import control as sv_control
    from paddle_tpu.tuning import learned

    recs = list(learned.iter_records(data_path))
    model = learned.train_model(recs, seed=int(data.get("seed", 0)))
    rc = 0
    old_mode = pt_flags.get_flag("serve_control_mode")
    pt_flags.set_flags({"serve_control_mode": "shadow"})
    try:
        for arm, row in sorted((data.get("arms") or {}).items()):
            sig = row.get("sig")
            if not isinstance(sig, dict):
                continue
            proposal, info = sv_control.propose(sig, model=model)
            got = sv_control.knob_key(proposal)
            want = row.get("proposal")
            if got != want:
                print(f"[gate] FAIL: retraining from {CONTROL_DATA} "
                      f"proposes [{got}] for arm '{arm}' but the artifact "
                      f"recorded [{want}] — dataset and artifact drifted "
                      f"apart; re-run tools/_serve_ab.py --control",
                      flush=True)
                rc = 1
    finally:
        pt_flags.set_flags({"serve_control_mode": old_mode})
    if rc == 0:
        print(f"[gate] control {label}: proposals reproduce from "
              f"{CONTROL_DATA} ({len(recs)} rows)", flush=True)
    return rc


def _check_obs(data: dict, label: str, require: bool = False) -> int:
    """Telemetry-block gate (ISSUE 13). Three failure modes:
      * missing block (only when `require` — artifacts predating the layer
        stay green under the plain bench gate; `--obs` demands it);
      * registry overhead above OBS_OVERHEAD_CEIL_PCT — the layer rides
        every hot loop, so measurable cost is a perf bug, not a feature;
      * metric-name drift: any name the run recorded that the declared
        schema (paddle_tpu/observability/schema.py) does not list — an
        undeclared metric is a lint error, because name drift is how
        dashboards and SLO rules silently go dark."""
    blk = data.get("telemetry")
    if not isinstance(blk, dict):
        if require:
            print(f"[gate] FAIL: {label} carries no telemetry block — "
                  f"bench.py must measure the registry A/B "
                  f"(bench_telemetry) for --obs to pass", flush=True)
            return 1
        return 0
    rc = 0
    pct = blk.get("obs_overhead_pct")
    print(f"[gate] bench {label}: telemetry overhead {pct}% "
          f"(on {blk.get('examples_per_sec_obs_on')} vs off "
          f"{blk.get('examples_per_sec_obs_off')} ex/s)", flush=True)
    if pct is None or pct > OBS_OVERHEAD_CEIL_PCT:
        print(f"[gate] FAIL: the telemetry registry costs {pct}% "
              f"(> {OBS_OVERHEAD_CEIL_PCT}%) of async-dispatch throughput "
              f"— instrumentation must stay ~free; check what landed on "
              f"the per-step path (histogram in a lock? sink doing I/O "
              f"inline?) before shipping", flush=True)
        rc = 1
    undeclared = blk.get("undeclared_metrics")
    if undeclared:
        print(f"[gate] FAIL: metrics recorded outside the declared schema: "
              f"{undeclared} — declare them in paddle_tpu/observability/"
              f"schema.py (with kind + help) or fix the call site's name",
              flush=True)
        rc = 1
    names = blk.get("metric_names")
    if names:
        sys.path.insert(0, REPO)
        from paddle_tpu.observability import schema

        drift = sorted(n for n in names
                       if n.split("{")[0] not in schema.DECLARED_NAMES
                       and not n.endswith(".seconds"))
        if drift:
            print(f"[gate] FAIL: artifact metric names not in "
                  f"observability/schema.py: {drift} — schema and emitters "
                  f"drifted apart", flush=True)
            rc = 1
        else:
            print(f"[gate] bench {label}: {len(names)} metric names, all "
                  f"declared", flush=True)
    return rc


def check_obs(path: str | None = None) -> int:
    """`--obs`: gate the newest (or given) bench artifact's telemetry block
    only, and REQUIRE the block to exist."""
    arts = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")))
    if path is None:
        if not arts:
            print("[gate] WARN: no BENCH_r*.json artifact", flush=True)
            return 0
        path = arts[-1]
    try:
        with open(path) as f:
            data = _bench_metrics(f.read())
    except (OSError, ValueError) as e:
        print(f"[gate] WARN: cannot read bench artifact {path}: {e}",
              flush=True)
        return 0
    if data is None:
        print(f"[gate] WARN: no bench metrics line in {path}", flush=True)
        return 0
    return _check_obs(data, os.path.basename(path), require=True)


def check_bench(path: str | None = None) -> int:
    """Flag a DeepFM end-to-end/device-path regression in the bench artifact.

    Pre-pipeline artifacts (no deepfm_e2e_device_ratio field) are skipped so
    the gate stays meaningful across old snapshots."""
    prev_path = None
    arts = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")))
    if path is None:
        if not arts:
            return 0
        path = arts[-1]
    apath = os.path.abspath(path)
    if apath in arts and arts.index(apath) > 0:
        prev_path = arts[arts.index(apath) - 1]
    try:
        with open(path) as f:
            text = f.read()
        data = _bench_metrics(text)
    except (OSError, ValueError, IndexError) as e:
        print(f"[gate] WARN: cannot read bench artifact {path}: {e}",
              flush=True)
        return 0
    if data is None:
        print(f"[gate] WARN: no bench metrics line in {path}", flush=True)
        return 0
    if _check_resnet_regression(data, prev_path, os.path.basename(path)):
        return 1
    if _check_kernel_ab(data, os.path.basename(path)):
        return 1
    if _check_tuner_coverage(data, os.path.basename(path)):
        return 1
    if _check_serving(data, prev_path, os.path.basename(path)):
        return 1
    if _check_embedding(data, prev_path, os.path.basename(path)):
        return 1
    if _check_obs(data, os.path.basename(path)):
        return 1
    ratio = data.get("deepfm_e2e_device_ratio")
    if ratio is None:
        return 0  # artifact predates the pipeline ratio
    e2e = data.get("deepfm_examples_per_sec")
    dev = data.get("deepfm_device_path_examples_per_sec")
    print(f"[gate] bench {os.path.basename(path)}: DeepFM e2e/device "
          f"ratio {ratio} (e2e {e2e} ex/s, device {dev} ex/s)", flush=True)
    if ratio < DEEPFM_RATIO_FLOOR:
        print(f"[gate] FAIL: DeepFM end-to-end path delivers only "
              f"{ratio:.0%} of device-path throughput "
              f"(floor {DEEPFM_RATIO_FLOOR}) — the feed/dispatch pipeline "
              f"regressed; judge against deepfm_windows_ex_s spread "
              f"(PERF.md r5) before blaming code", flush=True)
        return 1
    guard_pct = data.get("deepfm_guard_overhead_pct")
    if guard_pct is not None:
        print(f"[gate] bench {os.path.basename(path)}: health-sentinel "
              f"overhead {guard_pct}% vs the unguarded device path",
              flush=True)
        if guard_pct > GUARD_OVERHEAD_CEIL_PCT:
            print(f"[gate] FAIL: the in-graph health sentinel costs "
                  f"{guard_pct}% (> {GUARD_OVERHEAD_CEIL_PCT}%) of device "
                  f"throughput — the guard must stay ~free; check what the "
                  f"sentinel op compiled into (and the measurement spread) "
                  f"before blaming code", flush=True)
            return 1
    return 0


def check_costmodel(data_path: str | None = None,
                    model_path: str | None = None) -> int:
    """Learned cost-model gate (ISSUE 15): the committed model artifact must
    keep beating the analytic prior on its recorded holdout keys.

    Re-scores COSTMODEL_cpu.json against COSTMODEL_DATA_cpu.jsonl with the
    same scorer tools/costmodel.py eval uses. Fails when any group's holdout
    arm-ranking accuracy drops below COSTMODEL_RANK_ACC_FLOOR or below the
    analytic prior's on the same keys (a learned tier that ranks worse than
    the formula it shadows is a regression, not a tier). Also re-checks the
    newest bench artifact's learned fallback rate (the consult-mode half of
    the acceptance line) so `--costmodel` alone covers both. Repos without
    the committed artifacts skip with a WARN — the gate stays meaningful on
    old snapshots."""
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from paddle_tpu.tuning import learned

    data_path = data_path or os.path.join(REPO, COSTMODEL_DATA)
    model_path = model_path or os.path.join(REPO, COSTMODEL_MODEL)
    if not os.path.exists(data_path) or not os.path.exists(model_path):
        print(f"[gate] WARN: costmodel artifacts missing "
              f"({COSTMODEL_DATA} / {COSTMODEL_MODEL}) — skipping",
              flush=True)
        return 0
    try:
        model = learned.load_model(model_path)
    except ValueError as e:
        print(f"[gate] FAIL: committed cost model {model_path} is "
              f"unreadable ({e}) — retrain with tools/costmodel.py train",
              flush=True)
        return 1
    if model is None:
        print(f"[gate] WARN: cost model {model_path} vanished — skipping",
              flush=True)
        return 0
    recs = list(learned.iter_records(data_path))
    ev = learned.eval_model(model, recs)
    rc = 0
    if not ev["groups"]:
        print(f"[gate] FAIL: committed cost model has no evaluable group "
              f"against {os.path.basename(data_path)} — dataset/model "
              f"drifted apart; re-run tools/costmodel.py train", flush=True)
        return 1
    for g, r in sorted(ev["groups"].items()):
        acc, ana = r.get("rank_acc"), r.get("analytic_rank_acc")
        print(f"[gate] costmodel {g}: holdout rank-acc {acc} vs analytic "
              f"{ana} over {r.get('n')} keys", flush=True)
        if acc is None:
            continue
        if acc < COSTMODEL_RANK_ACC_FLOOR:
            print(f"[gate] FAIL: learned model ranks arms correctly on only "
                  f"{acc:.0%} of {g} holdout keys "
                  f"(floor {COSTMODEL_RANK_ACC_FLOOR:.0%}) — the committed "
                  f"model is stale for the committed dataset; retrain with "
                  f"tools/costmodel.py train", flush=True)
            rc = 1
        elif ana is not None and acc < ana:
            print(f"[gate] FAIL: learned model ({acc:.0%}) ranks {g} "
                  f"holdout arms WORSE than the analytic prior ({ana:.0%}) "
                  f"it is supposed to beat — the tier is a regression; "
                  f"retrain or widen the dataset", flush=True)
            rc = 1
    # the runtime half: the newest bench artifact's learned fallback rate
    # (also enforced on --bench via _check_tuner_coverage; harmless twice)
    arts = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")))
    if arts:
        try:
            with open(arts[-1]) as f:
                data = _bench_metrics(f.read())
        except (OSError, ValueError, IndexError):
            data = None
        if isinstance(data, dict):
            rc = _check_tuner_coverage(data, os.path.basename(arts[-1])) or rc
    return rc


def main() -> int:
    if "--obs" in sys.argv:
        arg = sys.argv[sys.argv.index("--obs") + 1:]
        return check_obs(arg[0] if arg else None)
    if "--bench" in sys.argv:
        arg = sys.argv[sys.argv.index("--bench") + 1:]
        return check_bench(arg[0] if arg else None)
    if "--multichip" in sys.argv:
        arg = sys.argv[sys.argv.index("--multichip") + 1:]
        return check_multichip(arg[0] if arg else None)
    if "--chaos" in sys.argv:
        return run_chaos()
    if "--kernels" in sys.argv:
        return check_kernel_registry()
    if "--costmodel" in sys.argv:
        return check_costmodel()
    if "--fleet" in sys.argv:
        arg = sys.argv[sys.argv.index("--fleet") + 1:]
        return check_fleet(arg[0] if arg else None)
    if "--disagg" in sys.argv:
        arg = sys.argv[sys.argv.index("--disagg") + 1:]
        return check_disagg(arg[0] if arg else None)
    if "--control" in sys.argv:
        arg = sys.argv[sys.argv.index("--control") + 1:]
        return check_control(arg[0] if arg else None)
    rc = run_suite()
    if "--fast" not in sys.argv:
        rc = rc or run_entry()
        rc = rc or check_kernel_registry()
        rc = rc or check_bench()
        rc = rc or check_multichip()
        rc = rc or check_costmodel()
        rc = rc or check_fleet()
        rc = rc or check_disagg()
        rc = rc or check_control()
    if rc == 0:
        print("[gate] OK — green suite, safe to snapshot")
    return rc


if __name__ == "__main__":
    sys.exit(main())
