"""Step-time decomposition for BERT bench config.
usage: _decomp.py MODE   (full | fp32 | nobwd | nohead | noattn | embmm)"""
import sys, time, json
import jax, numpy as np

def run(mode):
    import paddle_tpu as pt
    if mode == "embmm":
        import jax.numpy as jnp
        from paddle_tpu.ops import registry as R
        def mm_grad(ctx):
            w, ids, og = ctx.input("W"), ctx.input("Ids"), ctx.input("Out@GRAD")
            if og is None:
                return {"W@GRAD": jnp.zeros_like(w)}
            idsq = ids.reshape(ids.shape[:-1]) if ids.shape and ids.shape[-1] == 1 else ids
            rows = idsq.reshape(-1).astype(jnp.int32)
            vals = og.reshape(-1, og.shape[-1])
            oh = jax.nn.one_hot(rows, w.shape[0], dtype=vals.dtype)
            dense = jax.lax.dot_general(oh, vals, (((0,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)
            return {"W@GRAD": dense.astype(w.dtype)}
        R._REGISTRY["lookup_table_grad"] = R.OpDef("lookup_table_grad", mm_grad, no_grad=True)
        mode = "full"
    from paddle_tpu import layers as L
    from paddle_tpu.models import transformer
    cfg = transformer.TransformerConfig(
        vocab_size=30522, hidden_size=768, num_layers=12, num_heads=12,
        ffn_size=3072, max_position=512, dropout=0.0, use_tp=False)
    batch, seq_len, iters = 128, 128, 20
    main_p, startup = pt.Program(), pt.Program()
    with pt.program_guard(main_p, startup):
        if mode == "nohead":
            src = L.data(name="src_ids", shape=[seq_len], dtype="int64")
            pos = L.data(name="pos_ids", shape=[seq_len], dtype="int64")
            enc = transformer.transformer_encoder(src, pos, cfg)
            avg_loss = L.mean(enc)
            opt = pt.contrib.mixed_precision.decorate(pt.optimizer.Adam(learning_rate=1e-4))
            opt.minimize(avg_loss)
        elif mode == "noattn":
            import paddle_tpu.models.transformer as T
            orig_attn = T.multi_head_attention
            T.multi_head_attention = lambda x, cfg2, attn_bias=None, name="attn": x
            try:
                avg_loss, _ = transformer.bert_pretrain(cfg, seq_len=seq_len)
            finally:
                T.multi_head_attention = orig_attn
            opt = pt.contrib.mixed_precision.decorate(pt.optimizer.Adam(learning_rate=1e-4))
            opt.minimize(avg_loss)
        else:
            avg_loss, _ = transformer.bert_pretrain(cfg, seq_len=seq_len)
            if mode == "full":
                opt = pt.contrib.mixed_precision.decorate(pt.optimizer.Adam(learning_rate=1e-4))
                opt.minimize(avg_loss)
            elif mode == "fp32":
                pt.optimizer.Adam(learning_rate=1e-4).minimize(avg_loss)
            elif mode == "nobwd":
                pass  # forward only
    from __graft_entry__ import _example_feed
    feed = _example_feed(cfg, batch, seq_len)
    if mode == "nohead":
        feed = {k: v for k, v in feed.items() if k in ("src_ids", "pos_ids")}
    exe = pt.Executor()
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        exe.run(main_p, feed=feed, fetch_list=[avg_loss])
        exe.run(main_p, feed=feed)
        if mode == "nobwd":
            # forward-only: no state write — serialize via the fetched loss
            exe.run(main_p, feed=feed, fetch_list=[avg_loss], return_numpy=False)
            t0 = time.perf_counter()
            for _ in range(iters):
                (last,) = exe.run(main_p, feed=feed, fetch_list=[avg_loss],
                                  return_numpy=False)
            np.asarray(last)
        else:
            drain_name = "encoder.pos_emb"
            v = pt.global_scope().find_var(drain_name)
            assert v is not None, drain_name
            np.asarray(v)
            t0 = time.perf_counter()
            for _ in range(iters):
                exe.run(main_p, feed=feed)
            np.asarray(pt.global_scope().find_var(drain_name))
        dt = (time.perf_counter() - t0) / iters
    print(json.dumps({"mode": mode, "ms_per_step": round(dt * 1e3, 2),
                      "tok_s": round(batch * seq_len / dt, 1)}))

if __name__ == "__main__":
    run(sys.argv[1])
