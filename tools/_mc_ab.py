"""Multichip scaling campaign + seeded collective-overlap A/B sweep.

The measured half of ROADMAP item 2: the MULTICHIP artifact stops being a
loss-parity dryrun and gains NUMBERS. One seeded BERT-shaped workload (same
global batch everywhere, so tokens/s compare) is trained under every
parallelism axis of an 8-device mesh —

    single  one device, the reference arm every efficiency divides by
    dp      fleet shard_map collective (GradAllReduce), three overlap arms:
              per-grad allreduce parked at the optimizer boundary (off),
              bucketed c_allreduce_coalesced at grad-readiness points (on),
              ZeRO-1 reduce-scatter/shard-update/allgather (zero1)
    tp      GSPMD tensor parallelism (use_tp weight annotations)
    sp      GSPMD sequence parallelism (use_sp activation annotations)
    pp      device-placed pipeline, 1F1B vs GPipe fill-drain arms, with the
            schedule's explicit bubble accounting attached

— each timed with the tools/_timing.py protocol (median-of-windows,
interference band) and checked for loss parity: the final parameters must
match the single-device trajectory (THE equivalence oracle; a fast wrong
collective must not win a row).

Efficiency convention: `speedup_vs_single` = tokens/s of the mesh arm over
tokens/s of the single-device arm at the SAME global batch. On real chips
that is the scaling win (ideal = n); on a host-platform virtual mesh every
"device" shares the same silicon, so ideal is ~1.0 and the number measures
pure partitioning/collective overhead — which is exactly what a CPU CI can
gate on (tools/gate.py --multichip). `efficiency` = speedup / n_devices is
the per-chip spelling for real accelerators.

    python tools/_mc_ab.py [--devices 8] [--iters 4] [--passes 2]
                           [--sweep 0,1,4] [--record DB.json] [--quick]

--sweep runs the dp arm per bucket size; --record writes the winner into a
PR 6 tuning DB as a swept `collective|mesh=..|payload=..` verdict (tie
keeps the analytic prior per _timing.ab_verdict).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools import _timing  # noqa: E402

SEED = 0


def _cfg(n_layers=4, use_tp=False, use_sp=False):
    from paddle_tpu.models import transformer

    return transformer.TransformerConfig(
        vocab_size=512, hidden_size=64, num_layers=n_layers, num_heads=4,
        ffn_size=128, max_position=128, dropout=0.0,
        use_tp=use_tp, use_sp=use_sp)


def _feed(cfg, batch, seq_len, seed=SEED):
    """Seeded feed, pre-narrowed to runtime dtypes (np_feed_dtype contract:
    no int64 reaches device_put, so the artifact tail stays free of jax's
    truncation warning)."""
    from paddle_tpu.core.types import np_feed_dtype

    rng = np.random.default_rng(seed)
    f = {
        "src_ids": rng.integers(0, cfg.vocab_size, (batch, seq_len)),
        "pos_ids": np.tile(np.arange(seq_len), (batch, 1)),
        "lm_label": rng.integers(0, cfg.vocab_size, (batch, seq_len)),
        "lm_weight": np.ones((batch, seq_len), np.float32),
    }
    return {k: np.asarray(v).astype(np_feed_dtype(np.asarray(v).dtype),
                                    copy=False) for k, v in f.items()}


def _build(cfg, seq_len, transpile=None, pipeline=None):
    """Fresh (main, startup, loss) with Adam, optionally transpiled."""
    import paddle_tpu as pt
    from paddle_tpu.models import transformer

    main, startup = pt.Program(), pt.Program()
    main.random_seed = 7
    startup.random_seed = 7
    with pt.program_guard(main, startup):
        with pt.unique_name.guard():
            loss, _ = transformer.bert_pretrain(cfg, seq_len=seq_len)
            if pipeline is not None:
                pipeline(main, startup, loss)
            else:
                pt.optimizer.Adam(learning_rate=1e-3).minimize(loss)
                if transpile is not None:
                    transpile(main, startup)
    return main, startup, loss


class _Arm:
    """One built+initialized training arm with its own program/scope, so
    competing arms can be timed in INTERLEAVED windows (A B A B ...): the
    shared box's one-sided interference drifts on second-to-minute scales,
    which sequential per-arm measurement aliases straight into the A/B
    margin (observed: the same pair swinging keep<->retire between runs)."""

    def __init__(self, build, target_of, feed):
        import paddle_tpu as pt

        self.main, self.startup, self.loss = build()
        self.scope = pt.Scope()
        self.exe = pt.Executor()
        with pt.scope_guard(self.scope):
            self.exe.run(self.startup)
            self.target = target_of(self.main)
        self.drain_name = self.main.all_parameters()[-1].name
        self.feed = feed
        self.windows: list[float] = []

    def _step(self):
        self.exe.run(self.target, feed=self.feed, scope=self.scope)

    def _drain(self):
        np.asarray(self.scope.find_var(self.drain_name))

    def warmup(self, n=2):
        # 2 un-timed steps: compile + the one-time XLA/thread-pool settling
        # a first window would otherwise alias into the band
        for _ in range(n):
            self._step()
        self._drain()

    def window(self, iters):
        """One timed window (the bench.py protocol: async-dispatched iters
        ended by a host drain read)."""
        import time

        t0 = time.perf_counter()
        for _ in range(iters):
            self._step()
        self._drain()
        w = (time.perf_counter() - t0) / iters
        self.windows.append(w)
        return w

    def stats(self):
        return {
            "median_s": _timing.median(self.windows),
            "min_s": float(min(self.windows)),
            "windows_s": [round(w, 6) for w in self.windows],
            "band": round(_timing.interference_band(self.windows), 4),
        }

    def finish(self, parity_steps=3):
        """`parity_steps` extra deterministic steps, then the parameter
        snapshot — comparable across arms that ran equal step counts."""
        losses = []
        for _ in range(parity_steps):
            (lv,) = self.exe.run(self.target, feed=self.feed,
                                 fetch_list=[self.loss.name],
                                 scope=self.scope)
            losses.append(float(np.asarray(lv).reshape(-1)[0]))
        params = {p.name: np.asarray(self.scope.find_var(p.name))
                  for p in self.main.all_parameters()}
        return params, losses


def _measure_interleaved(arms, iters, passes):
    """ABAB...-interleave the timed windows of every arm in `arms`."""
    for a in arms:
        a.warmup()
    for _ in range(passes):
        for a in arms:
            a.window(iters)
    return [a.stats() for a in arms]


def _run_arm(build, target_of, feed, iters, passes, parity_steps=3):
    """Single-arm convenience: build + warm + time; returns
    (stats, params, losses)."""
    arm = _Arm(build, target_of, feed)
    arm.warmup()
    for _ in range(passes):
        arm.window(iters)
    params, losses = arm.finish(parity_steps)
    return arm.stats(), params, losses


def _ab_row(tokens: int, off_stats: dict, on_stats: dict) -> dict:
    """One overlap_ab block entry. The verdict compares MIN-of-windows (the
    bench.py steady-state convention: interference on the shared box is
    one-sided, so best-window is the honest estimate and is far more stable
    across runs than the median of 2-3 interleaved windows) under the wider
    of the two arms' bands and the gate.py default."""
    band = max(_timing.DEFAULT_BAND, off_stats["band"], on_stats["band"])
    return {
        "off_tok_s": round(tokens / off_stats["min_s"], 1),
        "on_tok_s": round(tokens / on_stats["min_s"], 1),
        "band": round(band, 4),
        "verdict": _timing.ab_verdict(off_stats["min_s"], on_stats["min_s"],
                                      band),
    }


def _param_drift(ref: dict, got: dict) -> float:
    """max over params of relative L-inf distance — the loss-parity oracle
    spelled on the trained state (local shard losses aren't comparable
    across regimes; parameter trajectories are)."""
    worst = 0.0
    for n, rv in ref.items():
        gv = got.get(n)
        if gv is None or gv.shape != rv.shape:
            return float("inf")
        scale = max(1e-6, float(np.max(np.abs(rv))))
        worst = max(worst, float(np.max(np.abs(gv - rv))) / scale)
    return worst


def campaign(n_devices=8, iters=4, passes=2, sweep=None, record=None,
             quick=False):
    import jax

    import paddle_tpu as pt
    from paddle_tpu.parallel import make_mesh
    from paddle_tpu.parallel.collective import GradAllReduce
    from paddle_tpu.parallel.pipeline import bubble_fraction

    devs = jax.devices()
    if len(devs) < n_devices:
        raise RuntimeError(
            f"campaign needs {n_devices} devices, found {len(devs)} — "
            f"provision a virtual CPU mesh first (bench.py --multichip "
            f"re-execs with XLA_FLAGS=--xla_force_host_platform_device_count)")
    platform = devs[0].platform
    if quick:
        iters, passes = max(2, iters // 2), min(passes, 2)

    seq_len, batch, M = 64, 32, 8
    tokens = batch * seq_len
    feed = _feed(_cfg(), batch, seq_len)

    def tok_s(stats):
        return round(tokens / stats["median_s"], 1)

    out: dict = {
        "metric": "multichip_scaling",
        "unit": "ratio",
        "n_devices": n_devices,
        "platform": platform,
        "config": f"bert L4 h64 b{batch} s{seq_len} Adam seed{SEED}",
        "tokens_per_step": tokens,
    }

    from paddle_tpu import tuning as _tuning
    from paddle_tpu.tuning.learned import store as _learned_store

    def _rec(arm_name, stats, n_used):
        # raw windows -> the measurement store (the learned cost model's
        # dataset); gated by FLAGS_tuning_record like every tool
        if _learned_store.recording_enabled(tool=True):
            _learned_store.record(
                "ab.multichip",
                f"workload=bert_mc b={batch} s={seq_len} devs={n_used}",
                "-", _tuning.device_kind(), arm_name,
                windows_s=stats["windows_s"], median_s=stats["median_s"],
                min_s=stats["min_s"], band=stats["band"], source="ab")

    # -- single-device reference arm -----------------------------------------
    s_stats, s_params, s_losses = _run_arm(
        lambda: _build(_cfg(), seq_len), lambda m: m, feed, iters, passes)
    single_tok_s = tok_s(s_stats)
    out["single"] = {"tokens_per_sec": single_tok_s,
                     "band": s_stats["band"],
                     "windows_s": s_stats["windows_s"]}
    _rec("single", s_stats, 1)

    scaling: dict = {}
    overlap_ab: dict = {}
    parity: dict = {}

    def add_axis(name, stats, params, n_used, extra=None):
        row = {"tokens_per_sec": tok_s(stats),
               "n_devices": n_used,
               "speedup_vs_single": round(tok_s(stats) / single_tok_s, 4),
               "efficiency": round(tok_s(stats) / single_tok_s / n_used, 4),
               "band": stats["band"]}
        if extra:
            row.update(extra)
        scaling[name] = row
        parity[name] = round(_param_drift(s_params, params), 6)
        _rec(name, stats, n_used)

    # -- dp: fleet collective with the three overlap arms, interleaved -------
    mesh_dp = make_mesh({"dp": n_devices})
    cf = lambda m: pt.CompiledProgram(m).with_collective(mesh=mesh_dp)  # noqa: E731

    def dp_build(bucket_mb, zero1=False, out=None):
        t = GradAllReduce(bucket_mb=bucket_mb, zero1=zero1)
        if out is not None:
            out.append(t)

        def tr(main, startup):
            t.transpile(startup, main, rank=0, nranks=n_devices)

        return lambda: _build(_cfg(), seq_len, transpile=tr)

    on_ts, z_ts = [], []
    arm_off = _Arm(dp_build(0.0), cf, feed)
    arm_on = _Arm(dp_build(None, out=on_ts), cf, feed)  # tuner/flag resolved
    arm_z = _Arm(dp_build(None, zero1=True, out=z_ts), cf, feed)
    off_stats, on_stats, z_stats = _measure_interleaved(
        [arm_off, arm_on, arm_z], iters, passes)
    off_params, _ = arm_off.finish()
    on_params, _ = arm_on.finish()
    z_params, _ = arm_z.finish()
    on_t, z_t = on_ts[0], z_ts[0]
    add_axis("dp", on_stats, on_params, n_devices, extra={
        "bucket_mb": on_t.resolved_bucket_mb,
        "bucket_source": on_t.bucket_source,
        "buckets": len(on_t.last_buckets)})
    parity["dp_overlap_off"] = round(_param_drift(s_params, off_params), 6)
    parity["dp_zero1"] = round(_param_drift(s_params, z_params), 6)
    _rec("dp_overlap_off", off_stats, n_devices)
    _rec("dp_zero1", z_stats, n_devices)
    overlap_ab["dp_bucketed"] = _ab_row(tokens, off_stats, on_stats)
    overlap_ab["dp_zero1"] = dict(_ab_row(tokens, on_stats, z_stats),
                                  zero1_params=len(z_t.zero1_params))

    # -- optional bucket-size sweep (the tools/tune.py pattern) --------------
    if sweep:
        sweep_arms = [(float(mb), _Arm(dp_build(float(mb)), cf, feed))
                      for mb in sweep]
        sweep_stats = _measure_interleaved([a for _, a in sweep_arms],
                                           iters, passes)
        rows = {}
        best_mb, best_s = None, None
        for (mb, _), st in zip(sweep_arms, sweep_stats):
            rows[str(mb)] = {"tok_s": tok_s(st), "median_s": st["median_s"],
                             "band": st["band"]}
            if best_s is None or st["median_s"] < best_s:
                best_mb, best_s = mb, st["median_s"]
        out["bucket_sweep"] = {"arms_mb": rows, "winner_mb": best_mb}
        if record:
            _record_verdict(record, n_devices, on_t, rows, best_mb, off_stats)

    # -- tp / sp: GSPMD over a single model/sequence axis --------------------
    for axis, kw in (("tp", {"use_tp": True}), ("sp", {"use_sp": True})):
        mesh = make_mesh({axis: n_devices})
        stats, params, _ = _run_arm(
            lambda: _build(_cfg(**kw), seq_len),
            lambda m: pt.CompiledProgram(m).with_data_parallel(mesh=mesh),
            feed, iters, passes)
        add_axis(axis, stats, params, n_devices)

    # -- pp: device-placed pipeline, 1F1B vs fill-drain, interleaved ---------
    n_pp = min(4, n_devices)
    place = [devs[i] for i in range(n_pp)]

    def pp_build(schedule):
        from paddle_tpu.models import transformer

        def pipe(main, startup, loss):
            cuts = transformer.last_layer_outputs[:n_pp - 1]
            pt.optimizer.PipelineOptimizer(
                pt.optimizer.Adam(learning_rate=1e-3), cut_list=[cuts],
                place_list=place, num_microbatches=M,
                schedule=schedule).minimize(loss)

        return lambda: _build(_cfg(n_layers=n_pp), seq_len, pipeline=pipe)

    arm_fd = _Arm(pp_build("gpipe"), lambda m: m, feed)
    arm_fb = _Arm(pp_build("1f1b"), lambda m: m, feed)
    # single-device reference for pp parity/speedup matches its layer count
    arm_pps = _Arm(lambda: _build(_cfg(n_layers=n_pp), seq_len),
                   lambda m: m, feed)
    fd_stats, fb_stats, pps_stats = _measure_interleaved(
        [arm_fd, arm_fb, arm_pps], iters, passes)
    fb_params, _ = arm_fb.finish()
    pps_params, _ = arm_pps.finish()
    arm_fd.finish()  # equal step counts keep the dispatch ledger honest
    pp_single_tok_s = tok_s(pps_stats)
    bubble = dict(arm_fb.main._pipeline.last_bubble)
    scaling["pp"] = {
        "tokens_per_sec": tok_s(fb_stats),
        "n_devices": n_pp,
        "speedup_vs_single": round(tok_s(fb_stats) / pp_single_tok_s, 4),
        "efficiency": round(tok_s(fb_stats) / pp_single_tok_s / n_pp, 4),
        "band": fb_stats["band"],
        "schedule": "1f1b",
        "num_microbatches": M,
        "bubble_analytic_frac": round(bubble_fraction(n_pp, M), 4),
        "bubble": bubble,
    }
    parity["pp"] = round(_param_drift(pps_params, fb_params), 6)
    overlap_ab["pp_1f1b"] = _ab_row(tokens, fd_stats, fb_stats)

    out["scaling"] = scaling
    out["overlap_ab"] = overlap_ab
    out["parity"] = parity
    out["value"] = round(min(r["speedup_vs_single"]
                             for r in scaling.values()), 4)
    out["vs_baseline"] = out["value"]
    return out


def _record_verdict(db_path, n_devices, transpiler, rows, best_mb,
                    off_stats):
    """Persist the sweep's winner as a swept tuning-DB verdict — a tie
    against the per-grad baseline keeps the analytic prior (ab_verdict's
    contract: a coin flip must not overwrite a model with reasons)."""
    from paddle_tpu import tuning

    best = rows[str(best_mb)] if str(best_mb) in rows else None
    if best is None:
        return
    verdict = _timing.ab_verdict(
        off_stats["median_s"], best["median_s"],
        max(_timing.DEFAULT_BAND, off_stats["band"], best["band"]))
    if verdict != "keep":
        print(f"[mc_ab] sweep verdict '{verdict}' vs per-grad baseline — "
              f"not recording (analytic prior stands)")
        return
    from paddle_tpu.parallel.mesh import axes_desc

    payload = getattr(transpiler, "last_payload_bytes", 1 << 20)
    key = tuning.canonical_key(
        "collective", tuning.collective_key(axes_desc(n_devices), payload),
        "float32", tuning.device_kind())
    db = tuning.TuningDB(db_path if os.path.exists(db_path) else None)
    db.put(key, {"bucket_mb": float(best_mb)}, source="swept",
           measured={m: r["median_s"] for m, r in rows.items()},
           note="tools/_mc_ab.py bucket sweep")
    db.save(db_path)
    print(f"[mc_ab] recorded {key} -> bucket_mb={best_mb} into {db_path}")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--iters", type=int, default=4)
    ap.add_argument("--passes", type=int, default=2)
    ap.add_argument("--sweep", type=str, default="",
                    help="comma-separated bucket sizes in MB, e.g. 0,1,4")
    ap.add_argument("--record", type=str, default="",
                    help="tuning-DB path to persist the sweep winner into")
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args(argv)

    sweep = [float(x) for x in args.sweep.split(",") if x.strip()] or None
    out = campaign(n_devices=args.devices, iters=args.iters,
                   passes=args.passes, sweep=sweep,
                   record=args.record or None, quick=args.quick)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
