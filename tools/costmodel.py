"""Train / evaluate the learned cost model (paddle_tpu/tuning/learned/).

The offline half of ROADMAP item 3's measured story: the measurement store
(grown as a side effect by tools/tune.py sweeps, the A/B harnesses, bench
rounds and explore-mode probes) is the dataset; this CLI turns it into the
trained artifact the policy's learned tier consults, and re-scores a
committed artifact so gate.py --costmodel can hold the line in CI.

Subcommands:
    collect — run a small CPU-runnable conv sweep grid purely to GROW a
              dataset (the decisions go to a scratch DB and are discarded;
              the raw windows are the product). This is how the committed
              COSTMODEL_DATA_cpu.jsonl was produced.
    train   — fit the per-(op, device_kind) ridge groups (seeded holdout
              split, numpy closed form) and write the artifact atomically.
              Deterministic: same data + same seed = byte-identical file.
    eval    — re-score a model against a dataset's RECORDED holdout keys:
              learned vs analytic arm-ranking accuracy per group (the
              gate.py --costmodel floor).
    report  — dataset inventory: records / keys / arms per group.
    propose — confidence-gated serving-knob proposal for one traffic
              regime (the serving controller's ridge tier, ISSUE 20 —
              same `propose` call the live engine uses).

Usage:
    python tools/costmodel.py collect --data COSTMODEL_DATA_cpu.jsonl
    python tools/costmodel.py train --data COSTMODEL_DATA_cpu.jsonl \\
        --out COSTMODEL_cpu.json
    python tools/costmodel.py eval --model COSTMODEL_cpu.json \\
        --data COSTMODEL_DATA_cpu.jsonl
    python tools/costmodel.py report --data COSTMODEL_DATA_cpu.jsonl
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.tuning import learned  # noqa: E402

# the collect grid: CPU-runnable conv shapes spanning the decision surface
# the PR 5 analytic model reasons over — narrow-vs-full input channels
# (im2col K-folding territory), 1x1 vs 3x3 vs strided 7x7 kernels, both
# layouts — at spatial extents small enough that a full fwd+bwd sweep of
# every arm finishes in CI time. ~40 keys x 2 arms; the seeded holdout
# carves the eval set out of these.
def _collect_grid():
    shapes = []
    for hw in (16, 32):
        for cin in (3, 8, 32, 64, 128):
            for cout in (16, 64):
                for k in (1, 3):
                    pad = k // 2
                    shapes.append((
                        f"g{hw}_c{cin}x{cout}_k{k}", 4, hw, hw, cin, cout,
                        k, k, (1, 1), [(pad, pad), (pad, pad)], (1, 1)))
    # the strided-stem family (where igemm historically flips)
    for cin in (3, 12):
        shapes.append((f"stem_c{cin}", 4, 32, 32, cin, 64, 7, 7, (2, 2),
                       [(3, 3), (3, 3)], (1, 1)))
    return shapes


def cmd_collect(args) -> int:
    from paddle_tpu import flags as pt_flags
    from paddle_tpu import tuning
    from tools import tune

    scratch_db = args.db or os.path.join(
        tempfile.mkdtemp(prefix="costmodel_collect_"), "scratch_db.json")
    pt_flags.set_flags({"tuning_db": scratch_db,
                        "tuning_measurements": args.data,
                        "tuning_record": "on"})
    grid = _collect_grid()
    if args.limit:
        grid = grid[:args.limit]
    for fmt in ("NHWC", "NCHW") if args.both_layouts else ("NHWC",):
        db = tuning.TuningDB(scratch_db)
        tune.sweep_conv(db, grid, args.dtype, args.iters, args.passes,
                        args.band, fmt=fmt)
    n = sum(1 for _ in learned.iter_records(args.data))
    print(json.dumps({"collect": "done", "data": os.path.abspath(args.data),
                      "records": n, "scratch_db": scratch_db}), flush=True)
    return 0


def cmd_train(args) -> int:
    recs = list(learned.iter_records(args.data))
    if not recs:
        print(json.dumps({"error": f"no usable records in {args.data!r}"}))
        return 1
    model = learned.train_model(recs, seed=args.seed,
                                holdout_frac=args.holdout, ridge=args.ridge)
    if not model["groups"]:
        print(json.dumps({"error": "no group had enough measured keys "
                                   "(need >= 6 keys with >= 2 arms each)"}))
        return 1
    learned.save_model(model, args.out)
    print(json.dumps({
        "trained": os.path.abspath(args.out),
        "records": len(recs),
        "groups": {g: {"n_train_keys": grp["n_train_keys"],
                       "n_holdout_keys": len(grp["holdout_keys"]),
                       "arms": sorted(grp["arms"]),
                       "holdout": grp["holdout"]}
                   for g, grp in model["groups"].items()},
    }, sort_keys=True), flush=True)
    return 0


def cmd_eval(args) -> int:
    """Re-score the model on the dataset's recorded holdout keys and print
    the learned-vs-analytic comparison gate.py --costmodel enforces.
    Exit 1 only on unusable inputs — the pass/fail policy lives in the
    gate, not here."""
    try:
        model = learned.load_model(args.model)
    except ValueError as e:
        print(json.dumps({"error": f"model {args.model!r}: {e}"}))
        return 1
    if model is None:
        print(json.dumps({"error": f"model {args.model!r}: missing"}))
        return 1
    recs = list(learned.iter_records(args.data))
    ev = learned.eval_model(model, recs)
    out = {"model": os.path.abspath(args.model),
           "data": os.path.abspath(args.data),
           "records": len(recs), "groups": {}}
    for g, r in ev["groups"].items():
        beats = (r["rank_acc"] is not None
                 and r["analytic_rank_acc"] is not None
                 and r["rank_acc"] >= r["analytic_rank_acc"])
        out["groups"][g] = {**r, "learned_beats_analytic": beats}
    print(json.dumps(out, sort_keys=True), flush=True)
    return 0


def cmd_propose(args) -> int:
    """Confidence-gated serving-knob proposal for one traffic regime —
    the CLI face of the serving controller's ridge tier (ISSUE 20).
    Operators, the control gate, and the live engine re-enter the policy
    through the same `propose` call; the regime is given in the store's
    own bucketed spelling (see serving/control/regime.py)."""
    from paddle_tpu import flags as pt_flags
    from paddle_tpu.serving import control as sv_control

    try:
        model = learned.load_model(args.model)
    except ValueError as e:
        print(json.dumps({"error": f"model {args.model!r}: {e}"}))
        return 1
    if model is None:
        print(json.dumps({"error": f"model {args.model!r}: missing"}))
        return 1
    sig = sv_control.parse_regime(args.regime)
    if sig is None:
        print(json.dumps(
            {"error": f"not a regime spelling: {args.regime!r} (fields: "
                      f"{' '.join(sv_control.REGIME_FIELDS)}, e.g. "
                      f"'rate=80 p50=32 p95=32 out=16 hit=95 occ=70 q=8 "
                      f"hr=50')"}))
        return 1
    # the policy's off-mode short circuit is a runtime safety, not a CLI
    # one: an explicit `propose` invocation always wants the model's view
    old = pt_flags.get_flag("serve_control_mode")
    pt_flags.set_flags({"serve_control_mode": "shadow"})
    try:
        proposal, info = sv_control.propose(sig, model=model,
                                            dev=args.device or None)
    finally:
        pt_flags.set_flags({"serve_control_mode": old})
    print(json.dumps({"regime": sv_control.regime_key(sig),
                      "proposal": sv_control.knob_key(proposal),
                      "knobs": proposal, "info": info}, sort_keys=True),
          flush=True)
    return 0


def cmd_report(args) -> int:
    groups: dict = {}
    n = 0
    for rec in learned.iter_records(args.data):
        n += 1
        g = groups.setdefault(f"{rec['op']}|{rec['device_kind']}", {
            "records": 0, "keys": set(), "arms": set(), "sources": set()})
        g["records"] += 1
        g["keys"].add((rec["shape_key"], rec["dtype"]))
        g["arms"].add(rec["arm"])
        g["sources"].add(rec.get("source", "?"))
    print(json.dumps({
        "data": os.path.abspath(args.data),
        "records": n,
        "groups": {g: {"records": v["records"], "keys": len(v["keys"]),
                       "arms": sorted(v["arms"]),
                       "sources": sorted(v["sources"])}
                   for g, v in sorted(groups.items())},
    }, sort_keys=True), flush=True)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    pc = sub.add_parser("collect", help="grow a dataset from a conv grid")
    pc.add_argument("--data", required=True)
    pc.add_argument("--db", default="",
                    help="scratch tuning DB path (default: temp dir)")
    pc.add_argument("--dtype", default="float32")
    pc.add_argument("--iters", type=int, default=3)
    pc.add_argument("--passes", type=int, default=2)
    pc.add_argument("--band", type=float, default=0.05)
    pc.add_argument("--limit", type=int, default=0,
                    help="truncate the grid (smoke runs)")
    pc.add_argument("--both-layouts", action="store_true",
                    help="sweep NCHW in addition to NHWC")
    pc.set_defaults(fn=cmd_collect)

    pt = sub.add_parser("train", help="fit and write the model artifact")
    pt.add_argument("--data", required=True)
    pt.add_argument("--out", required=True)
    pt.add_argument("--seed", type=int, default=0)
    pt.add_argument("--holdout", type=float, default=0.25)
    pt.add_argument("--ridge", type=float, default=1.0)
    pt.set_defaults(fn=cmd_train)

    pe = sub.add_parser("eval", help="re-score a model on a dataset")
    pe.add_argument("--model", required=True)
    pe.add_argument("--data", required=True)
    pe.set_defaults(fn=cmd_eval)

    pp = sub.add_parser("propose",
                        help="serving-knob proposal for one traffic regime")
    pp.add_argument("--model", required=True)
    pp.add_argument("--regime", required=True,
                    help="bucketed regime spelling, e.g. 'rate=80 p50=32 "
                         "p95=32 out=16 hit=95 occ=70 q=8 hr=50'")
    pp.add_argument("--device", default="",
                    help="device kind group to consult (default: this host)")
    pp.set_defaults(fn=cmd_propose)

    pr = sub.add_parser("report", help="dataset inventory")
    pr.add_argument("--data", required=True)
    pr.set_defaults(fn=cmd_report)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
