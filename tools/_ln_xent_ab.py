"""Pallas build-plan candidates layer_norm + softmax_xent: measure XLA
against the HBM-bytes roofline at BERT shapes (VERDICT r4 #8, the
conv-chain keep-or-retire methodology).

Both ops are bandwidth-bound at these shapes, so the decision rule is:
if XLA already sustains >=~85% of the bytes roofline, the maximum Pallas
headroom (<=1.2x on the op, <<1% end-to-end) cannot justify a kernel —
retire with data. Otherwise build it.

Chained in-graph (dispatch amortized), fwd+bwd through value_and_grad.
Run: python tools/_ln_xent_ab.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax
import jax.numpy as jnp
import numpy as np

N, Hdim = 128 * 128, 768     # BERT-base b128 s128 token rows
V = 30522                    # BERT vocab (the lm head xent)
DT = jnp.bfloat16
_drain = jax.jit(lambda v: v.reshape(-1)[0])
rng = np.random.default_rng(0)


def bench(fn, args, n_chain, n_rep, tag, train_bytes, extra=""):
    @jax.jit
    def run(*a):
        params, x = a[0], a[1]
        acc = 0.0
        for i in range(n_chain):
            loss, g = jax.value_and_grad(fn)(params, x)
            acc = acc + loss
            x = x + (acc * 1e-12).astype(x.dtype)
            params = jax.tree.map(
                lambda p, gg: p - (1e-9 * gg).astype(p.dtype), params, g)
        return acc, params

    acc, p = run(*args)
    np.asarray(_drain(acc))
    best = np.inf
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(n_rep):
            acc, p = run(*args)
        np.asarray(_drain(acc))
        best = min(best, (time.perf_counter() - t0) / (n_rep * n_chain))
    from _rn_roofline import measure_bw

    bw = measure_bw()
    roof = train_bytes / (bw * 1e9)
    print(f"{tag}: {best*1e3:.3f} ms/op-train, roofline {roof*1e3:.3f} ms "
          f"@ {bw:.0f} GB/s -> XLA at {roof/best*100:.0f}% of roofline"
          f"{extra}", flush=True)
    return best, roof


def main():
    # --- layer_norm fwd+bwd ------------------------------------------------
    x = jnp.asarray(rng.standard_normal((N, Hdim), np.float32), DT)
    g = jnp.ones((Hdim,), jnp.float32)
    b = jnp.zeros((Hdim,), jnp.float32)

    def ln_loss(params, x):
        gg, bb = params
        xf = x.astype(jnp.float32)
        m = xf.mean(-1, keepdims=True)
        v = jnp.square(xf - m).mean(-1, keepdims=True)
        y = ((xf - m) / jnp.sqrt(v + 1e-12) * gg + bb).astype(x.dtype)
        return jnp.sum(y.astype(jnp.float32) * 1e-6)

    # train bytes: fwd read x + write y; bwd read dy-chain is fused into
    # the scalar-sum cotangent (free), re-read x, write dx => ~4 passes bf16
    ln_bytes = 4 * N * Hdim * 2
    bench(ln_loss, ((g, b), x), 20, 5, f"layer_norm [{N},{Hdim}]", ln_bytes)

    # --- softmax_with_cross_entropy over the BERT vocab --------------------
    logits = jnp.asarray(rng.standard_normal((N, V), np.float32) * 0.1, DT)
    labels = jnp.asarray(rng.integers(0, V, N).astype(np.int32))

    def xent_loss(params, logits):
        (scale,) = params
        lg = logits.astype(jnp.float32) * scale
        lsm = jax.nn.log_softmax(lg, axis=-1)
        return -jnp.mean(jnp.take_along_axis(lsm, labels[:, None], axis=1))

    # train bytes: fwd read logits (+max/denominator passes fused), bwd
    # write dlogits; ~3 passes of the [N, V] bf16 tensor
    xent_bytes = 3 * N * V * 2
    bench(xent_loss, ((jnp.float32(1.0),), logits), 4, 5,
          f"softmax_xent [{N},{V}]", xent_bytes)


if __name__ == "__main__":
    main()


def variant_xent():
    """Gather-then-reduce xent: loss = -(x[label] - max - logsumexp) — the
    [N, V] log-softmax never materializes; bwd is one softmax read+write."""
    logits = jnp.asarray(rng.standard_normal((N, V), np.float32) * 0.1, DT)
    labels = jnp.asarray(rng.integers(0, V, N).astype(np.int32))

    def xent2(params, logits):
        (scale,) = params
        lg = logits.astype(jnp.float32) * scale
        m = jnp.max(lg, axis=-1, keepdims=True)
        lse = jnp.log(jnp.sum(jnp.exp(lg - m), axis=-1, keepdims=True))
        picked = jnp.take_along_axis(lg, labels[:, None], axis=1)
        return -jnp.mean(picked - m - lse)

    xent_bytes = 3 * N * V * 2
    bench(xent2, ((jnp.float32(1.0),), logits), 4, 5,
          f"softmax_xent gather-form [{N},{V}]", xent_bytes)


def variant_ln():
    """LN with bf16 output and fp32 stats only as scalars-per-row; same
    math, but nudge XLA to keep the normalized tensor bf16."""
    x = jnp.asarray(rng.standard_normal((N, Hdim), np.float32), DT)
    g = jnp.ones((Hdim,), jnp.float32)
    b = jnp.zeros((Hdim,), jnp.float32)

    def ln2(params, x):
        gg, bb = params
        xf = x.astype(jnp.float32)
        m = xf.mean(-1, keepdims=True)
        v = xf.var(-1, keepdims=True)
        inv = jax.lax.rsqrt(v + 1e-12)
        y = (xf * inv - m * inv) * gg + bb
        return jnp.sum(y.astype(DT).astype(jnp.float32) * 1e-6)

    bench(ln2, ((g, b), x), 20, 5, f"layer_norm rsqrt-form [{N},{Hdim}]",
          4 * N * Hdim * 2)

