"""Multi-process bootstrap: PjRt coordination instead of NCCL-ID rendezvous.

TPU-native replacement for the reference's multi-process plumbing:
  * gen_nccl_id RPC bootstrap
    (/root/reference/paddle/fluid/operators/distributed_ops/gen_nccl_id_op.cc:76)
  * the launcher's env contract
    (/root/reference/python/paddle/distributed/launch.py:132,243)
  * dygraph's prepare_context / Env
    (/root/reference/python/paddle/fluid/dygraph/parallel.py:37)

Instead of broadcasting an ncclUniqueId over raw sockets, every process joins
the PjRt coordination service (`jax.distributed.initialize`). After that, XLA
sees ONE global device topology spanning all hosts; `jax.sharding.Mesh` built
over `jax.devices()` covers the pod, and collectives ride ICI within a host
slice and DCN across hosts — no per-link communicator objects exist anywhere.

CPU backend note (tests / TestDistBase pattern): cross-process CPU collectives
need the gloo implementation (`jax_cpu_collectives_implementation=gloo`), and
this session's sitecustomize force-registers a TPU plugin, so `backend="cpu"`
pins `jax_platforms` via jax.config (env vars alone don't win).
"""
from __future__ import annotations

import os

__all__ = ["ParallelEnv", "init_parallel_env"]

_initialized = False


class ParallelEnv:
    """Rank/world-size view of the launcher's env contract (reference
    dygraph/parallel.py Env: nranks/local_rank/dev_id/endpoints)."""

    def __init__(self):
        self.nranks = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self.local_rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self.current_endpoint = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self.trainer_endpoints = eps.split(",") if eps else []

    @property
    def rank(self):
        return self.local_rank

    @property
    def world_size(self):
        return self.nranks


def init_parallel_env(
    coordinator: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    backend: str | None = None,
    local_device_count: int | None = None,
) -> ParallelEnv:
    """Join the job's coordination service and initialize the global topology.

    Reads the `python -m paddle_tpu.distributed.launch` env contract when
    arguments are omitted. Must run before any JAX computation so the backend
    initializes with the distributed client (the PjRt analogue of "call
    prepare_context before the first forward", reference parallel.py:51).
    """
    global _initialized
    env = os.environ
    coordinator = coordinator or env.get("PADDLE_COORDINATOR", "")
    if num_processes is None:
        num_processes = int(env.get("PADDLE_TRAINERS_NUM", "1"))
    if process_id is None:
        process_id = int(env.get("PADDLE_TRAINER_ID", "0"))
    backend = backend or env.get("PADDLE_DIST_BACKEND") or None
    if local_device_count is None and env.get("PADDLE_LOCAL_DEVICES"):
        local_device_count = int(env["PADDLE_LOCAL_DEVICES"])

    if local_device_count:
        # must land in XLA_FLAGS before the first backend initialization
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={local_device_count}"
        )

    import jax

    if backend:
        jax.config.update("jax_platforms", backend)
        if backend == "cpu" and num_processes > 1:
            # gloo needs the distributed client wired into backend creation;
            # jaxlib 0.4.37's make_gloo_tcp_collectives REQUIRES a real
            # DistributedRuntimeClient (passing None aborts backend init), so
            # a single-process run must stay on the default implementation —
            # it has no cross-process collectives to run anyway
            jax.config.update("jax_cpu_collectives_implementation", "gloo")

    if num_processes > 1 and not _initialized:
        if not coordinator:
            raise ValueError(
                "init_parallel_env: no coordinator address — pass one or run "
                "under `python -m paddle_tpu.distributed.launch`"
            )
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
        _initialized = True
    return ParallelEnv()
