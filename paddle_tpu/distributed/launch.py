"""`python -m paddle_tpu.distributed.launch` — multi-process job launcher.

TPU-native re-design of the reference launcher
(/root/reference/python/paddle/distributed/launch.py: start_procs:132,
launch:243): same job shape — spawn one training process per device group,
wire the rank/endpoint env contract, multiplex logs, propagate failures — but
rendezvous is the PjRt coordination service (see distributed/parallel.py), not
a trainer-0 socket broadcast of an ncclUniqueId.

Usage:
    python -m paddle_tpu.distributed.launch --nproc_per_node=2 \
        [--backend=cpu --local_devices_per_proc=1] \
        [--log_dir=log] train.py --your --args

Each worker process receives:
    PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM   rank / world size
    PADDLE_COORDINATOR                        coordination service address
    PADDLE_TRAINER_ENDPOINTS / PADDLE_CURRENT_ENDPOINT (fleet role makers)
    PADDLE_DIST_BACKEND / PADDLE_LOCAL_DEVICES (optional platform pinning)
and calls `paddle_tpu.distributed.init_parallel_env()` before building its
program (fleet.init with PaddleCloudRoleMaker picks up the same envs).
"""
from __future__ import annotations

import argparse
import os
import secrets
import signal
import socket
import subprocess
import sys
import time

__all__ = ["launch", "main"]


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _parse_args(argv):
    p = argparse.ArgumentParser(
        prog="paddle_tpu.distributed.launch",
        description="launch a multi-process distributed job",
    )
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes to spawn on this node")
    p.add_argument("--node_ip", default="127.0.0.1",
                   help="this node's IP (reference launch.py --node_ip)")
    p.add_argument("--coordinator", default=None,
                   help="coordination-service address host:port "
                        "(default: node_ip with a free port, single-node)")
    p.add_argument("--started_port", type=int, default=None,
                   help="base port for PADDLE_TRAINER_ENDPOINTS")
    p.add_argument("--backend", default=None,
                   help="pin jax platform in workers (e.g. 'cpu' for the "
                        "TestDistBase localhost pattern)")
    p.add_argument("--local_devices_per_proc", type=int, default=None,
                   help="virtual host devices per process (CPU backend)")
    p.add_argument("--log_dir", default=None,
                   help="write per-worker logs to LOG_DIR/workerlog.N")
    p.add_argument("--server_num", type=int, default=0,
                   help="parameter-server mode: spawn this many pservers "
                        "first (reference launch_ps.py --server_num)")
    p.add_argument("--worker_num", type=int, default=0,
                   help="parameter-server mode: trainer count "
                        "(reference launch_ps.py --worker_num)")
    p.add_argument("--servers", default=None,
                   help="explicit pserver endpoint list ip:port,ip:port "
                        "(default: node_ip with free ports)")
    p.add_argument("training_script", help="the script to run")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def launch_ps(args) -> int:
    """Parameter-server cluster launcher (reference
    python/paddle/distributed/launch_ps.py:55-82 start_procs): spawn
    --server_num pservers, then --worker_num trainers, all running the SAME
    training script; roles arrive via TRAINING_ROLE/PADDLE_* envs that the
    fleet RoleMakers (incubate/fleet/base.py PaddleCloudRoleMaker) read.
    Returns when every trainer exits (pservers are then terminated, matching
    the reference's procs[i].proc.terminate() for servers)."""
    n_servers = args.server_num
    n_workers = args.worker_num or 1
    if args.servers:
        server_eps = [e for e in args.servers.split(",") if e]
        if args.server_num and len(server_eps) != args.server_num:
            raise ValueError(
                f"--servers lists {len(server_eps)} endpoints but "
                f"--server_num={args.server_num}; drop one or make them "
                "agree (one local pserver process is spawned per endpoint)")
        loopback = {"127.0.0.1", "localhost", "::1"}
        remote = [ep for ep in server_eps
                  if ep.rsplit(":", 1)[0] not in loopback]
        if remote and not os.environ.get("PADDLE_PS_AUTHKEY"):
            # the per-launch generated secret only reaches THIS node's
            # children; processes launched on the other nodes would hold a
            # different key and every cross-node connect would die with an
            # opaque multiprocessing AuthenticationError
            raise RuntimeError(
                f"--servers includes non-local endpoint(s) {remote} but "
                "PADDLE_PS_AUTHKEY is not set. Cross-node pserver RPC "
                "authenticates with one shared secret: export the same "
                "PADDLE_PS_AUTHKEY (e.g. `export PADDLE_PS_AUTHKEY=$(openssl "
                "rand -hex 16)`) on every node before launching")
    else:
        server_eps = [f"{args.node_ip}:{_free_port()}"
                      for _ in range(n_servers)]
    base_port = args.started_port or _free_port()
    trainer_eps = [f"{args.node_ip}:{base_port + i}" for i in range(n_workers)]
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
    ps_authkey = os.environ.get("PADDLE_PS_AUTHKEY") or secrets.token_hex(16)

    common = {
        "PADDLE_PS_AUTHKEY": ps_authkey,
        "PADDLE_PSERVERS_IP_PORT_LIST": ",".join(server_eps),
        "PADDLE_PSERVER_ENDPOINTS": ",".join(server_eps),
        "PADDLE_TRAINERS_NUM": str(n_workers),
        "PADDLE_TRAINER_ENDPOINTS": ",".join(trainer_eps),
    }
    if args.backend:
        common["PADDLE_DIST_BACKEND"] = args.backend

    def _spawn(role_env, tag):
        env = dict(os.environ)
        env.update(common)
        env.update(role_env)
        out = None
        if args.log_dir:
            out = open(os.path.join(args.log_dir, f"{tag}.log"), "w")
            logs.append(out)
        cmd = [sys.executable, "-u", args.training_script,
               *args.training_script_args]
        return subprocess.Popen(cmd, env=env, stdout=out, stderr=out)

    logs: list = []
    servers = [
        _spawn({"TRAINING_ROLE": "PSERVER", "PADDLE_PSERVER_ID": str(i),
                "PADDLE_CURRENT_ENDPOINT": ep, "PADDLE_PORT": ep.rsplit(":", 1)[1],
                "POD_IP": ep.rsplit(":", 1)[0]}, f"serverlog.{i}")
        for i, ep in enumerate(server_eps)
    ]
    workers = [
        _spawn({"TRAINING_ROLE": "TRAINER", "PADDLE_TRAINER_ID": str(i),
                "PADDLE_CURRENT_ENDPOINT": trainer_eps[i]}, f"workerlog.{i}")
        for i in range(n_workers)
    ]

    rc = 0
    try:
        # poll loop (same discipline as the collective launch() below): one
        # crashed trainer must tear the whole job down — a sequential wait()
        # would hang forever on the surviving trainers' barriers
        alive = set(range(n_workers))
        while alive:
            for i in list(alive):
                r = workers[i].poll()
                if r is None:
                    continue
                alive.discard(i)
                if r != 0:
                    rc = r
                    for w in workers:
                        if w.poll() is None:
                            w.send_signal(signal.SIGTERM)
                    alive.clear()
            time.sleep(0.1)
        # trainers done (or failed): tear the servers down
        stop = list(servers) + ([w for w in workers if w.poll() is None]
                                if rc else [])
        for s in stop:
            if s.poll() is None:
                s.send_signal(signal.SIGTERM)
        deadline = time.time() + 10
        for s in stop:
            try:
                s.wait(max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                s.kill()
    finally:
        for f in logs:
            f.close()
    return rc


def launch(args) -> int:
    if args.server_num or args.worker_num:
        return launch_ps(args)
    n = args.nproc_per_node
    coordinator = args.coordinator or f"{args.node_ip}:{_free_port()}"
    base_port = args.started_port or _free_port()
    endpoints = [f"{args.node_ip}:{base_port + i}" for i in range(n)]

    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)

    # one random pserver-RPC auth secret per launch, shared by every rank
    ps_authkey = os.environ.get("PADDLE_PS_AUTHKEY") or secrets.token_hex(16)

    procs, logs = [], []
    for rank in range(n):
        env = dict(os.environ)
        env.update({
            "PADDLE_PS_AUTHKEY": ps_authkey,
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(n),
            "PADDLE_COORDINATOR": coordinator,
            "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
            "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
            "TRAINING_ROLE": "TRAINER",
        })
        if args.backend:
            env["PADDLE_DIST_BACKEND"] = args.backend
        if args.local_devices_per_proc:
            env["PADDLE_LOCAL_DEVICES"] = str(args.local_devices_per_proc)
        cmd = [sys.executable, "-u", args.training_script,
               *args.training_script_args]
        out = None
        if args.log_dir:
            out = open(os.path.join(args.log_dir, f"workerlog.{rank}"), "w")
            logs.append(out)
        procs.append(subprocess.Popen(cmd, env=env, stdout=out, stderr=out))

    rc = 0
    try:
        alive = set(range(n))
        while alive:
            for i in list(alive):
                r = procs[i].poll()
                if r is None:
                    continue
                alive.discard(i)
                if r != 0:
                    rc = r
                    # one worker died: the pod step can never complete — tear
                    # the job down (reference launch.py terminate_procs)
                    for j in alive:
                        procs[j].send_signal(signal.SIGTERM)
                    deadline = time.time() + 10
                    for j in alive:
                        try:
                            procs[j].wait(max(0.1, deadline - time.time()))
                        except subprocess.TimeoutExpired:
                            procs[j].kill()
                    alive.clear()
            time.sleep(0.1)
    finally:
        for f in logs:
            f.close()
    return rc


def main(argv=None):
    args = _parse_args(argv if argv is not None else sys.argv[1:])
    sys.exit(launch(args))


if __name__ == "__main__":
    main()
