"""Distributed runtime: variable RPC (pserver path) + multi-process launch.

TPU-native replacement for the reference's distributed stack
(/root/reference/paddle/fluid/operators/distributed/ gRPC/BRPC runtime,
distributed_ops/listen_and_serv_op.cc): dense math runs on chips; the sparse/
parameter-server path rides a host TCP variable service over DCN.
"""
from . import ps_rpc  # noqa: F401
from .parallel import ParallelEnv, init_parallel_env  # noqa: F401
