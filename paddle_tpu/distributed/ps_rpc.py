"""Parameter-server variable RPC: client + server runtime.

TPU-native replacement for the reference RPC stack:
  * `RPCClient` contract (rpc_client.h:33: AsyncSendVar :37, AsyncGetVar :43,
    barriers :68-74) -> `PSClient` (send_var/get_var/send_barrier/
    fetch_barrier/send_complete)
  * `listen_and_serv` event loop (distributed_ops/listen_and_serv_op.cc) +
    RequestSend/Get handlers (request_handler_impl.cc) -> `PServerRuntime`
  * gRPC ByteBuffer serde (grpc/grpc_serde.cc) -> a length-prefixed raw
    tensor frame over `multiprocessing.connection` byte pipes: a small JSON
    meta header (op, name, trainer, dtype/shape table) followed by the raw
    tensor bytes, decoded with zero-copy np.frombuffer views. No pickle on
    the wire — version-stable and copy-light, the same serde discipline as
    the reference's zero-copy gRPC ByteBuffer path. The connection-level
    HMAC challenge (authkey) is kept for transport auth.

Sync semantics (sync_mode=True): the server buffers each trainer's gradient
per variable; when every trainer has posted its send_barrier, gradients are
averaged, the per-block optimize programs run once, the global step++, and
only then are the barrier replies released — so a subsequent get_var always
observes the post-update parameters (the reference's send_barrier/
fetch_barrier protocol collapsed into one blocking round).

Liveness (the distributed hang defense):
  * every RPC reply wait and the connect loop are bounded by
    `FLAGS_rpc_deadline` (ms, reference semantics) — no hardcoded timeouts;
  * each trainer runs a heartbeat daemon thread (`_HeartbeatSender`, its own
    connections so a blocking barrier can't delay a beat) that refreshes the
    server's per-trainer `last_seen` clock;
  * a server-side monitor thread watches stalled sync rounds: a trainer that
    is holding the barrier hostage with no liveness signal for the deadline
    is EVICTED — its half-round gradients are dropped, the barrier count
    renormalizes to the survivors, the round runs, and the eviction is
    logged (`PServerRuntime.liveness_log`) — instead of blocking everyone;
  * an evicted trainer that comes back (an explicit `rejoin` RPC from a
    restarted process resuming at CheckpointManager.latest_step, or simply
    its next send/barrier if it was a false positive) is re-admitted at the
    next barrier accounting, and the server grants evicted trainers a
    rejoin-grace window before it will shut down without them."""
from __future__ import annotations

import logging
import threading
import time
from multiprocessing.connection import Client, Listener
from typing import Any

import numpy as np

logger = logging.getLogger("paddle_tpu.distributed.ps_rpc")


def rpc_deadline_s() -> float:
    """`FLAGS_rpc_deadline` (milliseconds, reference
    fluid/__init__.py:65-71 semantics) as seconds; floor 1ms."""
    from .. import flags

    try:
        ms = float(flags.get_flag("rpc_deadline"))
    except KeyError:  # flags module mid-import
        ms = 180000.0
    return max(ms, 1.0) / 1000.0


def heartbeat_timeout_s() -> float:
    """Server-side liveness deadline: `FLAGS_heartbeat_timeout_ms`, falling
    back to the RPC deadline when unset (0)."""
    from .. import flags

    try:
        ms = float(flags.get_flag("heartbeat_timeout_ms"))
    except KeyError:
        ms = 0.0
    return ms / 1000.0 if ms > 0 else rpc_deadline_s()

def _authkey() -> bytes:
    """Connection auth secret. The launcher exports PADDLE_PS_AUTHKEY (one
    random value per launch) so all ranks share it; a hand-run cluster must
    export it itself. The fallback keeps single-process tests working but is
    NOT a security boundary."""
    import os

    return os.environ.get("PADDLE_PS_AUTHKEY", "paddle_tpu_ps").encode()


def _parse_ep(ep: str):
    host, port = ep.rsplit(":", 1)
    return (host, int(port))


# -- wire frame: JSON meta + raw tensor blocks --------------------------------
# frame := u32(meta_len) meta_json tensor_bytes*
# meta["_t"] = [[dtype_str, shape], ...] describes the appended raw blocks in
# order; everything else in meta is small scalars/strings. send_bytes adds the
# outer length prefix. SelectedRows travel as two blocks (rows, values) plus
# a "height" field; replies are {"s": "ok"|"err", ...} frames.

import json as _json
import struct as _struct


def _pack(meta: dict, tensors=()) -> bytes:
    tensors = [np.asarray(t) for t in tensors]
    meta = dict(meta)
    # shapes recorded BEFORE ascontiguousarray (it promotes 0-d to 1-d)
    meta["_t"] = [[t.dtype.str, list(t.shape)] for t in tensors]
    mb = _json.dumps(meta, separators=(",", ":")).encode()
    parts = [_struct.pack("<I", len(mb)), mb]
    parts += [memoryview(np.ascontiguousarray(t)).cast("B") for t in tensors]
    return b"".join(parts)


def _unpack(buf):
    (mlen,) = _struct.unpack_from("<I", buf, 0)
    meta = _json.loads(bytes(buf[4:4 + mlen]).decode())
    off = 4 + mlen
    tensors = []
    for dtype_str, shape in meta.pop("_t", []):
        dt = np.dtype(dtype_str)
        n = int(np.prod(shape)) if shape else 1
        t = np.frombuffer(buf, dt, count=n, offset=off).reshape(tuple(shape))
        off += n * dt.itemsize
        tensors.append(t)
    return meta, tensors


def _reply_ok(conn, tensors=(), **fields):
    conn.send_bytes(_pack({"s": "ok", **fields}, tensors))


def _reply_err(conn, msg: str):
    conn.send_bytes(_pack({"s": "err", "msg": msg}))


# -- wire contract for row-sliced variables ----------------------------------
# One definition of the "name.block{j}" section protocol shared by the send/
# recv ops AND the async Communicator — the slicing math must never drift
# between the three users (reference parameter_send.cc / parameter_recv.cc).


def iter_sections(name: str, arr, epmap, sections):
    """The one definition of the row-split wire protocol: yields
    (endpoint, wire_name, row_slice). EMPTY sections = unsliced whole var
    under its bare name; NON-empty (even a single block) = the server
    registered "name.block{j}" wire names."""
    if not sections:
        yield epmap[0], name, arr
        return
    offs = np.cumsum([0] + list(sections[:-1]))
    for j, (ep, off, rows) in enumerate(zip(epmap, offs, sections)):
        yield ep, f"{name}.block{j}", arr[off:off + rows]


def _guard_drops_send(name: str, arr) -> bool:
    """Trainer-side numeric hygiene (FLAGS_guard_numerics, resilience/
    guardrails.py): a non-finite payload is dropped BEFORE the wire so the
    pserver never averages poison into shared parameters. The sync server
    renormalizes the round to the trainers that posted (_run_round), the
    same stance as PR 3's dead-trainer eviction."""
    from .. import flags, profiler

    if not flags.get_flag("guard_numerics"):
        return False
    a = np.asarray(arr)
    if a.dtype.kind != "f" or np.isfinite(a).all():
        return False
    profiler.bump("ps.nonfinite_drop")
    print(f"[ps_rpc] dropping non-finite send '{name}' "
          f"(FLAGS_guard_numerics fleet hygiene)", flush=True)
    return True


def send_sections(client, name: str, arr, epmap, sections) -> None:
    if _guard_drops_send(name, arr):
        return
    for ep, wire, part in iter_sections(name, arr, epmap, sections):
        client.send_var(ep, wire, part)


def fetch_sections(client, name: str, epmap, sections) -> np.ndarray:
    """Inverse of send_sections: pull + row-concat a var's blocks."""
    if not sections:
        return client.get_var(epmap[0], name)
    parts = [client.get_var(ep, f"{name}.block{j}")
             for j, ep in enumerate(epmap)]
    return np.concatenate(parts, axis=0)


def send_sparse_sections(client, name: str, sr, epmap, begins,
                         sections) -> None:
    """Route a SelectedRows grad to its row-owning servers with slice-LOCAL
    indices (reference split_ids + parameter_send.cc SelectedRows path).
    Empty sections = whole table on epmap[0], global rows as-is."""
    from ..core.selected_rows import SelectedRows

    if _guard_drops_send(name, sr.values):
        return
    if not sections:
        client.send_var(epmap[0], name, sr)
        return
    rows = np.asarray(sr.rows)
    vals = np.asarray(sr.values)
    for j, (ep, b, s) in enumerate(zip(epmap, begins, sections)):
        mask = (rows >= b) & (rows < b + s)
        if not mask.any():
            continue
        client.send_var(ep, f"{name}.block{j}",
                        SelectedRows(rows[mask] - b, vals[mask], s))


class _HeartbeatSender(threading.Thread):
    """Per-client liveness beacon: a daemon thread sending `hb` frames to
    every pserver at FLAGS_heartbeat_interval_ms over its OWN connections —
    a blocking sync-barrier RPC holds the shared connection's lock for the
    whole round, so beats must never ride that socket. A beat's reply
    carries the server's eviction verdict for this trainer (surfaced via
    PSClient.was_evicted so a partitioned-but-alive trainer can notice and
    rejoin)."""

    def __init__(self, client: "PSClient", interval_s: float):
        super().__init__(daemon=True,
                         name=f"ps-heartbeat-{client.trainer_id}")
        self.client = client
        self.interval = float(interval_s)
        self.stop_event = threading.Event()
        self.evicted = threading.Event()
        self._conns: dict[str, Any] = {}

    def run(self):
        from ..resilience.faults import InjectedFault, fault_point

        while not self.stop_event.wait(self.interval):
            try:
                fault_point("heartbeat_loss")
            except InjectedFault:
                continue  # this beat is lost on the (simulated) floor
            for ep in self.client.endpoints:
                if self.stop_event.is_set():
                    return
                self._beat(ep)

    def _beat(self, ep: str):
        try:
            conn = self._conns.get(ep)
            if conn is None:
                conn = self._conns[ep] = Client(_parse_ep(ep),
                                                authkey=_authkey())
            conn.send_bytes(_pack({"op": "hb",
                                   "trainer": self.client.trainer_id}))
            if not conn.poll(max(self.interval, 1.0)):
                raise TimeoutError("heartbeat reply timed out")
            meta, _ = _unpack(conn.recv_bytes())
            if meta.get("evicted"):
                self.evicted.set()
        except Exception:
            # a sick endpoint only costs its own beat; redial next tick
            conn = self._conns.pop(ep, None)
            if conn is not None:
                try:
                    conn.close()
                except Exception:
                    pass

    def stop(self):
        self.stop_event.set()
        for conn in self._conns.values():
            try:
                conn.close()
            except Exception:
                pass
        self._conns.clear()


class PSClient:
    """One connection per pserver endpoint; thread-safe via a lock per conn."""

    _instances: dict[tuple, "PSClient"] = {}

    def __init__(self, endpoints: list[str], trainer_id: int):
        self.endpoints = list(endpoints)
        self.trainer_id = trainer_id
        self._conns = {}
        self._locks = {}
        # guards first-connection creation: the async Communicator calls in
        # from N send threads + the recv thread concurrently, and an
        # unsynchronized check-then-create could hand two threads the same
        # Connection under different locks
        self._create_lock = threading.Lock()
        self._retry = None  # lazy RetryPolicy (resilience/retry.py)
        self._hb: _HeartbeatSender | None = None

    def _policy(self):
        if self._retry is None:
            from ..resilience.retry import rpc_policy

            self._retry = rpc_policy()
        return self._retry

    def _drop_conn(self, ep: str) -> None:
        """Forget a (possibly broken) connection so the next RPC redials."""
        with self._create_lock:
            lock = self._locks.setdefault(ep, threading.Lock())
        with lock:
            conn = self._conns.pop(ep, None)
            if conn is not None:
                try:
                    conn.close()
                except Exception:
                    pass

    @classmethod
    def get(cls, endpoints, trainer_id) -> "PSClient":
        key = (tuple(endpoints), trainer_id)
        inst = cls._instances.get(key)
        if inst is None:
            inst = cls._instances[key] = cls(endpoints, trainer_id)
        return inst

    def _conn(self, ep: str):
        # the global lock only guards per-endpoint lock creation; the
        # (FLAGS_rpc_deadline-bounded) connect-retry runs under the
        # ENDPOINT's lock so one unreachable server cannot stall RPCs to
        # healthy ones
        with self._create_lock:
            lock = self._locks.setdefault(ep, threading.Lock())
        with lock:
            if ep not in self._conns:
                from ..resilience.retry import connect_policy

                def _dial():
                    self._conns[ep] = Client(_parse_ep(ep),
                                             authkey=_authkey())

                # flat-interval, FLAGS_rpc_deadline-bounded dial (the
                # server may still be starting) through the shared policy
                connect_policy().call(_dial)
        return self._conns[ep], lock

    def _call(self, ep: str, meta: dict, tensors=(), timeout=None):
        """One framed request/reply round; returns (meta, tensors).

        The reply wait is bounded: `timeout` seconds when given, else
        FLAGS_rpc_deadline — a dead server raises TimeoutError (transient,
        so the retrying callers redial) instead of blocking forever."""
        from ..resilience.faults import fault_point

        fault_point("rpc_drop")
        if timeout is None:
            timeout = rpc_deadline_s()
        conn, lock = self._conn(ep)
        with lock:
            conn.send_bytes(_pack(meta, tensors))
            if timeout and timeout > 0 and not conn.poll(timeout):
                # a late reply would desync the next RPC's framing — forget
                # the conn (inline: we already hold this endpoint's lock,
                # _drop_conn would deadlock re-acquiring it)
                self._conns.pop(ep, None)
                try:
                    conn.close()
                except Exception:
                    pass
                raise TimeoutError(
                    f"pserver {ep}: no reply to '{meta.get('op')}' within "
                    f"{timeout:.3g}s (FLAGS_rpc_deadline)")
            buf = conn.recv_bytes()
        rmeta, rtensors = _unpack(buf)
        if rmeta.get("s") == "err":
            raise RuntimeError(f"pserver {ep}: {rmeta.get('msg')}")
        return rmeta, rtensors

    # -- RPCClient contract --------------------------------------------------
    # Transient transport failures retry under the resilience rpc_policy,
    # redialing the endpoint between attempts. Dense sends are idempotent
    # within a round (the server keeps last-per-trainer); a sparse re-send
    # after an ambiguous mid-reply failure can double rows — the same
    # at-least-once tradeoff the reference gRPC retry path accepts.
    def send_var(self, ep: str, name: str, value) -> None:
        from ..resilience.faults import fault_point

        if hasattr(value, "rows"):  # SelectedRows
            meta = {"op": "send", "name": name, "trainer": self.trainer_id,
                    "kind": "sparse", "height": int(value.height)}
            tensors = [np.asarray(value.rows), np.asarray(value.values)]
        else:
            meta = {"op": "send", "name": name, "trainer": self.trainer_id,
                    "kind": "dense"}
            tensors = [np.asarray(value)]

        def _do():
            fault_point("ps.send")
            self._call(ep, meta, tensors)

        self._policy().call(_do, on_retry=lambda a, e: self._drop_conn(ep))

    def get_var(self, ep: str, name: str) -> np.ndarray:
        from ..resilience.faults import fault_point

        def _do():
            fault_point("ps.recv")
            _, (v,) = self._call(ep, {"op": "get", "name": name,
                                      "trainer": self.trainer_id})
            return v

        return self._policy().call(
            _do, on_retry=lambda a, e: self._drop_conn(ep))

    def prefetch(self, ep: str, name: str, ids) -> np.ndarray:
        """Fetch only the given (slice-local) rows of a server-resident
        table (reference RPCClient::AsyncPrefetchVar rpc_client.h:62 +
        RequestPrefetchHandler) — the whole table never travels."""
        def _do():
            _, (v,) = self._call(ep, {"op": "prefetch", "name": name},
                                 [np.asarray(ids, np.int64)])
            return v

        return self._policy().call(
            _do, on_retry=lambda a, e: self._drop_conn(ep))

    # -- liveness ------------------------------------------------------------
    def start_heartbeat(self) -> None:
        """Start the liveness beacon (idempotent; auto-invoked by the first
        send_barrier so every sync trainer heartbeats without API changes).
        FLAGS_heartbeat_interval_ms <= 0 disables."""
        if self._hb is not None and self._hb.is_alive():
            return
        from .. import flags

        interval_ms = int(flags.get_flag("heartbeat_interval_ms"))
        if interval_ms <= 0:
            return
        self._hb = _HeartbeatSender(self, interval_ms / 1000.0)
        self._hb.start()

    def stop_heartbeat(self) -> None:
        if self._hb is not None:
            self._hb.stop()
            self._hb = None

    @property
    def was_evicted(self) -> bool:
        """True once any heartbeat reply reported this trainer evicted."""
        return self._hb is not None and self._hb.evicted.is_set()

    def rejoin(self) -> int:
        """Ask every pserver to re-admit this trainer after an eviction (a
        restarted process calls this before resuming from its latest
        checkpoint). Returns the servers' max global step so the caller can
        log how far the survivors got while it was away."""
        step = 0
        for ep in self.endpoints:
            meta, _ = self._call(ep, {"op": "rejoin",
                                      "trainer": self.trainer_id})
            step = max(step, int(meta.get("step", 0)))
        if self._hb is not None:
            self._hb.evicted.clear()
        self.start_heartbeat()
        return step

    def send_barrier(self) -> None:
        """Blocks until the server has aggregated + applied this round.

        Bounded by 2x FLAGS_rpc_deadline, not 1x: the reply is legitimately
        gated on the server's own eviction deadline when a peer trainer
        died, so the client grants one extra deadline of grace before it
        gives up on the server itself."""
        import os

        from ..resilience.faults import InjectedFault, fault_point

        try:
            fault_point("trainer_crash")
        except InjectedFault:
            # the in-process stand-in for a mid-round SIGKILL: no cleanup,
            # no complete, heartbeats die with the process
            os._exit(137)
        self.start_heartbeat()
        timeout = 2.0 * rpc_deadline_s()
        for ep in self.endpoints:
            self._call(ep, {"op": "barrier", "trainer": self.trainer_id},
                       timeout=timeout)

    def fetch_barrier(self) -> None:
        pass  # subsumed: send_barrier only returns post-update

    def checkpoint_notify(self, dirname: str) -> None:
        """Ask every pserver to persist its parameter slices (reference
        checkpoint_notify_op.cc / RPCClient::AsyncCheckpointNotify): the
        server-side save means no slice ever travels back to the trainer."""
        for ep in self.endpoints:
            self._call(ep, {"op": "checkpoint", "dirname": dirname,
                            "trainer": self.trainer_id})

    def send_complete(self) -> None:
        self.stop_heartbeat()
        for ep in self.endpoints:
            try:
                self._call(ep, {"op": "complete", "trainer": self.trainer_id})
            except (EOFError, ConnectionError, TimeoutError, RuntimeError):
                pass

    def close(self):
        self.stop_heartbeat()
        for conn in self._conns.values():
            try:
                conn.close()
            except Exception:
                pass
        self._conns.clear()


class PServerRuntime:
    """The listen_and_serv event loop: owns a scope of parameter blocks and
    per-gradient optimize programs; serves send/get/barrier until every
    trainer sends `complete`."""

    def __init__(self, endpoint: str, n_trainers: int, sync_mode: bool,
                 blocks: list[dict], scope, executor,
                 dc_asgd: bool = False, dc_asgd_lambda: float = 1.0):
        """blocks: [{grad, param, optimize_program, sparse,
                     origin_param?, begin?, rows?}]"""
        self.endpoint = endpoint
        self.n_trainers = n_trainers
        self.sync_mode = sync_mode
        # delay-compensated async SGD (reference _append_dc_asgd_ops):
        # per-(grad, trainer) parameter snapshots for the compensation term
        self.dc_asgd = dc_asgd and not sync_mode
        self.dc_lambda = float(dc_asgd_lambda)
        self._param_bak: dict[tuple[str, int], np.ndarray] = {}
        self.blocks = {b["grad"]: b for b in blocks}
        self.scope = scope
        self.exe = executor
        # row-sliced params: carve this server's slice out of the full
        # startup-initialized value (reference get_startup_program splits
        # init ops; equal-seed init + slicing is equivalent)
        for b in blocks:
            rows = b.get("rows")
            if rows is not None and b["param"] != b.get("origin_param"):
                full = scope.find_var(b["origin_param"])
                if full is None:
                    raise RuntimeError(
                        f"pserver scope missing '{b['origin_param']}' — run "
                        f"the startup program first")
                begin = int(b.get("begin", 0))
                scope.set_var(b["param"],
                              np.asarray(full)[begin:begin + rows].copy())
        # delta payloads (geo-SGD) arrive under the PARAM wire name
        self._param_blocks = {b["param"]: b for b in blocks}
        self._lock = threading.Lock()
        self._grad_buf: dict[str, dict[int, Any]] = {}
        self._barrier_waiting: list = []
        self._barriers_seen: set[int] = set()
        self._completed: set[int] = set()
        self._step = 0
        self._shutdown = threading.Event()
        # -- liveness state (monitor thread + heartbeat handlers) -----------
        # invariant: _evicted and _completed stay disjoint
        self._last_seen: dict[int, float] = {}
        self._evicted: set[int] = set()
        self._round_started: float | None = None
        self._all_done_since: float | None = None
        self.liveness_log: list[dict] = []  # evict/rejoin forensic record

    # -- liveness ------------------------------------------------------------
    def _touch_locked(self, trainer) -> None:
        if trainer is not None:
            self._last_seen[int(trainer)] = time.monotonic()

    def _readmit_locked(self, trainer, how: str) -> None:
        """Re-admit an evicted trainer. Explicit `rejoin` RPCs land here, but
        so does an evicted trainer's next send/barrier — a false-positive
        eviction (e.g. a long GC pause) self-heals on its next round. Net
        barrier accounting stays consistent mid-round: readmission raises
        the active count by one exactly when the trainer re-enters the
        protocol."""
        t = int(trainer)
        if t not in self._evicted:
            return
        self._evicted.discard(t)
        self._all_done_since = None
        rec = {"event": "rejoin", "trainer": t,
               "step": self._step, "via": how}
        self.liveness_log.append(rec)
        # the print is load-bearing (tests grep the server subprocess's
        # stdout); the logger + registry carry the structured copies
        print(f"[ps_rpc] {self.endpoint}: trainer {t} rejoined via {how} "
              f"at step {self._step}", flush=True)
        logger.info("trainer %d rejoined via %s at step %d", t, how,
                    self._step, extra={"ps_liveness": rec})
        self._note_liveness(rec, "ps.rejoins")

    def _evict_locked(self, t: int, idle_s: float, timeout_s: float) -> None:
        self._evicted.add(t)
        # the dead trainer's half-round gradients must not leak into the
        # survivors' average (_run_round rescales to the active count)
        for buf in self._grad_buf.values():
            buf.pop(t, None)
        rec = {"event": "evict", "trainer": t, "step": self._step,
               "idle_s": round(idle_s, 3)}
        self.liveness_log.append(rec)
        print(f"[ps_rpc] {self.endpoint}: evicted trainer {t} from the "
              f"sync barrier at step {self._step} (no liveness signal for "
              f"{idle_s:.2f}s > {timeout_s:.2f}s deadline)", flush=True)
        logger.warning("evicted trainer %d at step %d (idle %.2fs > %.2fs)",
                       t, self._step, idle_s, timeout_s,
                       extra={"ps_liveness": rec})
        self._note_liveness(rec, "ps.evictions")

    def _note_liveness(self, rec: dict, counter: str) -> None:
        try:
            from .. import observability as obs

            obs.counter_inc(counter)
            obs.event("ps.liveness", rec, level="warning")
        except Exception:  # noqa: BLE001 — telemetry never stalls the server
            pass

    def _maybe_release_barrier_locked(self) -> bool:
        """Run the round and release every waiting trainer once the posted
        barriers cover all ACTIVE (not completed, not evicted) trainers."""
        if (not self._barriers_seen
                or len(self._barriers_seen) < self._active_trainers()):
            return False
        self._run_round()
        waiting, self._barrier_waiting = self._barrier_waiting, []
        self._barriers_seen = set()
        self._round_started = None
        for c in waiting:
            try:
                _reply_ok(c)
            except Exception:
                pass
        return True

    def _monitor_loop(self):
        """Liveness monitor: while a sync round is blocked, evict trainers
        whose last heartbeat/RPC (or, if never seen, the round's start) is
        older than the liveness deadline, then re-check barrier release.
        Also enforces the rejoin-grace shutdown so a permanently-dead
        trainer cannot make the server serve forever after everyone else
        completed."""
        while not self._shutdown.is_set():
            timeout = heartbeat_timeout_s()
            self._shutdown.wait(min(max(timeout / 4.0, 0.05), 1.0))
            if self._shutdown.is_set():
                return
            now = time.monotonic()
            shutdown = False
            with self._lock:
                if self._barrier_waiting and self._round_started is not None:
                    for t in range(self.n_trainers):
                        if (t in self._barriers_seen or t in self._completed
                                or t in self._evicted):
                            continue
                        # clamp to round start: eviction measures the stall,
                        # and a trainer that last spoke long before this
                        # round still gets one full deadline of it
                        seen = max(self._last_seen.get(t, 0.0),
                                   self._round_started)
                        idle = now - seen
                        if idle > timeout:
                            self._evict_locked(t, idle, timeout)
                    self._maybe_release_barrier_locked()
                remaining = (self.n_trainers - len(self._completed)
                             - len(self._evicted))
                if self._evicted and remaining <= 0 and self._completed:
                    if self._all_done_since is None:
                        self._all_done_since = now
                    elif now - self._all_done_since > max(10.0 * timeout,
                                                          60.0):
                        print(f"[ps_rpc] {self.endpoint}: evicted "
                              f"trainer(s) {sorted(self._evicted)} never "
                              f"rejoined within the grace window — "
                              f"shutting down", flush=True)
                        logger.warning(
                            "evicted trainer(s) %s never rejoined; shutting "
                            "down", sorted(self._evicted),
                            extra={"ps_liveness": {
                                "event": "grace_shutdown",
                                "evicted": sorted(self._evicted)}})
                        shutdown = True
                else:
                    self._all_done_since = None
            if shutdown:
                self._signal_shutdown()
                return

    # -- request handlers ----------------------------------------------------
    def _handle_send(self, msg):
        name = msg["name"]
        kind = msg["value"][0]
        with self._lock:
            self._touch_locked(msg.get("trainer"))
            if msg.get("trainer") is not None:
                self._readmit_locked(msg["trainer"], how="send")
            buf = self._grad_buf.setdefault(name, {})
            if kind == "sparse" and msg["trainer"] in buf:
                # accumulate repeated sparse sends within a round
                prev = buf[msg["trainer"]]
                buf[msg["trainer"]] = ("sparse",
                                       np.concatenate([prev[1], msg["value"][1]]),
                                       np.concatenate([prev[2], msg["value"][2]]),
                                       msg["value"][3])
            else:
                buf[msg["trainer"]] = msg["value"]
            if not self.sync_mode:
                self._apply_one(name)
        return True

    def _apply_one(self, grad_name):
        """Async mode: apply immediately with whatever arrived."""
        buf = self._grad_buf.get(grad_name, {})
        for tid in list(buf):
            self._apply_update(grad_name, [buf.pop(tid)], scale=1.0,
                               trainer=tid)

    def _handle_barrier(self, msg, conn):
        with self._lock:
            t = msg["trainer"]
            self._touch_locked(t)
            self._readmit_locked(t, how="barrier")
            if not self._barrier_waiting:
                self._round_started = time.monotonic()  # the stall clock
            self._barriers_seen.add(t)
            self._barrier_waiting.append(conn)
            if self._maybe_release_barrier_locked():
                return None  # replies already sent
        return "wait"  # reply deferred until the round completes

    def _active_trainers(self):
        return self.n_trainers - len(self._completed) - len(self._evicted)

    def _run_round(self):
        # sparse scales by the ACTIVE trainer count, not by how many posted:
        # a row-sharded sparse table legitimately gets rows from a subset of
        # trainers in a round, but the sync average is still over all of
        # them. Dense scales by the POSTED count (normally identical) so a
        # guardrail-dropped poisoned send renormalizes to the survivors.
        n_active = max(self._active_trainers(), 1)
        for grad_name, buf in list(self._grad_buf.items()):
            vals = [buf[t] for t in sorted(buf)]
            if not vals:
                continue
            if vals[0][0] == "sparse":
                scale = 1.0 / n_active
            else:
                # dense grads normally arrive from every active trainer; a
                # trainer that dropped a non-finite send (guardrails fleet
                # hygiene) simply doesn't post this round — renormalize the
                # average to the survivors, the same stance as the eviction
                # path's half-round drop (_evict_locked)
                scale = 1.0 / len(vals)
            self._apply_update(grad_name, vals, scale=scale)
            self._grad_buf[grad_name] = {}
        self._step += 1

    def _apply_update(self, grad_name, payloads, scale: float, trainer=None):
        from ..core.selected_rows import SelectedRows

        if payloads[0][0] == "delta":
            # geo-SGD payload: arrives under the PARAM wire name; the
            # server just ADDS it (reference GeoSgdCommunicator server
            # contract), no optimize program
            spec = self._param_blocks.get(grad_name)
            if spec is None:
                return
            param = np.asarray(self.scope.find_var(spec["param"]),
                               dtype=np.float32)
            for p in payloads:
                param = param + np.asarray(p[1], np.float32)
            self.scope.set_var(spec["param"], param)
            return
        spec = self.blocks.get(grad_name)
        if spec is None:
            return

        if payloads[0][0] == "sparse":
            rows = np.concatenate([p[1] for p in payloads])
            vals = np.concatenate([p[2] for p in payloads]) * scale
            grad = SelectedRows(rows, vals, payloads[0][3])
        else:
            acc = payloads[0][1].astype(np.float32).copy()
            for p in payloads[1:]:
                acc += p[1]
            grad = acc * scale
            if self.dc_asgd and trainer is not None:
                # reference _append_dc_asgd_ops: g_comp = g + lambda *
                # g*g*(param_now - param_bak[trainer]); the snapshot then
                # advances to the freshly updated param
                param = np.asarray(self.scope.find_var(spec["param"]),
                                   dtype=np.float32)
                bak = self._param_bak.get((grad_name, trainer))
                if bak is not None:
                    grad = grad + self.dc_lambda * grad * grad * (param - bak)
        from ..executor import scope_guard

        with scope_guard(self.scope):
            self.exe.run(spec["optimize_program"], feed={grad_name: grad})

    def _handle_checkpoint(self, msg):
        """Persist this server's slices (reference checkpoint_notify -> the
        pserver-side save in listen_and_serv). One npz per server endpoint;
        the load side is fleet.init_server(model_dir) (parameter_server.py).
        Written tmp-then-rename under the lock: concurrent notifies from
        several trainers must not interleave zip writes."""
        import os

        dirname = msg["dirname"]
        os.makedirs(dirname, exist_ok=True)
        safe_ep = self.endpoint.replace(":", "_").replace("/", "_")
        path = os.path.join(dirname, f"pserver-{safe_ep}.npz")
        with self._lock:
            arrays = {n: np.asarray(self.scope.find_var(n))
                      for n in self.scope.var_names()
                      if self.scope.find_var(n) is not None}
            # np.savez appends ".npz" when missing — keep the suffix so the
            # tmp name is exactly what gets written
            tmp = path + f".tmp{msg.get('trainer', 0)}.npz"
            np.savez(tmp, **arrays)
            os.replace(tmp, path)
        return path

    def _handle_get(self, msg):
        with self._lock:
            self._touch_locked(msg.get("trainer"))
            v = self.scope.find_var(msg["name"])
            if v is None:
                raise KeyError(f"pserver has no var '{msg['name']}'")
            out = np.asarray(v)
            if self.dc_asgd and "trainer" in msg:
                # DC-ASGD snapshots the param AT THE MOMENT THE TRAINER
                # SEES IT — compensation then measures exactly the updates
                # that trainer missed (snapshotting at apply time instead
                # would also count updates it had already observed)
                for spec in self.blocks.values():
                    if spec["param"] == msg["name"]:
                        self._param_bak[(spec["grad"], msg["trainer"])] = \
                            out.astype(np.float32).copy()
        return out

    def _handle_prefetch(self, msg):
        """Row-gather from a table slice (reference
        RequestPrefetchHandler::Handle running the table's lookup block).
        ids are slice-LOCAL (the trainer's prefetch op already subtracted
        the block's row offset)."""
        with self._lock:
            v = self.scope.find_var(msg["name"])
            if v is None:
                raise KeyError(f"pserver has no table '{msg['name']}'")
            table = np.asarray(v)
            ids = np.asarray(msg["ids"], np.int64)
            if ids.size and (ids.min() < 0 or ids.max() >= table.shape[0]):
                raise IndexError(
                    f"prefetch ids out of range for '{msg['name']}' "
                    f"[0, {table.shape[0]}): min={ids.min()} max={ids.max()}")
            return table[ids]

    # -- event loop ----------------------------------------------------------
    def _signal_shutdown(self):
        """Set the flag, then poke the listen socket: closing an fd does NOT
        wake a thread blocked in accept() on Linux, so serve() is nudged with
        a throwaway connection instead."""
        self._shutdown.set()
        import socket as _socket

        try:
            s = _socket.create_connection(_parse_ep(self.endpoint), timeout=1.0)
            s.close()
        except OSError:
            pass

    def _warm_optimize_programs(self):
        """Pre-compile each dense block's optimize program before accepting
        traffic: the first real send otherwise pays the whole-block jit
        compile while holding the server lock, stalling every trainer for
        seconds (observed: an async trainer finishes its run before the
        first update lands). A zero-grad run hits the same compile cache as
        real sends (same feed shape); the scope snapshot/restore makes it
        side-effect-free for any optimizer state."""
        from ..executor import scope_guard

        todo = [s for s in self.blocks.values()
                if not s.get("sparse")
                and self.scope.find_var(s["param"]) is not None]
        if not todo:
            return
        # ONE snapshot around all warmups, as HOST COPIES: the executor
        # donates state buffers into each run, so restoring the original
        # jax.Array references would put deleted buffers back into the scope
        snapshot = {}
        for k, v in self.scope._vars.items():
            try:
                snapshot[k] = np.array(np.asarray(v))
            except Exception:
                snapshot[k] = v  # non-array state: not donate-able
        try:
            for spec in todo:
                pv = self.scope.find_var(spec["param"])
                zero = np.zeros(np.asarray(pv).shape, np.float32)
                with scope_guard(self.scope):
                    self.exe.run(spec["optimize_program"],
                                 feed={spec["grad"]: zero})
        finally:
            self.scope._vars = snapshot

    def serve(self):
        import os

        host = _parse_ep(self.endpoint)[0]
        if (host not in ("127.0.0.1", "localhost", "::1")
                and not os.environ.get("PADDLE_PS_AUTHKEY")):
            # the built-in fallback authkey is not a boundary; a bind on a
            # routable address without an explicit launch secret would accept
            # writes from anything on the network
            raise RuntimeError(
                f"refusing to bind pserver on non-loopback '{self.endpoint}' "
                "with the default authkey — export PADDLE_PS_AUTHKEY (the "
                "launcher does this automatically)")
        self._warm_optimize_programs()
        listener = Listener(_parse_ep(self.endpoint), authkey=_authkey())
        threading.Thread(target=self._monitor_loop, daemon=True,
                         name="ps-liveness-monitor").start()
        threads = []
        while not self._shutdown.is_set():
            try:
                conn = listener.accept()
            except (OSError, EOFError):
                if self._shutdown.is_set():
                    break
                raise  # a healthy listener doesn't fail accept — surface it
            except Exception:
                continue  # auth failure from a stray client: keep serving
            if self._shutdown.is_set():
                break
            t = threading.Thread(target=self._client_loop, args=(conn,),
                                 daemon=True)
            t.start()
            threads.append(t)
        try:
            listener.close()
        except OSError:
            pass

    def _client_loop(self, conn):
        while not self._shutdown.is_set():
            try:
                buf = conn.recv_bytes()
            except (EOFError, OSError):
                return
            try:
                msg, tensors = _unpack(buf)
                op = msg["op"]
                # reconstruct the handler-facing payload tuples from the raw
                # tensor blocks (frame kinds: dense/sparse/delta)
                if op == "send":
                    kind = msg["kind"]
                    if kind == "sparse":
                        msg["value"] = ("sparse", tensors[0], tensors[1],
                                        msg["height"])
                    else:
                        msg["value"] = (kind, tensors[0])
                    self._handle_send(msg)
                    _reply_ok(conn)
                elif op == "get":
                    _reply_ok(conn, [self._handle_get(msg)])
                elif op == "prefetch":
                    msg["ids"] = tensors[0]
                    _reply_ok(conn, [self._handle_prefetch(msg)])
                elif op == "barrier":
                    r = self._handle_barrier(msg, conn)
                    if r == "wait":
                        pass  # reply comes when the round completes
                elif op == "checkpoint":
                    _reply_ok(conn, path=self._handle_checkpoint(msg))
                elif op == "hb":
                    with self._lock:
                        self._touch_locked(msg["trainer"])
                        evicted = int(msg["trainer"]) in self._evicted
                    _reply_ok(conn, evicted=evicted)
                elif op == "rejoin":
                    with self._lock:
                        self._touch_locked(msg["trainer"])
                        # a restarted trainer trains again: it owes a fresh
                        # `complete`, so it cannot stay in the done set
                        self._completed.discard(int(msg["trainer"]))
                        self._readmit_locked(msg["trainer"], how="rejoin")
                        step = self._step
                    _reply_ok(conn, step=step)
                elif op == "complete":
                    with self._lock:
                        self._touch_locked(msg["trainer"])
                        self._completed.add(msg["trainer"])
                        self._evicted.discard(int(msg["trainer"]))
                        done = len(self._completed) >= self.n_trainers
                        # release any trainers stuck on the barrier
                        self._maybe_release_barrier_locked()
                    _reply_ok(conn)
                    if done:
                        self._signal_shutdown()
                        return
                else:
                    _reply_err(conn, f"unknown op {msg['op']}")
            except Exception as e:  # serve must not die on one bad request
                try:
                    _reply_err(conn, f"{type(e).__name__}: {e}")
                except Exception:
                    return


def send_delta_sections(client, name: str, delta, epmap, sections) -> None:
    """Geo-SGD push: ship an accumulated parameter DELTA under the PARAM
    wire name (server adds it, no optimizer). Shares iter_sections so the
    slicing math cannot drift from send_sections. NOT retried at this layer:
    the server ADDS deltas, so an ambiguous re-send would double-apply —
    geo's rebase-on-pull makes a lost push self-correcting instead."""
    if _guard_drops_send(name, delta):
        return
    for ep, wire, part in iter_sections(name, delta, epmap, sections):
        client._call(ep, {"op": "send", "name": wire,
                          "trainer": client.trainer_id, "kind": "delta"},
                     [np.asarray(part)])
