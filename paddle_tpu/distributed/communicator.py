"""Async-mode Communicator: per-gradient send queues, merge-before-send,
independent recv thread.

TPU-native redesign of the reference async stack
(/root/reference/paddle/fluid/operators/distributed/communicator.h:162
AsyncCommunicator: send_varname_to_queue_ + per-grad SendThread merging up to
max_merge_var_num grads before one RPC, RecvThread pulling parameters after
min_send_grad_num_before_recv sends; knobs exported at
/root/reference/python/paddle/fluid/__init__.py:65-71).

Trainer flow in async mode: the program's `send` ops ENQUEUE the gradient
here and return immediately (no barrier ops exist); per-grad worker threads
drain the queue, merge (dense: mean, sparse: row-concat — the server's row
update handles duplicates), and push to the assigned pserver(s), where each
send applies one optimizer step at arrival time (ps_rpc._apply_one). A
single recv thread refreshes every parameter into the trainer scope at a
fixed cadence once enough grads have gone out.

Knobs ride the flags registry (FLAGS_communicator_*), same names as the
reference.
"""
from __future__ import annotations

import queue
import threading
import time

import numpy as np

from .. import flags, profiler

__all__ = ["Communicator", "GeoCommunicator"]


class Communicator:
    """One per trainer process (reference Communicator::GetInstance)."""

    _singleton: "Communicator | None" = None

    def __init__(self, send_ctx: dict, recv_ctx: dict, client, scope):
        """send_ctx: {grad_name: {"epmap": [...], "sections": [...]}};
        recv_ctx: {param_name: {"epmap": [...], "sections": [...]}};
        client: PSClient; scope: the trainer Scope recv writes into."""
        self.send_ctx = send_ctx
        self.recv_ctx = recv_ctx
        self.client = client
        self.scope = scope
        self.max_merge = flags.get_flag("communicator_max_merge_var_num")
        self.queue_size = flags.get_flag("communicator_send_queue_size")
        self.wait_times = flags.get_flag("communicator_send_wait_times")
        self.min_send_before_recv = flags.get_flag(
            "communicator_min_send_grad_num_before_recv")
        self.independent_recv = flags.get_flag(
            "communicator_independent_recv_thread")
        self._queues: dict[str, queue.Queue] = {
            n: queue.Queue(maxsize=self.queue_size) for n in send_ctx}
        self._threads: list[threading.Thread] = []
        self._running = False
        self._grads_sent = 0
        self._lock = threading.Lock()
        self._send_errors: dict[str, Exception] = {}
        # merged-batch retry: few attempts, fast backoff — the PSClient
        # already retries each wire RPC with backoff, so this layer only
        # papers over failures that poison a whole merge (e.g. one endpoint
        # of a sliced send). The wall-clock budget is FLAGS_rpc_deadline
        # (reference semantics), not a constant of this file.
        from ..resilience.retry import RetryPolicy
        from .ps_rpc import rpc_deadline_s

        self._send_retry = RetryPolicy(max_attempts=2, base_delay=0.02,
                                       max_delay=0.1,
                                       deadline=rpc_deadline_s())

    # -- lifecycle -----------------------------------------------------------
    @classmethod
    def get_instance(cls) -> "Communicator | None":
        return cls._singleton

    def start(self):
        if self._running:
            return
        self._running = True
        Communicator._singleton = self
        for name in self.send_ctx:
            t = threading.Thread(target=self._send_loop, args=(name,),
                                 daemon=True, name=f"comm-send-{name}")
            t.start()
            self._threads.append(t)
        if self.independent_recv and self.recv_ctx:
            t = threading.Thread(target=self._recv_loop, daemon=True,
                                 name="comm-recv")
            t.start()
            self._threads.append(t)

    def stop(self):
        """Flush every queue, then stop the threads (reference
        Communicator::Stop waits for send queues to drain)."""
        if not self._running:
            return
        for q in self._queues.values():
            q.join()  # all enqueued grads merged + sent
        self._running = False
        from .ps_rpc import rpc_deadline_s

        # backstop only — after the flush the loops exit within one poll
        # tick; a thread still stuck here is wedged in an RPC, whose own
        # waits are already bounded by the same deadline
        for t in self._threads:
            t.join(timeout=rpc_deadline_s())
        self._threads.clear()
        if Communicator._singleton is self:
            Communicator._singleton = None
        err = getattr(self, "_recv_error", None)
        if err is not None:
            raise RuntimeError(
                f"Communicator recv thread failed: {err}") from err
        if self._send_errors:
            # a failure on the run's FINAL batches has no later push() to
            # surface through — the tail gradients were lost
            detail = "; ".join(
                f"'{n}': {e}" for n, e in self._send_errors.items())
            err = next(iter(self._send_errors.values()))
            raise RuntimeError(
                f"Communicator send thread(s) failed (tail gradients "
                f"dropped): {detail}") from err
        # one final parameter pull so the trainer scope holds the servers'
        # latest state when training ends
        self._recv_all()

    @property
    def is_running(self):
        return self._running

    # -- send side -----------------------------------------------------------
    def push(self, name: str, value) -> None:
        """Called by the `send` op. Blocks when the queue is full
        (backpressure — reference send_queue_size contract); surfaces a
        send-thread failure instead of blocking forever behind it."""
        q = self._queues[name]
        while True:
            err = self._send_errors.get(name)
            if err is not None:
                raise RuntimeError(
                    f"Communicator send thread for '{name}' failed: "
                    f"{err}") from err
            try:
                q.put(value, timeout=1.0)
                return
            except queue.Full:
                continue

    def _send_loop(self, name: str):
        q = self._queues[name]
        ctx = self.send_ctx[name]
        while self._running or not q.empty():
            try:
                first = q.get(timeout=0.05)
            except queue.Empty:
                continue
            batch = [first]
            # merge-before-send: wait up to wait_times short intervals for
            # more grads, cap at max_merge_var_num (reference SendThread)
            waits = 0
            while len(batch) < self.max_merge and waits < self.wait_times:
                try:
                    batch.append(q.get_nowait())
                except queue.Empty:
                    waits += 1
                    time.sleep(0.002)
            try:
                self._send_retry.call(self._send_merged, name, ctx, batch)
                # transient failures don't poison — but only THIS grad's
                # success clears its entry; another grad's healthy sends
                # must not mask a broken one
                self._send_errors.pop(name, None)
            except Exception as e:
                # a dead send thread would silently jam the queue and block
                # every future push() — survive, drop the batch, record the
                # error per-gradient so push() can surface it
                self._send_errors[name] = e
            finally:
                for _ in batch:
                    q.task_done()
            with self._lock:
                self._grads_sent += len(batch)
            if not self.independent_recv and self.recv_ctx:
                # non-independent mode (reference AsyncCommunicator with
                # the flag off): recv inline with send progress
                with self._lock:
                    ready = self._grads_sent >= self.min_send_before_recv
                    if ready:
                        self._grads_sent = 0
                if ready:
                    self._recv_all()

    def _send_merged(self, name, ctx, batch):
        from .ps_rpc import send_sections, send_sparse_sections

        epmap = ctx["epmap"]
        sections = ctx.get("sections") or []
        begins = ctx.get("begins") or [0]
        sparse = [v for v in batch if hasattr(v, "rows")]
        if sparse:
            from ..core.selected_rows import SelectedRows

            rows = np.concatenate([np.asarray(v.rows) for v in sparse])
            vals = np.concatenate([np.asarray(v.values) for v in sparse])
            if self._drop_nonfinite(name, vals, len(batch)):
                return
            send_sparse_sections(
                self.client, name,
                SelectedRows(rows, vals, sparse[0].height),
                epmap, begins, sections)
            return
        acc = np.asarray(batch[0], dtype=np.float32).copy()
        for v in batch[1:]:
            acc += np.asarray(v)
        acc /= len(batch)  # mean of merged grads (reference MergeVars)
        if self._drop_nonfinite(name, acc, len(batch)):
            return
        send_sections(self.client, name, acc, epmap, sections)

    @staticmethod
    def _drop_nonfinite(name, arr, n_merged) -> bool:
        """Fleet numeric hygiene (FLAGS_guard_numerics): one trainer's
        NaN/Inf gradient must never reach the pservers — on the PS path it
        would poison EVERY worker's next parameter pull. The poisoned merge
        is dropped whole (and counted); the sync pserver renormalizes the
        round to the trainers that did post, exactly as it does for an
        evicted trainer's half-round (ps_rpc._run_round)."""
        if not flags.get_flag("guard_numerics"):
            return False
        if np.isfinite(arr).all():
            return False
        profiler.bump("comm.nonfinite_drop", n_merged)
        print(f"[communicator] dropping non-finite merged send '{name}' "
              f"({n_merged} grad(s)) — poisoned gradients never ship",
              flush=True)
        return True

    # -- recv side -----------------------------------------------------------
    def _recv_loop(self):
        """Pull params every `min_send_grad_num_before_recv` sent grads —
        recv cadence tracks training PROGRESS, not wall-clock (reference
        RecvThread: grad_num_ >= min -> RecvAll, counter reset), so a fast
        trainer can't race ahead on stale parameters."""
        while self._running:
            with self._lock:
                ready = self._grads_sent >= self.min_send_before_recv
                if ready:
                    self._grads_sent = 0
            if ready:
                try:
                    self._recv_all()
                except Exception as e:
                    # a dead recv thread = the whole run silently trains on
                    # stale params; record so stop() re-raises
                    self._recv_error = e
                    return
            else:
                time.sleep(0.005)

    def _recv_all(self):
        from .ps_rpc import fetch_sections

        for pname, ctx in self.recv_ctx.items():
            try:
                val = fetch_sections(self.client, pname, ctx["epmap"],
                                     ctx.get("sections") or [])
            except (ConnectionError, EOFError, OSError):
                return  # server shutting down: keep the last-known params
            # a server-side "err" reply (RuntimeError from PSClient._call —
            # e.g. a wrong name in recv_ctx) propagates: swallowing it would
            # silently train the whole run on initial parameters
            self.scope.set_var(pname, val)


class GeoCommunicator:
    """Geo-SGD communication (reference GeoSgdCommunicator,
    communicator.h:190): the trainer optimizes LOCALLY; every
    `push_nums` steps it pushes the accumulated parameter delta
    (local_param - param_at_last_sync) to the servers — which simply ADD
    it — then pulls the fresh global param and rebases. Staleness trades
    for a push_nums-fold reduction in communication rounds.

    param_ctx: {param_name: {"epmap": [...], "sections": [...]}} — note
    PARAMS, not grads: geo mode ships parameter deltas, never gradients.
    """

    def __init__(self, param_ctx: dict, client, scope,
                 push_nums: int = 100):
        self.param_ctx = param_ctx
        self.client = client
        self.scope = scope
        self.push_nums = max(int(push_nums), 1)
        self._base: dict[str, np.ndarray] = {}
        self._steps = 0

    def start(self):
        for name in self.param_ctx:
            v = self.scope.find_var(name)
            if v is None:
                raise RuntimeError(f"GeoCommunicator: scope missing '{name}'")
            self._base[name] = np.asarray(v, dtype=np.float32).copy()

    def mark_step(self):
        """Call once per local optimizer step; pushes + rebases on the
        push_nums boundary."""
        self._steps += 1
        if self._steps % self.push_nums == 0:
            self.push_and_pull()

    def push_and_pull(self):
        from .ps_rpc import fetch_sections, send_delta_sections

        for name, ctx in self.param_ctx.items():
            local = np.asarray(self.scope.find_var(name), dtype=np.float32)
            delta = local - self._base[name]
            send_delta_sections(self.client, name, delta,
                                ctx["epmap"], ctx.get("sections") or [])
            fresh = fetch_sections(self.client, name,
                                   ctx["epmap"], ctx.get("sections") or [])
            self.scope.set_var(name, fresh.astype(local.dtype))
            self._base[name] = np.asarray(fresh, dtype=np.float32).copy()
