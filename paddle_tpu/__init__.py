"""paddle_tpu — a TPU-native deep-learning framework with the capabilities of
PaddlePaddle Fluid 1.5 (reference at /root/reference, surveyed in SURVEY.md).

Fluid's contract — declarative Program IR built from Python layers, autodiff
and distribution as program transformations, Executor.run(feed, fetch) — with
a new execution model: whole-block lowering to XLA via JAX, SPMD parallelism
over jax.sharding meshes, and Pallas kernels for hot ops.
"""
from . import flags  # noqa: F401  (first: other modules read flags at import)
from . import observability  # noqa: F401  (before profiler: its shims use it)
from . import core  # noqa: F401
from . import ops  # noqa: F401
from . import profiler  # noqa: F401
from . import layers  # noqa: F401
from . import initializer  # noqa: F401
from . import optimizer  # noqa: F401
from . import regularizer  # noqa: F401
from . import clip  # noqa: F401
from . import unique_name  # noqa: F401
from . import parallel  # noqa: F401
from . import nets  # noqa: F401
from . import models  # noqa: F401
from . import metrics  # noqa: F401
from . import io  # noqa: F401
from . import contrib  # noqa: F401
from . import reader  # noqa: F401
from . import dataset  # noqa: F401
from . import transpiler  # noqa: F401
from . import debugger  # noqa: F401
from . import average  # noqa: F401
from . import evaluator  # noqa: F401
from . import net_drawer  # noqa: F401
from . import install_check  # noqa: F401
from . import passes  # noqa: F401
from . import distributed  # noqa: F401
from . import inference  # noqa: F401
from . import dygraph  # noqa: F401
from . import resilience  # noqa: F401
from . import pipeline  # noqa: F401
from . import serving  # noqa: F401
from .pipeline import DeviceLoader  # noqa: F401
from .transpiler import DistributeTranspiler, DistributeTranspilerConfig  # noqa: F401
from .fluid_dataset import DatasetFactory, InMemoryDataset, QueueDataset  # noqa: F401
from .data_feeder import DataFeeder  # noqa: F401
from .pyreader import DataLoader, PyReader  # noqa: F401
batch = reader.batch  # paddle.batch alias
from .backward import append_backward, gradients  # noqa: F401
from .compiler import BuildStrategy, CompiledProgram, ExecutionStrategy  # noqa: F401
from .executor import Executor, Scope, global_scope, scope_guard  # noqa: F401
from .framework import (  # noqa: F401
    Block,
    OpError,
    Operator,
    Parameter,
    Program,
    Variable,
    default_main_program,
    default_startup_program,
    name_scope,
    program_guard,
)
from .param_attr import ParamAttr  # noqa: F401

# Place objects: thin tags for API parity (reference platform/place.h:79).
# Device selection is JAX's job; these only pick cpu vs tpu backends.
class CPUPlace:
    def __repr__(self):
        return "CPUPlace"


class TPUPlace:
    def __init__(self, device_id: int = 0):
        self.device_id = device_id

    def __repr__(self):
        return f"TPUPlace({self.device_id})"


# CUDAPlace intentionally absent: zero CUDA in this build (BASELINE.json).

__version__ = "0.1.0"
