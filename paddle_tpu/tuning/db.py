"""Persistent per-(op, shape, dtype, device_kind) decision database.

The artifact is a single schema-versioned JSON file (FLAGS_tuning_db):

    {
      "schema": 1,
      "entries": {
        "<op>|<canonical shape key>|<dtype>|<device_kind>": {
          "decision": {...},            # op-specific, e.g. {"lowering": "igemm"}
          "source":   "swept",          # swept | candidate | recorded
          "measured": {...},            # sweep numbers (median ms per arm, band)
          "note":     "..."             # free-form provenance
        },
        ...
      }
    }

Write discipline follows the PR 1 checkpoint rules: temp file in the same
directory + os.replace, so a crashed sweep never leaves a half-written DB.
Read discipline is fail-open: a missing file is an empty DB; a corrupt or
wrong-schema file warns ONCE and degrades to an empty DB, so a consult-mode
run falls back to the analytic prior instead of dying (the acceptance
contract — a bad cache may cost performance, never correctness).
"""
from __future__ import annotations

import json
import os
import tempfile
import threading
import warnings

DB_SCHEMA = 1

__all__ = ["DB_SCHEMA", "TuningDB", "canonical_key", "conv_key",
           "attention_key", "bucket_key", "amp_key", "collective_key",
           "epilogue_key", "xent_key", "embedding_key", "evidence"]


def evidence(measured: dict) -> dict:
    """The canonical `measured` block every writer attaches to an entry:
    {arm: {"median_s": ..., "band": ...}} distilled from full
    tools/_timing.measure dicts. Offline sweeps (tools/tune.py) and
    explore-mode promotions (tuning/learned/explore.py) both go through
    here, so a candidate promoted online carries byte-identical evidence
    to one swept offline — and a candidate entry that HAS been measured
    (an in-band tie) keeps its times instead of just the decision."""
    out = {}
    for arm in sorted(measured):
        m = measured[arm]
        if not isinstance(m, dict) or m.get("median_s") is None:
            continue
        e = {"median_s": m["median_s"]}
        if m.get("band") is not None:
            e["band"] = m["band"]
        out[arm] = e
    return out


def canonical_key(op: str, shape_key: str, dtype: str, device_kind: str) -> str:
    """The one key format every layer agrees on. `shape_key` is the
    op-specific canonical shape spelling (see conv_key/attention_key);
    shapeless decisions (AMP op lists) use '-'."""
    return f"{op}|{shape_key}|{dtype}|{device_kind}"


def conv_key(n, hout, wout, cin, cout, kh, kw, strides, dilations, fmt) -> str:
    """conv2d lowering decisions key on everything the cost model sees plus
    the layout (NHWC/NCHW lower differently). Spatial extent is the OUTPUT
    tile (what the GEMM's M dim sees), so the same conv at two input pads
    that produce one output shape shares an entry."""
    return (f"n={n} out={hout}x{wout} cin={cin} cout={cout} "
            f"k={kh}x{kw} s={strides[0]}x{strides[1]} "
            f"d={dilations[0]}x{dilations[1]} {fmt}")


def attention_key(batch, num_heads, sq, sk, head_dim, causal) -> str:
    return (f"b={batch} nh={num_heads} sq={sq} sk={sk} dh={head_dim} "
            f"causal={int(bool(causal))}")


def bucket_key(var_name: str, dim: int, raw_extent: int) -> str:
    """Shape-bucketing boundary decisions: which padded extent a raw ragged
    extent rounds to (recorded so sweeps can revisit the pow2 default)."""
    return f"var={var_name} dim={dim} raw={raw_extent}"


def epilogue_key(kind: str, rows: int, channels: int, channel_pos: str,
                 act: str, has_residual: bool) -> str:
    """Fused normalize+affine+activation epilogue decisions
    (ops/pallas_kernels/epilogue.py): keyed on the canonical 2-D problem
    the kernel sees — reduction row count x channel extent — plus the
    layout ('last' = NHWC channels-last, 'row' = NCHW channels-row), the
    fused activation, and whether a residual add rides along. kind is
    'bn' (apply given stats) or 'ln' (in-kernel row statistics)."""
    return (f"kind={kind} rows={rows} c={channels} ch={channel_pos} "
            f"act={act or 'identity'} res={int(bool(has_residual))}")


def xent_key(rows: int, vocab: int) -> str:
    """Fused softmax-xent decisions (ops/pallas_kernels/xent.py): the
    kernel's problem is the flattened [rows, vocab] logits tile."""
    return f"rows={rows} v={vocab}"


def embedding_key(table: str, vocab: int, dim: int) -> str:
    """Tiered-embedding cache geometry decisions (embedding/engine.py):
    keyed on the table's identity and its row geometry — slots and prefetch
    width trade HBM footprint against hit rate for THIS table's id
    distribution, so the key must name the table, not just its shape."""
    return f"table={table} vocab={vocab} dim={dim}"


def amp_key(op_type: str) -> str:
    # AMP list membership is a per-op-TYPE decision (shapeless)
    return f"op={op_type}"


def collective_key(mesh_desc: str, payload_bytes: int) -> str:
    """Gradient-bucket sizing decisions (parallel/collective.py) key on the
    mesh layout and the TOTAL gradient payload, pow2-quantized in MB so one
    swept verdict covers the jitter between model revisions: bucket sizing
    trades per-collective launch/latency overhead against overlap
    granularity, and both scale with (ranks, payload), not with exact
    parameter shapes."""
    mb = max(1, int(payload_bytes) >> 20)
    q = 1
    while q < mb:
        q <<= 1
    return f"mesh={mesh_desc} payload={q}mb"


class TuningDB:
    """In-memory view of one JSON decision file. Thread-safe for the mixed
    trace-time (consult) / tool-time (record) usage; instances are cheap —
    the policy layer caches one per (path, mtime)."""

    def __init__(self, path: str | None = None):
        self.path = path
        self.entries: dict[str, dict] = {}
        self._lock = threading.Lock()
        self._dirty = False
        if path:
            self._load(path)

    # -- read ---------------------------------------------------------------
    def _load(self, path: str) -> None:
        if not os.path.exists(path):
            return  # missing file == empty DB (first sweep creates it)
        try:
            with open(path) as f:
                raw = json.load(f)
            if not isinstance(raw, dict):
                raise ValueError("top level is not an object")
            schema = raw.get("schema")
            if schema != DB_SCHEMA:
                raise ValueError(f"schema {schema!r} != {DB_SCHEMA}")
            entries = raw.get("entries", {})
            if not isinstance(entries, dict):
                raise ValueError("'entries' is not an object")
            self.entries = {k: v for k, v in entries.items()
                            if isinstance(v, dict) and "decision" in v}
        except (OSError, ValueError) as e:
            warnings.warn(
                f"tuning DB {path!r} unreadable ({e}); falling back to the "
                f"analytic cost model for every decision", stacklevel=3)
            self.entries = {}

    def lookup(self, key: str) -> dict | None:
        """Exact-hit tier: the entry dict, or None (caller falls to the
        analytic prior / conservative default)."""
        return self.entries.get(key)

    # -- write --------------------------------------------------------------
    def put(self, key: str, decision: dict, source: str = "swept",
            measured: dict | None = None, note: str | None = None,
            overwrite: bool = True) -> bool:
        """Insert/update one entry. `overwrite=False` keeps an existing
        swept verdict (candidates recorded at runtime must never clobber a
        measured decision)."""
        with self._lock:
            if not overwrite and key in self.entries:
                return False
            entry = {"decision": dict(decision), "source": source}
            if measured:
                entry["measured"] = measured
            if note:
                entry["note"] = note
            self.entries[key] = entry
            self._dirty = True
        return True

    def save(self, path: str | None = None) -> str:
        """Atomic temp+rename write (the PR 1 checkpoint discipline)."""
        path = path or self.path
        if not path:
            raise ValueError("TuningDB.save: no path (set FLAGS_tuning_db)")
        payload = {"schema": DB_SCHEMA, "entries": self.entries}
        d = os.path.dirname(os.path.abspath(path)) or "."
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(prefix=".tuning_db.", dir=d)
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(payload, f, indent=1, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        with self._lock:
            self._dirty = False
        self.path = path
        return path

    def __len__(self) -> int:
        return len(self.entries)
