"""Hand features over canonical shape keys — the arXiv:2008.01040 framing.

The learned tier does not parse graphs; it parses the SAME canonical shape
spellings the tuning DB keys on (db.py conv_key/attention_key/...), so a
measurement store record and a trace-time decide() query featurize
identically by construction. Features are the quantities the analytic
models already reason in — log FLOPs, log bytes moved, arithmetic
intensity, MXU/VPU tile-fill fractions, arity/layout flags — which is what
makes a regressor over a few dozen measured shapes generalize to unseen
ones instead of memorizing keys.

Only op families whose arms are timed alternatives of one categorical
decision are featurizable (conv2d lowering, attention backend, epilogue
backend, xent backend). Integer-valued levers (bucket boundaries, embedding
geometry, collective bucket sizing) and shapeless ones (AMP lists) stay on
their analytic priors — a ranking model has nothing to rank there.
"""
from __future__ import annotations

import math

__all__ = ["FAMILIES", "decision_field", "featurize", "feature_names",
           "analytic_decision", "parse_shape_key"]

# op family -> the decision dict's field (arm name == decision value)
FAMILIES = {
    "conv2d": "lowering",
    "attention": "backend",
    "epilogue": "backend",
    "xent": "backend",
    # serving control (ISSUE 20): the "shape" is a traffic regime and the
    # "arm" is a canonical knob-config spelling — the same store rows and
    # ridge fit rank serving configs the way they rank conv lowerings
    "serving.control": "knobs",
}

_DTYPE_BYTES = {
    "float64": 8, "float32": 4, "float16": 2, "bfloat16": 2,
    "int32": 4, "int8": 1,
}

_LANE = 128  # MXU/VPU lane width the tile-fill fractions quantize against


def _itemsize(dtype: str) -> float:
    return float(_DTYPE_BYTES.get(str(dtype).strip().lower(), 4))


def _fill(x: int, tile: int = _LANE) -> float:
    """Occupied fraction of the tile-padded extent: 1.0 = perfectly packed,
    small = the hardware pads most of the tile (the PR 5 cost model's
    fill(k) term, exact instead of clamped)."""
    x = max(1, int(x))
    return x / (tile * math.ceil(x / tile))


def _log(x: float) -> float:
    return math.log(max(float(x), 1e-30))


def parse_shape_key(op: str, shape_key: str) -> dict | None:
    """Tokenize one db.py shape spelling into {field: int/str}. Bare tokens
    (the conv layout suffix) land under 'fmt'. None = not parseable."""
    out: dict = {}
    try:
        for tok in str(shape_key).split():
            if "=" in tok:
                k, v = tok.split("=", 1)
                if "x" in v and k in ("out", "k", "s", "d"):
                    a, b = v.split("x", 1)
                    out[k] = (int(a), int(b))
                else:
                    try:
                        out[k] = int(v)
                    except ValueError:
                        out[k] = v
            else:
                out["fmt"] = tok
    except ValueError:
        return None
    return out if out else None


# fixed, versioned feature orders — a trained artifact stores the names it
# was fitted on, and predict refuses a mismatch (feature drift must retrain)
_CONV_FEATURES = (
    "log_m", "log_k", "log_n", "log_flops", "log_bytes", "intensity",
    "fill_m", "fill_k", "fill_n", "kernel_area", "stride", "is_1x1",
    "nhwc", "itemsize")
_ATTN_FEATURES = (
    "log_rows", "log_sq", "log_sk", "log_dh", "log_flops", "log_bytes",
    "intensity", "fill_sk", "fill_dh", "causal", "decode", "itemsize")
_EPI_FEATURES = (
    "log_rows", "log_c", "log_elems", "fill_c", "ch_last", "has_res",
    "act_identity", "kind_bn", "itemsize")
_XENT_FEATURES = ("log_rows", "log_v", "log_elems", "fill_v", "itemsize")
# serving.control regime keys (serving/control/regime.py spells them):
# arrival rate, prompt-length percentiles, output budget, prefix-hit rate,
# pool occupancy, queue depth, TTFT/SLO headroom — ratios arrive as percent
# ints so the spelling stays canonical-integer like every other shape key
_CTRL_FEATURES = (
    "log_rate", "log_p50", "log_p95", "log_out", "hit", "occ", "log_q",
    "headroom")


def feature_names(op: str) -> tuple | None:
    return {"conv2d": _CONV_FEATURES, "attention": _ATTN_FEATURES,
            "epilogue": _EPI_FEATURES, "xent": _XENT_FEATURES,
            "serving.control": _CTRL_FEATURES}.get(op)


def featurize(op: str, shape_key: str, dtype: str) -> list | None:
    """The feature vector for one (op, shape_key, dtype) — order matches
    feature_names(op). None = this key is outside the learned tier."""
    if op not in FAMILIES:
        return None
    kv = parse_shape_key(op, shape_key)
    if kv is None:
        return None
    it = _itemsize(dtype)
    try:
        if op == "conv2d":
            n = kv["n"]
            hout, wout = kv["out"]
            cin, cout = kv["cin"], kv["cout"]
            kh, kw = kv["k"]
            sh, _sw = kv.get("s", (1, 1))
            m = n * hout * wout            # GEMM M (output pixels)
            k = cin * kh * kw              # GEMM K (patch extent)
            flops = 2.0 * m * k * cout
            bytes_ = it * (m * k + k * cout + m * cout)
            return [
                _log(m), _log(k), _log(cout), _log(flops), _log(bytes_),
                _log(flops) - _log(bytes_), _fill(m, 8), _fill(k),
                _fill(cout), float(kh * kw), float(sh),
                float(kh == 1 and kw == 1),
                float(kv.get("fmt") == "NHWC"), it,
            ]
        if op == "attention":
            b, nh = kv["b"], kv["nh"]
            sq, sk, dh = kv["sq"], kv["sk"], kv["dh"]
            rows = b * nh * sq
            flops = 4.0 * b * nh * sq * sk * dh
            bytes_ = it * b * nh * (2 * sq * dh + 2 * sk * dh + sq * sk)
            return [
                _log(rows), _log(sq), _log(sk), _log(dh), _log(flops),
                _log(bytes_), _log(flops) - _log(bytes_), _fill(sk),
                _fill(dh), float(kv.get("causal", 0)), float(sq == 1), it,
            ]
        if op == "epilogue":
            rows, c = kv["rows"], kv["c"]
            return [
                _log(rows), _log(c), _log(rows * c), _fill(c),
                float(kv.get("ch") == "last"), float(kv.get("res", 0)),
                float(kv.get("act", "identity") == "identity"),
                float(kv.get("kind") == "bn"), it,
            ]
        if op == "xent":
            rows, v = kv["rows"], kv["v"]
            return [_log(rows), _log(v), _log(rows * v), _fill(v), it]
        if op == "serving.control":
            return [
                _log(float(kv["rate"])), _log(float(kv["p50"])),
                _log(float(kv["p95"])), _log(float(kv["out"])),
                float(kv["hit"]) / 100.0, float(kv["occ"]) / 100.0,
                _log(float(kv["q"]) + 1.0),
                float(kv.get("hr", 100)) / 100.0,
            ]
    except (KeyError, TypeError, ValueError):
        return None
    return None


def decision_field(op: str) -> str | None:
    return FAMILIES.get(op)


def analytic_decision(op: str, shape_key: str, dtype: str) -> str | None:
    """The arm the analytic tier would pick for this key — the baseline a
    trained model's holdout ranking accuracy is judged against
    (tools/costmodel.py eval, gate.py --costmodel). Mirrors the registered
    priors: the PR 5 tile-fill-vs-HBM model for convs, the measured
    dispatch rule for attention, XLA for epilogues, Pallas for xent."""
    kv = parse_shape_key(op, shape_key)
    if kv is None:
        return None
    try:
        if op == "conv2d":
            from ...ops.nn_ops import _igemm_predict_win

            hout, wout = kv["out"]
            kh, kw = kv["k"]
            return "igemm" if _igemm_predict_win(
                kv["n"], hout, wout, kv["cin"], kv["cout"], kh, kw,
                int(_itemsize(dtype))) else "direct"
        if op == "attention":
            # the attention_ops prior sans platform probes: XLA at the
            # train sizes, the bundled flash kernel past S=1024
            return "flash_bundled" if (kv["sq"] > 1024
                                       and kv["sq"] == kv["sk"]) else "xla"
        if op == "epilogue":
            return "xla"
        if op == "xent":
            return "pallas"
    except (KeyError, TypeError, ValueError):
        return None
    return None
