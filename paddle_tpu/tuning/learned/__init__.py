"""Learned cost-model subsystem (ROADMAP item 3, the measured half).

Three parts, one package:

  store.py    — append-only JSONL measurement store every sweep / A/B
                harness / bench round / explore probe feeds;
  features.py + model.py
              — hand features over the canonical shape keys and the
                numpy-only seeded ridge regressor tools/costmodel.py
                trains per (op, device_kind);
  explore.py  — bounded online exploration (FLAGS_tuning_mode=explore)
                that promotes candidate keys to swept verdicts from the
                executor's idle gaps.

This module owns the glue the policy layer consults: the (path, mtime)
model cache with the tuning-DB read discipline (missing file = no learned
tier, corrupt file = warn ONCE + fail open), `decide_learned()` — the new
tier between exact-DB-hit and analytic prior — and the provenance counters
behind the tuning.learned.* metrics.
"""
from __future__ import annotations

import os
import threading
import warnings

from ... import flags
from . import explore, features, model, store
from .explore import maybe_explore
from .model import (ENVELOPE_MARGIN, MODEL_SCHEMA, RANK_ACC_FLOOR,
                    eval_model, load_model, predict_times, save_model,
                    train_model)
from .store import (STORE_SCHEMA, iter_records, measurements_path, record,
                    record_measured, recording_enabled)

__all__ = [
    "store", "features", "model", "explore",
    "STORE_SCHEMA", "MODEL_SCHEMA", "RANK_ACC_FLOOR", "ENVELOPE_MARGIN",
    "measurements_path", "recording_enabled", "record", "record_measured",
    "iter_records", "train_model", "eval_model", "save_model", "load_model",
    "predict_times", "maybe_explore",
    "model_path", "get_model", "invalidate_model_cache", "decide_learned",
    "bump_prediction", "bump_fallback", "bump_promotion",
    "snapshot", "reset_counters",
]

_lock = threading.Lock()
_model_cache: tuple[str, float, dict | None] | None = None
_warned_paths: set[str] = set()

# learned-tier provenance: predictions that stood, fallbacks by reason,
# explore promotions — bench.py's tuning block and gate.py's fallback-rate
# ceiling read the snapshot
_counts = {"predictions": 0, "fallbacks": 0, "promotions": 0}
_fallback_reasons: dict[str, int] = {}


def model_path() -> str | None:
    """FLAGS_tuning_model, or derived from FLAGS_tuning_db
    (`<db stem>.model.json` next to it). None = no learned tier."""
    p = str(flags.get_flag("tuning_model")).strip()
    if p:
        return p
    db = str(flags.get_flag("tuning_db")).strip()
    if not db:
        return None
    stem, _ = os.path.splitext(db)
    return stem + ".model.json"


def get_model() -> dict | None:
    """The trained artifact for model_path(), reloaded when the file's
    mtime moves (a costmodel.py retrain mid-session is picked up without a
    restart — the get_db discipline). Missing file: silently no model.
    Corrupt file: warn once per path, then behave as missing until the
    file changes — the learned tier may cost coverage, never a run."""
    global _model_cache
    path = model_path()
    if not path:
        return None
    try:
        mtime = os.stat(path).st_mtime
    except OSError:
        mtime = -1.0
    with _lock:
        if _model_cache and _model_cache[0] == path \
                and _model_cache[1] == mtime:
            return _model_cache[2]
    try:
        m = load_model(path)
    except ValueError as e:
        if path not in _warned_paths:
            _warned_paths.add(path)
            warnings.warn(
                f"tuning cost model {path!r} {e}; the learned tier is "
                f"disabled — falling back to the analytic prior",
                stacklevel=3)
        m = None
    with _lock:
        _model_cache = (path, mtime, m)
    return m


def invalidate_model_cache() -> None:
    global _model_cache
    with _lock:
        _model_cache = None
        _warned_paths.clear()


def bump_prediction(op: str) -> None:
    from ... import observability as obs

    with _lock:
        _counts["predictions"] += 1
    obs.counter_inc("tuning.learned.predictions", labels={"op": op})


def bump_fallback(op: str, reason: str) -> None:
    from ... import observability as obs

    with _lock:
        _counts["fallbacks"] += 1
        _fallback_reasons[reason] = _fallback_reasons.get(reason, 0) + 1
    obs.counter_inc("tuning.learned.fallbacks",
                    labels={"op": op, "reason": reason})


def bump_promotion(op: str) -> None:
    from ... import observability as obs

    with _lock:
        _counts["promotions"] += 1
    obs.counter_inc("tuning.learned.explore_promotions", labels={"op": op})


def reset_counters() -> None:
    with _lock:
        _counts.update(predictions=0, fallbacks=0, promotions=0)
        _fallback_reasons.clear()


def snapshot() -> dict:
    """Learned-tier provenance for the bench artifact's tuning block:
    attempts = keys the tier tried to predict; fallback_rate is what
    gate.py's --costmodel ceiling reads."""
    with _lock:
        c = dict(_counts)
        reasons = dict(_fallback_reasons)
    attempts = c["predictions"] + c["fallbacks"]
    return {
        **c,
        "attempts": attempts,
        "fallback_rate": round(c["fallbacks"] / attempts, 4)
        if attempts else None,
        "fallback_reasons": reasons,
    }


def decide_learned(op: str, key: str, validate=None) -> dict | None:
    """The policy tier between exact-DB-hit and analytic prior: predict
    per-arm times for this (unseen) key and return the argmin as a
    decision dict — or None (with the fallback reason counted) so decide()
    falls through to the analytic prior. Absence of a model, or of any
    trained group for this op, is not an attempt — like a DB miss, it is
    counted nowhere."""
    if op not in features.FAMILIES:
        return None
    m = get_model()
    if m is None:
        return None
    parts = key.split("|")
    if len(parts) != 4 or parts[0] != op:
        return None
    _, shape_key, dtype, dev = parts
    times, info = predict_times(m, op, shape_key, dtype, dev)
    if times is None:
        reason = info.get("reason", "unknown")
        if reason != "no_group":
            bump_fallback(op, reason)
        return None
    arm = min(sorted(times), key=lambda a: times[a])
    decision = {info.get("decision_field",
                         features.decision_field(op)): arm}
    if validate is not None and not validate(decision):
        bump_fallback(op, "validate")
        return None
    bump_prediction(op)
    return decision
