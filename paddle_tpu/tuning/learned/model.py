"""Seeded-deterministic ridge regression over the hand features.

One tiny linear model per (op, device_kind, arm): standardized features ->
log(median seconds), closed-form ridge solve (numpy only — the tuner must
never grow a dependency). Per-arm time models compose into an arm RANKING
(argmin of predicted times), which is all the policy tier consumes — the
absolute times only have to be monotone enough to order two lowerings.

The artifact (MODEL_SCHEMA = 1) is a single JSON file next to the tuning
DB, written atomically (temp+rename, the PR 1 checkpoint discipline):

    {
      "schema": 1, "seed": 0, "ridge": 1.0, "holdout_frac": 0.25,
      "groups": {
        "conv2d|cpu": {
          "decision_field": "lowering",
          "feature_names": [...],            # refuse drift at predict time
          "mean": [...], "std": [...],       # train standardization
          "fmin": [...], "fmax": [...],      # extrapolation envelope
          "arms": {"direct": {"w": [...]}, "igemm": {"w": [...]}},
          "n_train_keys": 21, "holdout_keys": ["<shape_key>|<dtype>", ...],
          "holdout": {"rank_acc": 0.83, "analytic_rank_acc": 0.5,
                      "mae_log": 0.21, "n": 6}
        }, ...
      }
    }

Confidence gates at predict time (both must pass, else the caller falls
back to the analytic prior — arXiv:2008.01040's lesson that a learned
model is a prior, not an oracle):

  * holdout gate — the group's held-out arm-ranking accuracy must clear
    RANK_ACC_FLOOR (a model that cannot rank its own holdout has no
    business ranking production shapes);
  * envelope gate — every feature must lie within the training range
    widened by ENVELOPE_MARGIN of its span (linear-in-log models
    extrapolate confidently and wrongly; a 10x-beyond-envelope shape is
    rejected, not predicted).

Cross-device transfer: when no group exists for the current device_kind,
the same-op group from another device (CPU-collected data, typically) is
used for arm RANKING only — relative ordering transfers across devices far
better than absolute times (the TVM transfer observation), and both gates
still apply.
"""
from __future__ import annotations

import json
import os
import tempfile

import numpy as np

from . import features

MODEL_SCHEMA = 1
RANK_ACC_FLOOR = 0.6     # holdout confidence gate
ENVELOPE_MARGIN = 0.25   # fraction of the train span features may overhang
RANK_TIE_BAND = 0.05     # near-ties count as correctly ranked (gate.py band)
MIN_GROUP_KEYS = 6       # fewer measured keys cannot support a holdout
MIN_ARM_SAMPLES = 3      # fewer rows than this cannot fit an arm

__all__ = ["MODEL_SCHEMA", "RANK_ACC_FLOOR", "ENVELOPE_MARGIN",
           "train_model", "eval_model", "save_model", "load_model",
           "predict_times", "group_samples"]


def group_samples(records) -> dict:
    """Fold store records into {(op, device_kind): {(shape_key, dtype):
    {arm: median_s}}}. Multiple records of one (key, arm) reduce by median
    — repeated sweeps refine, not duplicate. Non-featurizable op families
    and unusable rows are dropped."""
    acc: dict = {}
    for rec in records:
        op = rec.get("op")
        if op not in features.FAMILIES:
            continue
        t = rec.get("median_s")
        if not isinstance(t, (int, float)) or t <= 0:
            continue
        g = acc.setdefault((op, str(rec.get("device_kind", "cpu"))), {})
        k = (str(rec.get("shape_key", "")), str(rec.get("dtype", "")))
        g.setdefault(k, {}).setdefault(str(rec["arm"]), []).append(float(t))
    out: dict = {}
    for gk, keys in acc.items():
        out[gk] = {k: {a: float(np.median(ts)) for a, ts in arms.items()}
                   for k, arms in keys.items()}
    return out


def _ridge_fit(X: np.ndarray, y: np.ndarray, ridge: float) -> np.ndarray:
    """Closed-form ridge with an unpenalized bias column (penalizing the
    intercept would drag every prediction toward 1 second)."""
    Xb = np.concatenate([X, np.ones((X.shape[0], 1))], axis=1)
    reg = ridge * np.eye(Xb.shape[1])
    reg[-1, -1] = 0.0
    return np.linalg.solve(Xb.T @ Xb + reg, Xb.T @ y)


def _predict_arm(w, x_std) -> float:
    xb = np.concatenate([x_std, [1.0]])
    return float(np.exp(np.clip(xb @ np.asarray(w, dtype=np.float64),
                                -60.0, 60.0)))


def _rank_correct(times: dict, picked: str | None) -> bool:
    """A pick is correct when its measured time is within RANK_TIE_BAND of
    the measured best — the same near-tie tolerance the A/B verdicts use
    (a 'wrong' pick inside machine noise is not a ranking error)."""
    if picked is None or picked not in times:
        return False
    return times[picked] <= min(times.values()) * (1.0 + RANK_TIE_BAND)


def train_model(records, seed: int = 0, holdout_frac: float = 0.25,
                ridge: float = 1.0) -> dict:
    """Fit every (op, device_kind) group with enough measured keys.
    Deterministic for a given (records, seed): keys are sorted before the
    seeded permutation, so CI retrains reproduce the committed artifact
    byte-for-byte. The holdout split is BY KEY (all arms of a shape stay
    on one side — splitting arms of one shape across the fence would leak
    the very timings the holdout is supposed to be blind to)."""
    groups = {}
    for (op, dev), keys in sorted(group_samples(records).items()):
        names = features.feature_names(op)
        usable = []
        for k in sorted(keys):
            shape_key, dtype = k
            f = features.featurize(op, shape_key, dtype)
            arms = {a: t for a, t in keys[k].items() if t > 0}
            if f is not None and len(arms) >= 2:
                usable.append((k, np.asarray(f, dtype=np.float64), arms))
        if len(usable) < MIN_GROUP_KEYS:
            continue
        rng = np.random.default_rng(seed)
        perm = rng.permutation(len(usable))
        n_hold = max(1, int(round(holdout_frac * len(usable))))
        hold_idx = set(int(i) for i in perm[:n_hold])
        train = [u for i, u in enumerate(usable) if i not in hold_idx]
        hold = [u for i, u in enumerate(usable) if i in hold_idx]
        if len(train) < MIN_GROUP_KEYS - 1:
            continue
        Xtr = np.stack([f for _, f, _ in train])
        mean = Xtr.mean(axis=0)
        std = Xtr.std(axis=0)
        std[std < 1e-12] = 1.0
        arms_w = {}
        for arm in sorted({a for _, _, arms in train for a in arms}):
            rows = [(f, arms[arm]) for _, f, arms in train if arm in arms]
            if len(rows) < MIN_ARM_SAMPLES:
                continue
            Xa = (np.stack([f for f, _ in rows]) - mean) / std
            ya = np.log(np.asarray([t for _, t in rows]))
            arms_w[arm] = {"w": [round(float(v), 10)
                                 for v in _ridge_fit(Xa, ya, ridge)]}
        if len(arms_w) < 2:
            continue  # one fitted arm cannot rank anything
        group = {
            "decision_field": features.decision_field(op),
            "feature_names": list(names),
            "mean": [round(float(v), 10) for v in mean],
            "std": [round(float(v), 10) for v in std],
            "fmin": [round(float(v), 10) for v in Xtr.min(axis=0)],
            "fmax": [round(float(v), 10) for v in Xtr.max(axis=0)],
            "arms": arms_w,
            "n_train_keys": len(train),
            "holdout_keys": sorted(f"{k[0]}|{k[1]}" for k, _, _ in hold),
        }
        group["holdout"] = _eval_group(op, group, hold)
        groups[f"{op}|{dev}"] = group
    return {"schema": MODEL_SCHEMA, "seed": int(seed), "ridge": float(ridge),
            "holdout_frac": float(holdout_frac), "groups": groups}


def _group_predict(group: dict, f: np.ndarray) -> dict:
    x = (f - np.asarray(group["mean"])) / np.asarray(group["std"])
    return {arm: _predict_arm(spec["w"], x)
            for arm, spec in group["arms"].items()}


def _eval_group(op: str, group: dict, hold) -> dict:
    """Holdout metrics: learned vs analytic arm-ranking accuracy on the
    SAME keys, plus mean |log t_pred - log t_meas| over measured arms."""
    n = correct = analytic_correct = 0
    abs_log_err = []
    for (shape_key, dtype), f, arms in hold:
        pred = _group_predict(group, np.asarray(f, dtype=np.float64))
        scored = {a: pred[a] for a in arms if a in pred}
        if len(scored) < 2:
            continue
        n += 1
        pick = min(sorted(scored), key=lambda a: scored[a])
        correct += _rank_correct(arms, pick)
        analytic_correct += _rank_correct(
            arms, features.analytic_decision(op, shape_key, dtype))
        abs_log_err.extend(abs(np.log(pred[a]) - np.log(arms[a]))
                           for a in scored)
    return {
        "n": n,
        "rank_acc": round(correct / n, 4) if n else None,
        "analytic_rank_acc": round(analytic_correct / n, 4) if n else None,
        "mae_log": round(float(np.mean(abs_log_err)), 4)
        if abs_log_err else None,
    }


def eval_model(model: dict, records) -> dict:
    """Re-score every group against its RECORDED holdout keys in a dataset
    — the gate.py --costmodel path: committed model + committed dataset
    must reproduce (and clear) the training-time holdout numbers."""
    samples = group_samples(records)
    out = {}
    for gkey, group in sorted(model.get("groups", {}).items()):
        op, dev = gkey.split("|", 1)
        keys = samples.get((op, dev), {})
        hold = []
        want = set(group.get("holdout_keys", []))
        for (shape_key, dtype), arms in sorted(keys.items()):
            if f"{shape_key}|{dtype}" not in want:
                continue
            f = features.featurize(op, shape_key, dtype)
            if f is not None and len(arms) >= 2:
                hold.append(((shape_key, dtype),
                             np.asarray(f, dtype=np.float64), arms))
        out[gkey] = _eval_group(op, group, hold)
    return {"groups": out}


def predict_times(model: dict, op: str, shape_key: str, dtype: str,
                  device_kind: str, gated: bool = True):
    """Per-arm predicted times for one key, or (None, {"reason": ...}).
    With gated=True (the policy tier) the holdout + envelope confidence
    gates apply; gated=False is the eval path's raw prediction."""
    groups = model.get("groups", {})
    gkey = f"{op}|{device_kind}"
    info: dict = {}
    group = groups.get(gkey)
    if group is None:
        # cross-device transfer: same-op group from another device ranks
        # arms (CPU-first — the committed dataset's device)
        others = sorted(g for g in groups if g.split("|", 1)[0] == op)
        others.sort(key=lambda g: (not g.endswith("|cpu"), g))
        if not others:
            return None, {"reason": "no_group"}
        group = groups[others[0]]
        info["transfer_from"] = others[0]
    f = features.featurize(op, shape_key, dtype)
    if f is None:
        return None, {"reason": "features"}
    names = features.feature_names(op)
    if list(group.get("feature_names", [])) != list(names):
        return None, {"reason": "feature_drift"}
    fv = np.asarray(f, dtype=np.float64)
    if gated:
        hold = group.get("holdout", {})
        acc = hold.get("rank_acc")
        if acc is None or acc < RANK_ACC_FLOOR:
            return None, {"reason": "accuracy", **info}
        fmin = np.asarray(group["fmin"])
        fmax = np.asarray(group["fmax"])
        span = np.maximum(fmax - fmin, 1e-9)
        lo = fmin - ENVELOPE_MARGIN * span
        hi = fmax + ENVELOPE_MARGIN * span
        if bool(np.any(fv < lo)) or bool(np.any(fv > hi)):
            return None, {"reason": "envelope", **info}
    times = _group_predict(group, fv)
    info["decision_field"] = group.get(
        "decision_field", features.decision_field(op))
    return times, info


def save_model(model: dict, path: str) -> str:
    """Atomic temp+rename write, sorted keys — retraining on identical data
    with an identical seed reproduces the artifact byte-for-byte."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(prefix=".costmodel.", dir=d)
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(model, f, indent=1, sort_keys=True)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def load_model(path: str) -> dict | None:
    """None for a missing file (no model yet — the learned tier simply
    does not exist); ValueError for a present-but-unusable one (the policy
    layer warns once and fails open to the analytic tier, the tuning-DB
    read discipline)."""
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            raw = json.load(f)
    except (OSError, ValueError) as e:
        raise ValueError(f"unreadable ({e})") from e
    if not isinstance(raw, dict):
        raise ValueError("top level is not an object")
    if raw.get("schema") != MODEL_SCHEMA:
        raise ValueError(f"schema {raw.get('schema')!r} != {MODEL_SCHEMA}")
    if not isinstance(raw.get("groups"), dict):
        raise ValueError("'groups' is not an object")
    return raw
