"""Bounded online exploration: sweep-in-production (FLAGS_tuning_mode=explore).

Consult mode leaves `candidate` DB entries forever unmeasured unless an
offline `tools/tune.py --what candidates` run happens to visit the box.
Explore mode closes that loop from inside the running job, with the TVM
bounds (arXiv:1802.04799) that make online measurement safe:

  * paced      — at most ONE candidate is probed every
                 FLAGS_tuning_explore_every executor steps (the probe rides
                 the window-drain idle gap at the end of run_async; steady
                 training throughput, not the probe, owns the device);
  * bounded    — each probe is a handful of tiny timed windows
                 (EXPLORE_ITERS x EXPLORE_PASSES), never an open-ended
                 sweep;
  * band-gated — a verdict is accepted ONLY outside the interference band
                 (max of the 5% floor and every arm's measured spread); a
                 tie keeps the candidate AND attaches the evidence, so a
                 later offline sweep starts from data, not zero;
  * write-equal — promotions land as `source="swept"` entries with the
                 SAME measured-evidence schema offline sweeps write
                 (db.evidence), so nothing downstream can tell who swept.

Every probe's raw windows also land in the measurement store
(source="explore") — exploration grows the learned tier's training set as
a side effect, which is the whole point.
"""
from __future__ import annotations

import threading
import time

import numpy as np

from ... import flags
from ..db import evidence
from . import features, store

# tools/_timing.DEFAULT_BAND (tools/ is not importable from the package):
# margins inside 5% are machine noise, not a measured win
EXPLORE_BAND = 0.05
EXPLORE_ITERS = 2
EXPLORE_PASSES = 3

__all__ = ["maybe_explore", "explore_one", "reset_state",
           "EXPLORE_BAND", "EXPLORE_ITERS", "EXPLORE_PASSES"]

_lock = threading.Lock()
_state = {"steps": 0, "done": set()}


def reset_state() -> None:
    with _lock:
        _state["steps"] = 0
        _state["done"] = set()


def maybe_explore() -> dict | None:
    """The executor's per-step hook: cheap no-op outside explore mode; in
    it, every Nth step probes the next unmeasured candidate. Returns the
    probe result dict (or None) — callers ignore it; tests don't."""
    from .. import policy

    if policy.mode() != "explore":
        return None
    try:
        every = int(flags.get_flag("tuning_explore_every"))
    except (TypeError, ValueError):
        return None
    if every <= 0:
        return None
    with _lock:
        _state["steps"] += 1
        if _state["steps"] % every:
            return None
    return explore_one()


def explore_one() -> dict | None:
    """Probe the first unvisited candidate key for THIS device_kind.
    Unbuildable keys (op families without an arm builder, platform-gated
    kernels) are marked visited and skipped — explore never retries a key
    in-process, so a stuck candidate cannot eat every idle gap."""
    from .. import policy

    db = policy.get_db()
    dk = policy.device_kind()
    for key in sorted(db.entries):
        entry = db.entries[key]
        if entry.get("source") != "candidate":
            continue
        if not key.endswith("|" + dk):
            continue
        with _lock:
            if key in _state["done"]:
                continue
            _state["done"].add(key)
        out = _probe(db, key, entry)
        if out is not None:
            return out
    return None


def _probe(db, key: str, entry: dict) -> dict | None:
    from .. import policy

    parts = key.split("|")
    if len(parts) != 4:
        return None
    op, shape_key, dtype, _dev = parts
    field = features.decision_field(op)
    if field is None:
        return None
    arms = _build_arms(op, shape_key, dtype)
    if not arms or len(arms) < 2:
        return None
    measured = {a: _measure(arms[a]) for a in sorted(arms)}
    store.record_measured(key, measured, source="explore")
    base = str(entry.get("decision", {}).get(field, ""))
    if base not in measured:
        base = sorted(measured)[0]
    best = min(sorted(measured), key=lambda a: measured[a]["median_s"])
    band = max([EXPLORE_BAND] + [m["band"] for m in measured.values()])
    verdict = _verdict(measured[base]["median_s"],
                       measured[best]["median_s"], band) \
        if best != base else "retire"
    path = str(flags.get_flag("tuning_db")).strip()
    if verdict == "tie":
        # inside the band: the analytic candidate stands, but now with
        # measured evidence attached (the db.py satellite fix — candidates
        # carry times when available)
        db.put(key, entry.get("decision", {}), source="candidate",
               measured=evidence(measured),
               note="explore: tie inside band")
        result = {"key": key, "verdict": "tie", "decision": None}
    else:
        winner = best if verdict == "keep" else base
        db.put(key, {field: winner}, source="swept",
               measured=evidence(measured),
               note=f"explore: verdict={verdict} base={base}")
        _bump_promotion(op)
        result = {"key": key, "verdict": verdict, "decision": winner}
    if path:
        try:
            db.save(path)
            policy.invalidate_db_cache()
        except OSError:
            pass  # read-only FS: the in-memory entry still serves
    result["measured"] = {a: m["median_s"] for a, m in measured.items()}
    return result


def _bump_promotion(op: str) -> None:
    from . import bump_promotion

    bump_promotion(op)


def _verdict(base_s: float, cand_s: float, band: float) -> str:
    if cand_s < (1.0 - band) * base_s:
        return "keep"
    if cand_s > (1.0 + band) * base_s:
        return "retire"
    return "tie"


def _measure(fn) -> dict:
    """Tiny bounded version of tools/_timing.measure: one warmup call
    (compile), then EXPLORE_PASSES windows of EXPLORE_ITERS calls each."""
    import jax

    jax.block_until_ready(fn())
    windows = []
    for _ in range(EXPLORE_PASSES):
        t0 = time.perf_counter()
        out = None
        for _ in range(EXPLORE_ITERS):
            out = fn()
        jax.block_until_ready(out)
        windows.append((time.perf_counter() - t0) / EXPLORE_ITERS)
    ws = np.asarray(windows, dtype=np.float64)
    med = float(np.median(ws))
    return {
        "median_s": med,
        "min_s": float(ws.min()),
        "windows_s": [round(float(w), 9) for w in windows],
        "band": round(float((ws.max() - ws.min()) / med), 4)
        if med > 0 else 0.0,
    }


def _build_arms(op: str, shape_key: str, dtype: str) -> dict | None:
    """Reconstruct the timed arms for one candidate key — the same
    fwd+bwd jitted closures tools/tune.py sweeps, rebuilt from the key
    alone. Families explore cannot rebuild (paged decode needs a live KV
    pool; epilogue/xent arms are platform-gated) return None and are
    skipped — offline sweeps remain their path to a verdict."""
    kv = features.parse_shape_key(op, shape_key)
    if kv is None:
        return None
    try:
        if op == "conv2d":
            return _conv_arms(kv, dtype)
        if op == "attention" and kv.get("sq", 0) > 1 \
                and kv.get("sq") == kv.get("sk"):
            return _attention_arms(kv, dtype)
    except Exception:
        return None  # an unbuildable arm must never crash the train loop
    return None


def _conv_arms(kv: dict, dtype: str) -> dict | None:
    import jax
    import jax.numpy as jnp

    from ...ops.nn_ops import _conv2d_igemm_f32

    n, (hout, wout) = kv["n"], kv["out"]
    cin, cout = kv["cin"], kv["cout"]
    kh, kw = kv["k"]
    strides, d = kv.get("s", (1, 1)), kv.get("d", (1, 1))
    fmt = kv.get("fmt", "NHWC")
    if fmt not in ("NHWC", "NCHW"):
        return None
    # any VALID-padded input reproducing the keyed output tile times the
    # same GEMM (the key deliberately forgets the padding)
    h = (hout - 1) * strides[0] + (kh - 1) * d[0] + 1
    w = (wout - 1) * strides[1] + (kw - 1) * d[1] + 1
    pads = ((0, 0), (0, 0))
    rhs = "HWIO" if fmt == "NHWC" else "OIHW"
    rng = np.random.default_rng(0)
    x_shape = (n, h, w, cin) if fmt == "NHWC" else (n, cin, h, w)
    w_shape = (kh, kw, cin, cout) if fmt == "NHWC" else (cout, cin, kh, kw)
    x = jax.device_put(rng.standard_normal(
        x_shape, dtype=np.float32).astype(dtype))
    wt = jax.device_put((rng.standard_normal(
        w_shape, dtype=np.float32) * 0.05).astype(dtype))

    def loss_direct(xx, ww):
        out = jax.lax.conv_general_dilated(
            xx, ww, window_strides=strides, padding=pads,
            rhs_dilation=d, dimension_numbers=(fmt, rhs, fmt))
        return jnp.sum(jnp.square(out.astype(jnp.float32)))

    def loss_igemm(xx, ww):
        return jnp.sum(jnp.square(
            _conv2d_igemm_f32(xx, ww, strides, pads, d, fmt)))

    f_direct = jax.jit(jax.grad(loss_direct, argnums=(0, 1)))
    f_igemm = jax.jit(jax.grad(loss_igemm, argnums=(0, 1)))
    return {"direct": lambda: f_direct(x, wt)[1],
            "igemm": lambda: f_igemm(x, wt)[1]}


def _attention_arms(kv: dict, dtype: str) -> dict | None:
    import jax
    import jax.numpy as jnp

    from ...ops.attention_ops import (_flash_bundled_ok, _pallas_short128_ok,
                                      _pallas_short_ok, _reference_attention)

    b, nh, s, dh = kv["b"], kv["nh"], kv["sq"], kv["dh"]
    causal = bool(kv.get("causal", 0))
    rng = np.random.default_rng(0)
    q, k, v = (jax.device_put(rng.standard_normal(
        (b, nh, s, dh), dtype=np.float32).astype(dtype)) for _ in range(3))
    sm = dh ** -0.5

    def mk(attn_fn):
        def loss(qq, kk, vv):
            return jnp.sum(jnp.square(
                attn_fn(qq, kk, vv).astype(jnp.float32)))
        g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        return lambda: g(q, k, v)[0]

    arms = {"xla": mk(lambda qq, kk, vv: _reference_attention(
        qq, kk, vv, None, causal, sm))}
    if _pallas_short_ok(q.shape, k.shape, None):
        from ...ops.pallas_kernels import attention as psa

        arms["pallas_short"] = mk(lambda qq, kk, vv: psa.short_seq_attention(
            qq, kk, vv, causal=causal, sm_scale=sm))
    if _pallas_short128_ok(q.shape, k.shape, None):
        from ...ops.pallas_kernels import short_attention as s128

        arms["pallas_short128"] = mk(
            lambda qq, kk, vv: s128.short128_attention(
                qq, kk, vv, causal=causal, sm_scale=sm))
    if _flash_bundled_ok(q.shape, k.shape, q.dtype):
        from jax.experimental.pallas.ops.tpu import flash_attention as fa

        arms["flash_bundled"] = mk(lambda qq, kk, vv: fa.flash_attention(
            qq, kk, vv, causal=causal, sm_scale=sm))
    return arms
