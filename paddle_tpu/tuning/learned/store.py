"""Append-only measurement store: the raw timings behind every verdict.

Every `tools/tune.py` sweep arm, every A/B harness pass, every bench round
and every explore-mode probe used to discard its window timings the moment
the keep/retire verdict was spoken. This store keeps them: one JSON line
per (key, arm) measurement, schema-versioned, so `tools/costmodel.py` can
train the learned tier (arXiv:2008.01040 — measured (op, shape, dtype)
timings generalize to unseen shapes) from data the existing workflows
produce as a side effect.

Record shape (STORE_SCHEMA = 1):

    {
      "schema": 1,
      "op": "conv2d",                  # op family (or "ab.*" / "bench")
      "shape_key": "n=8 out=...",      # the db.py canonical shape spelling
      "dtype": "float32",
      "device_kind": "cpu",
      "arm": "igemm",                  # arm name == decision value
      "median_s": 0.0123,              # _timing.measure summary fields
      "min_s": 0.0119,
      "band": 0.02,                    # interference band of the windows
      "windows_s": [...],              # raw per-window seconds
      "source": "sweep",               # sweep | ab | bench | explore
      "host": {"host": ..., "platform": ..., "cpus": ...},
      "ts": 1754...                    # unix seconds, int
    }

Write discipline is the observability JSONL one (exporters.py): each record
is one canonical compact line written with a single O_APPEND write, so
concurrent sweeps interleave whole lines, never bytes. Read discipline is
fail-open like the tuning DB: a missing file is an empty dataset; corrupt
or wrong-schema lines are skipped, not fatal — a damaged store may cost
training data, never a run.
"""
from __future__ import annotations

import json
import os
import platform
import socket
import time

from ... import flags

STORE_SCHEMA = 1

__all__ = ["STORE_SCHEMA", "measurements_path", "recording_enabled",
           "host_fingerprint", "record", "record_measured", "iter_records"]


def measurements_path() -> str | None:
    """FLAGS_tuning_measurements, or derived from FLAGS_tuning_db
    (`<db stem>.measurements.jsonl` next to it) so a sweep with a DB
    configured grows a dataset without extra flags. None = no store."""
    p = str(flags.get_flag("tuning_measurements")).strip()
    if p:
        return p
    db = str(flags.get_flag("tuning_db")).strip()
    if not db:
        return None
    stem, _ = os.path.splitext(db)
    return stem + ".measurements.jsonl"


def recording_enabled(tool: bool = False) -> bool:
    """FLAGS_tuning_record gate. 'on'/'off' are absolute; 'auto' (default)
    records from the tools (sweeps, A/B harnesses — `tool=True`) whenever a
    store path resolves, and from the runtime only in sweep/explore mode
    (consult-mode training steps must not grow files as a side effect)."""
    r = str(flags.get_flag("tuning_record")).strip().lower()
    if r == "off":
        return False
    if measurements_path() is None:
        return False
    if r == "on" or tool:
        return True
    m = str(flags.get_flag("tuning_mode")).strip().lower()
    return m in ("sweep", "explore")


_host: dict | None = None


def host_fingerprint() -> dict:
    """Which box produced the numbers — a model trained on a quiet CI
    runner must be auditable against data from a loaded dev box."""
    global _host
    if _host is None:
        _host = {
            "host": socket.gethostname(),
            "platform": platform.platform(),
            "cpus": os.cpu_count() or 0,
        }
    return _host


def _jsonl_line(record: dict) -> bytes:
    # exporters.py's canonical encoding: compact separators + sorted keys
    return (json.dumps(record, sort_keys=True,
                       separators=(",", ":")) + "\n").encode()


def record(op: str, shape_key: str, dtype: str, device_kind: str, arm: str,
           *, windows_s=None, median_s=None, min_s=None, band=None,
           source: str = "sweep", extras: dict | None = None,
           path: str | None = None) -> bool:
    """Append one measurement line. Returns True if a line landed. Never
    raises on I/O trouble (read-only FS etc.) — measurement capture is a
    side effect, not a contract the measured run depends on."""
    path = path or measurements_path()
    if not path:
        return False
    ws = [round(float(w), 9) for w in windows_s] if windows_s else []
    if median_s is None and ws:
        xs = sorted(ws)
        n = len(xs)
        median_s = xs[n // 2] if n % 2 else 0.5 * (xs[n // 2 - 1] + xs[n // 2])
    rec = {
        "schema": STORE_SCHEMA,
        "op": op,
        "shape_key": shape_key,
        "dtype": dtype,
        "device_kind": device_kind,
        "arm": arm,
        "median_s": round(float(median_s), 9) if median_s is not None else None,
        "min_s": round(float(min_s), 9) if min_s is not None else (
            round(min(ws), 9) if ws else None),
        "band": round(float(band), 4) if band is not None else None,
        "windows_s": ws,
        "source": source,
        "host": host_fingerprint(),
        "ts": int(time.time()),
    }
    if extras:
        rec.update({k: v for k, v in extras.items() if k not in rec})
    try:
        d = os.path.dirname(os.path.abspath(path))
        if d:
            os.makedirs(d, exist_ok=True)
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        try:
            os.write(fd, _jsonl_line(rec))
        finally:
            os.close(fd)
        return True
    except OSError:
        return False


def record_measured(key: str, measured: dict, source: str = "sweep",
                    path: str | None = None) -> int:
    """Append every arm of one tune.py-style measurement set. `key` is the
    db.py canonical `<op>|<shape_key>|<dtype>|<device_kind>` spelling;
    `measured` maps arm name -> _timing.measure summary (median_s / min_s /
    windows_s / band, extra fields ignored). Returns lines written."""
    parts = key.split("|")
    if len(parts) != 4:
        return 0
    op, shape_key, dtype, device_kind = parts
    n = 0
    for arm, m in sorted(measured.items()):
        if not isinstance(m, dict):
            continue
        n += bool(record(
            op, shape_key, dtype, device_kind, arm,
            windows_s=m.get("windows_s"), median_s=m.get("median_s"),
            min_s=m.get("min_s"), band=m.get("band"),
            source=source, path=path))
    return n


def iter_records(path: str | None = None):
    """Yield parsed records, fail-open: missing file yields nothing;
    corrupt or wrong-schema lines are skipped silently (an interrupted
    append leaves at most one torn final line)."""
    path = path or measurements_path()
    if not path or not os.path.exists(path):
        return
    try:
        with open(path, "rb") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if (isinstance(rec, dict)
                        and rec.get("schema") == STORE_SCHEMA
                        and rec.get("op") and rec.get("arm")):
                    yield rec
    except OSError:
        return
