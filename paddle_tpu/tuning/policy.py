"""Decision resolution: DB hit -> learned model -> analytic -> default.

`decide()` is the one consult point every tunable lever flows through
(conv lowering, attention backend, conv+BN fusion, AMP list membership,
bucket boundaries). Four tiers, strictly ordered:

  1. exact hit  — the swept DB has this (op, shape, dtype, device_kind) key;
  2. learned    — the trained cost model (tuning/learned/) predicts per-arm
                  times for this UNSEEN key and its confidence gates pass;
  3. analytic   — the registered prior for the op kind (the PR 5 cost model
                  for convs, the measured-dispatch rules for attention);
  4. default    — the caller's conservative fallback (what the code did
                  before the tuner existed).

Every resolution bumps a per-op provenance counter so bench.py can report
how much of a workload ran on swept decisions vs the prior (`gate.py` flags
a consult-mode workload that runs mostly untuned).

Modes (FLAGS_tuning_mode):
  off     — decide() is never consulted; levers use their pre-tuner logic.
  consult — resolve through the tiers above.
  sweep   — resolve analytically like `off`, but RECORD every distinct key
            encountered into the DB as a `candidate` entry (never clobbering
            a swept verdict) so `tools/tune.py` knows what to measure.
  explore — consult, plus candidate recording, plus bounded ONLINE
            measurement: tuning/learned/explore.py probes one recorded
            candidate every FLAGS_tuning_explore_every executor steps and
            promotes out-of-band verdicts to swept entries (TVM-style).
"""
from __future__ import annotations

import threading

from .. import flags
from .db import TuningDB

__all__ = ["decide", "mode", "consult_enabled", "sweep_enabled", "get_db",
           "invalidate_db_cache", "device_kind", "provenance_snapshot",
           "reset_provenance", "on_minimize"]

_lock = threading.Lock()
_db_cache: tuple[str, float, TuningDB] | None = None  # (path, mtime, db)

# provenance counters: {op: {"db": n, "analytic": n, "default": n}}
_counters: dict[str, dict[str, int]] = {}


def mode() -> str:
    m = str(flags.get_flag("tuning_mode")).strip().lower()
    return m if m in ("off", "consult", "sweep", "explore") else "off"


def consult_enabled() -> bool:
    # explore IS consult (same tier resolution) with online measurement on
    return mode() in ("consult", "explore")


def sweep_enabled() -> bool:
    return mode() == "sweep"


_device_kind: str | None = None


def device_kind() -> str:
    """Canonical device component of every key. Cached after the first
    backend query — decide() runs inside jit traces."""
    global _device_kind
    if _device_kind is not None:
        return _device_kind
    try:
        import jax

        _device_kind = str(getattr(jax.devices()[0], "device_kind", "cpu"))
    except Exception:  # pragma: no cover - no backend at all
        _device_kind = "cpu"
    return _device_kind


def get_db() -> TuningDB:
    """The DB for FLAGS_tuning_db, reloaded when the file's mtime moves
    (a sweep finishing mid-session is picked up without a restart). An
    empty/unset path is a permanently-empty DB (pure analytic mode)."""
    global _db_cache
    path = str(flags.get_flag("tuning_db")).strip()
    if not path:
        return TuningDB(None)
    import os

    try:
        mtime = os.stat(path).st_mtime
    except OSError:
        mtime = -1.0
    with _lock:
        if _db_cache and _db_cache[0] == path and _db_cache[1] == mtime:
            return _db_cache[2]
        db = TuningDB(path)
        _db_cache = (path, mtime, db)
        return db


def invalidate_db_cache() -> None:
    global _db_cache
    with _lock:
        _db_cache = None


def _bump(op: str, tier: str) -> None:
    with _lock:
        c = _counters.setdefault(op, {"db": 0, "analytic": 0, "default": 0})
        c[tier] = c.get(tier, 0) + 1
    from .. import observability as obs

    obs.counter_inc("tuning.decisions", labels={"op": op, "tier": tier})


def reset_provenance() -> None:
    with _lock:
        _counters.clear()


def provenance_snapshot() -> dict:
    """Per-op tier counts plus the aggregate rates bench.py reports:
    hit_rate is swept-DB resolutions over all resolutions, tuned_rate
    additionally credits the learned tier (a model prediction IS a
    measured-data decision, just an interpolated one — gate.py's coverage
    floor reads tuned_rate so a model-served workload is not flagged as
    untuned)."""
    with _lock:
        per_op = {op: dict(c) for op, c in _counters.items()}
    total = sum(sum(c.values()) for c in per_op.values())
    hits = sum(c["db"] for c in per_op.values())
    learned = sum(c.get("learned", 0) for c in per_op.values())
    return {
        "decisions": total,
        "db_hits": hits,
        "learned": learned,
        "hit_rate": round(hits / total, 4) if total else None,
        "tuned_rate": round((hits + learned) / total, 4) if total else None,
        "per_op": per_op,
    }


def decide(op: str, key: str, prior=None, default: dict | None = None,
           validate=None) -> tuple[dict, str]:
    """Resolve one decision. Returns (decision dict, tier) with tier in
    {"db", "learned", "analytic", "default"}.

    `prior`: zero-arg callable returning the analytic decision (evaluated
    lazily — cost models only run on a DB miss). `validate`: optional
    predicate on a DB or learned decision; a decision the current build
    cannot honor (e.g. a pallas backend off-TPU) falls through to the prior
    instead of being obeyed blindly. In sweep mode the analytic resolution
    is recorded as a candidate entry for tools/tune.py; explore mode records
    candidates too (food for the online prober) while resolving normally."""
    if sweep_enabled():
        d = _resolve_prior(op, prior, default)
        _record_candidate(key, d)
        return d
    m = mode()
    db = get_db()
    entry = db.lookup(key)
    if entry is not None and entry.get("source") != "candidate":
        decision = entry["decision"]
        if validate is None or validate(decision):
            _bump(op, "db")
            return decision, "db"
    from . import learned

    ld = learned.decide_learned(op, key, validate)
    if ld is not None:
        _bump(op, "learned")
        if m == "explore" and entry is None:
            _record_candidate(key, (ld, "learned"))
        return ld, "learned"
    res = _resolve_prior(op, prior, default)
    if m == "explore" and entry is None:
        _record_candidate(key, res)
    return res


def _resolve_prior(op, prior, default):
    if prior is not None:
        d = prior()
        if d is not None:
            _bump(op, "analytic")
            return d, "analytic"
    _bump(op, "default")
    return dict(default or {}), "default"


_seen_candidates: set[str] = set()


def _record_candidate(key: str, resolved: tuple[dict, str]) -> None:
    """Sweep mode: persist the key (with its analytic resolution as the
    provisional decision) so the offline sweeper knows the workload's
    decision surface. Write-through is cheap — each distinct key is recorded
    once per process and the file is small."""
    if key in _seen_candidates:
        return
    _seen_candidates.add(key)
    path = str(flags.get_flag("tuning_db")).strip()
    if not path:
        return
    db = get_db()
    if db.put(key, resolved[0], source="candidate",
              note=f"analytic resolution tier={resolved[1]}",
              overwrite=False):
        try:
            db.save(path)
            invalidate_db_cache()  # mtime moved; reload clean next consult
        except OSError:
            pass  # read-only FS: candidates stay in-memory only


def on_minimize(program) -> None:
    """minimize()-time hook (optimizer.Optimizer.backward): force the DB
    load NOW so a corrupt file warns at graph-build time — once, attached to
    the minimize call — rather than somewhere inside an op trace, and stamp
    the program with the mode it was built under (bench provenance)."""
    m = mode()
    program._tuning_mode = m
    if m != "off":
        get_db()
