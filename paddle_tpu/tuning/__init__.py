"""Framework-wide autotuner: a persistent per-(op, shape, dtype, device_kind)
decision cache with measured A/B sweeps.

PR 5 proved the pattern once — a hand-built tile-fill-vs-HBM cost model
gates the implicit-GEMM conv lowering per shape. This package generalizes
it (ROADMAP item 3, the TVM search-over-schedules framing, arXiv:1802.04799,
with measured sweeps replacing hand models per arXiv:2008.01040): every
per-shape perf lever resolves through ONE three-tier policy —

    exact swept-DB hit  ->  analytic prior  ->  conservative default

Levers wired through it today: conv2d lowering (direct vs implicit-GEMM,
incl. 1x1-as-matmul), attention backend (XLA fusion vs the short-seq Pallas
kernel vs the bundled flash kernel), conv+BN epilogue fusion
(passes.fuse_conv_bn_stats), AMP gray-op list membership, and feed-bucketing
boundaries. The DB is populated offline by `tools/tune.py` (the
tools/_rn_igemm.py loop made generic: median-of-windows timing, interference
band, keep-or-retire verdict per shape) and consulted at minimize()/trace
time under FLAGS_tuning_mode=consult; bench.py reports per-workload hit-rate
so tools/gate.py can flag a workload running mostly untuned.
"""
from .db import (DB_SCHEMA, TuningDB, amp_key, attention_key, bucket_key,
                 canonical_key, collective_key, conv_key, embedding_key,
                 epilogue_key, evidence, xent_key)
from .policy import (consult_enabled, decide, device_kind, get_db,
                     invalidate_db_cache, mode, on_minimize,
                     provenance_snapshot, reset_provenance, sweep_enabled)
from . import learned
from .learned import maybe_explore

__all__ = [
    "DB_SCHEMA", "TuningDB", "canonical_key", "conv_key", "attention_key",
    "bucket_key", "amp_key", "collective_key", "epilogue_key", "xent_key",
    "embedding_key", "evidence",
    "decide", "mode", "consult_enabled",
    "sweep_enabled", "get_db", "invalidate_db_cache", "device_kind",
    "provenance_snapshot", "reset_provenance", "on_minimize",
    "learned", "maybe_explore",
]
