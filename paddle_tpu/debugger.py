"""Program visualization: render a Program's block as Graphviz dot.

Reference: /root/reference/python/paddle/fluid/debugger.py
draw_block_graphviz (+ ir/graph_viz_pass.cc for the C++ IR). Same role on
this IR: operators as rectangles, variables as ellipses (parameters
highlighted), dataflow edges from input vars -> op -> output vars. Emits dot
TEXT (render with any graphviz install; none is vendored)."""
from __future__ import annotations

from .framework import Parameter, Program

__all__ = ["draw_block_graphviz", "program_to_dot"]


def _esc(s: str) -> str:
    return s.replace('"', r"\"")


def program_to_dot(program: Program, block_idx: int = 0,
                   highlights=None, name: str = "program") -> str:
    """Return the dot source for one block (reference draw_block_graphviz)."""
    block = program.blocks[block_idx]
    highlights = set(highlights or ())
    lines = [f'digraph "{_esc(name)}" {{', "  rankdir=TB;"]
    var_ids: dict[str, str] = {}

    def var_node(n: str) -> str:
        if n in var_ids:
            return var_ids[n]
        vid = f"var_{len(var_ids)}"
        var_ids[n] = vid
        try:
            v = block.var(n)
            label = f"{n}\\n{tuple(v.shape)} {v.dtype.value}"
            is_param = isinstance(v, Parameter)
        except KeyError:
            label, is_param = n, False
        style = ('style=filled, fillcolor="#d5e8d4"' if is_param
                 else 'style=filled, fillcolor="#f5f5f5"')
        if n in highlights:
            style = 'style=filled, fillcolor="#ffe6cc"'
        lines.append(f'  {vid} [shape=ellipse, {style}, '
                     f'label="{_esc(label)}"];')
        return vid

    for i, op in enumerate(block.ops):
        oid = f"op_{i}"
        lines.append(f'  {oid} [shape=rectangle, style=filled, '
                     f'fillcolor="#dae8fc", label="{_esc(op.type)}"];')
        for n in op.input_names:
            if n:
                lines.append(f"  {var_node(n)} -> {oid};")
        for n in op.output_names:
            if n:
                lines.append(f"  {oid} -> {var_node(n)};")
    lines.append("}")
    return "\n".join(lines)


def draw_block_graphviz(block_or_program, highlights=None, path=None,
                        name="program"):
    """Write the dot file (reference debugger.py draw_block_graphviz
    contract: (block, highlights, path)); returns the dot source."""
    if isinstance(block_or_program, Program):
        program, idx = block_or_program, 0
    else:
        program, idx = block_or_program.program, block_or_program.idx
    dot = program_to_dot(program, idx, highlights, name)
    if path:
        with open(path, "w") as f:
            f.write(dot)
    return dot
