"""Host-memory tier of a tiered giant-embedding table.

A production sparse table (10^8..10^9 rows) does not fit one chip's HBM; the
full table lives here, in process host memory, split into contiguous row
shards (numpy, one allocation per shard — the in-process analogue of the
per-pserver row partition the transpiler computes, and the unit a future
multi-host tier would place one-per-host). The device only ever holds the
hot-ID cache (engine.py); this tier serves the cache's misses (`gather`) and
absorbs its evictions (`scatter`).

Checkpointing is delta-based (checkpoint.py): `scatter`/`load_rows` track the
dirty-row set since the last full base snapshot, so the periodic checkpoint
of a 10 GB table writes only the rows training actually touched.
"""
from __future__ import annotations

import threading

import numpy as np

__all__ = ["HostShardedTable"]

# numpy RNG mixing constant for per-table seeds (any odd 64-bit prime works;
# this one is splitmix64's)
_SEED_MIX = 0x9E3779B97F4A7C15


class HostShardedTable:
    """One table's host tier: [vocab, dim] rows in contiguous shards.

    init: ("uniform", low, high) | ("gaussian", mean, std) |
          ("constant", value) — the numpy rendering of the startup-program
    init op the tiered rewrite removed (passes.rewrite_tiered_embeddings).
    Deterministic in (seed): a rebuilt table re-draws identical rows.
    """

    def __init__(self, name: str, vocab: int, dim: int,
                 dtype=np.float32, num_shards: int = 1,
                 init: tuple = ("constant", 0.0), seed: int = 0):
        self.name = name
        self.vocab = int(vocab)
        self.dim = int(dim)
        self.dtype = np.dtype(dtype)
        self.num_shards = max(1, min(int(num_shards), self.vocab or 1))
        self.init = tuple(init)
        self.seed = int(seed)
        self._lock = threading.Lock()
        # contiguous row ranges: shard s covers [bounds[s], bounds[s+1])
        base = self.vocab // self.num_shards
        rem = self.vocab % self.num_shards
        sizes = [base + (1 if s < rem else 0) for s in range(self.num_shards)]
        self.bounds = np.zeros(self.num_shards + 1, np.int64)
        np.cumsum(sizes, out=self.bounds[1:])
        self.shards = [self._init_shard(s, sizes[s])
                       for s in range(self.num_shards)]
        # dirty-row tracking for delta checkpoints: rows changed since the
        # last BASE snapshot (cumulative — a delta is restorable against its
        # base alone, so a crash between delta saves never loses rows)
        self._dirty: set[int] = set()

    # -- construction --------------------------------------------------------
    def _init_shard(self, s: int, rows: int) -> np.ndarray:
        kind = self.init[0]
        if kind == "constant":
            return np.full((rows, self.dim), self.init[1], self.dtype)
        rng = np.random.default_rng((self.seed ^ _SEED_MIX) + s)
        if kind == "uniform":
            lo, hi = self.init[1], self.init[2]
            return rng.uniform(lo, hi, (rows, self.dim)).astype(self.dtype)
        if kind == "gaussian":
            mean, std = self.init[1], self.init[2]
            return (rng.standard_normal((rows, self.dim)) * std
                    + mean).astype(self.dtype)
        raise ValueError(f"unknown host-tier init kind {kind!r}")

    # -- geometry ------------------------------------------------------------
    @property
    def nbytes(self) -> int:
        return sum(sh.nbytes for sh in self.shards)

    def _locate(self, rows: np.ndarray):
        """(shard index, local row) per global row id."""
        sidx = np.searchsorted(self.bounds, rows, side="right") - 1
        return sidx, rows - self.bounds[sidx]

    # -- the cache's two verbs ----------------------------------------------
    def gather(self, rows) -> np.ndarray:
        """Fetch rows [n] -> [n, dim] (miss resolution / prefetch fill)."""
        rows = np.asarray(rows, np.int64).reshape(-1)
        if rows.size == 0:
            return np.zeros((0, self.dim), self.dtype)
        if (rows < 0).any() or (rows >= self.vocab).any():
            bad = rows[(rows < 0) | (rows >= self.vocab)][:8]
            raise IndexError(
                f"host tier '{self.name}': row ids {bad.tolist()} outside "
                f"[0, {self.vocab})")
        out = np.empty((rows.size, self.dim), self.dtype)
        sidx, local = self._locate(rows)
        with self._lock:
            for s in np.unique(sidx):
                m = sidx == s
                out[m] = self.shards[s][local[m]]
        return out

    def scatter(self, rows, values) -> None:
        """Write rows back (eviction write-back / cache flush); marks dirty."""
        rows = np.asarray(rows, np.int64).reshape(-1)
        if rows.size == 0:
            return
        values = np.asarray(values, self.dtype).reshape(rows.size, self.dim)
        sidx, local = self._locate(rows)
        with self._lock:
            for s in np.unique(sidx):
                m = sidx == s
                self.shards[s][local[m]] = values[m]
            self._dirty.update(int(r) for r in rows)

    # an explicit alias for bulk loads (parity harnesses, restore)
    load_rows = scatter

    def to_dense(self) -> np.ndarray:
        """Full [vocab, dim] materialization — small-scale oracles only."""
        with self._lock:
            return np.concatenate(self.shards, axis=0) if self.shards else \
                np.zeros((0, self.dim), self.dtype)

    # -- delta-checkpoint bookkeeping ---------------------------------------
    def dirty_rows(self) -> np.ndarray:
        with self._lock:
            return np.fromiter(self._dirty, np.int64, len(self._dirty))

    def clear_dirty(self) -> None:
        """Called when a BASE snapshot commits (the delta chain restarts)."""
        with self._lock:
            self._dirty.clear()

    def set_dirty(self, rows) -> None:
        """Restore-time reset: exactly the rows the applied delta carried
        differ from the base, so the NEXT delta must re-include them."""
        with self._lock:
            self._dirty = {int(r) for r in np.asarray(rows).reshape(-1)}
