"""Streaming delta checkpoints for host-tier embedding shards.

A giant table makes the PR 1 save-everything checkpoint untenable: the host
tier is most of the model's bytes and almost none of it changes between two
saves. This provider rides the CheckpointManager state-provider hook
(resilience/checkpoint.py):

  * BASE snapshots — the full host tier, written atomically to the
    checkpoint ROOT (`emb_<table>.base_<step>.npz`) every
    FLAGS_emb_ckpt_base_every saves (and whenever no live base exists). The
    last two bases are kept so every retained step directory's delta stays
    restorable across base rotation.
  * DELTAS — every step-directory save writes only the rows dirtied since
    the current base (`emb_<table>.delta.npz` inside the atomic step dir),
    CUMULATIVE against that base: restore never needs a chain, just
    base + the one delta riding the restored step, and a crash between
    delta saves cannot lose rows.

Restore = load base, apply delta, reset the device cache cold (the host
tier is authoritative; slots refill on first touch), and re-mark the delta's
rows dirty so the next delta stays consistent with the restored base.
"""
from __future__ import annotations

import glob
import os
import re
import tempfile

import numpy as np

__all__ = ["EmbeddingStateProvider"]

_BASE_RE = re.compile(r"\.base_(\d{8})\.npz$")


def _atomic_savez(path: str, **arrays) -> None:
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(prefix=".emb_base.", suffix=".npz", dir=d)
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


class EmbeddingStateProvider:
    """One engine's host-tier state, spliced into CheckpointManager saves."""

    name = "tiered_embedding"

    def __init__(self, engine):
        self._engine = engine
        self._base_step: dict[str, int] = {}   # table -> live base step
        self._saves_since: dict[str, int] = {}

    # -- save -----------------------------------------------------------------
    def _base_path(self, root: str, table: str, step: int) -> str:
        return os.path.join(root, f"emb_{table}.base_{step:08d}.npz")

    def _gc_bases(self, root: str, table: str) -> None:
        paths = sorted(glob.glob(
            os.path.join(root, f"emb_{table}.base_*.npz")))
        for p in paths[:-2]:  # keep the live base + one predecessor
            try:
                os.unlink(p)
            except OSError:
                pass

    def save_state(self, manager, tmp_dir: str, step: int, executor=None,
                   program=None, scope=None) -> dict:
        from .. import flags

        if executor is not None and hasattr(executor, "wait"):
            executor.wait()  # write-backs + cache values must be final
        self._engine.flush_cache(scope)
        base_every = max(1, int(flags.get_flag("emb_ckpt_base_every")))
        frag: dict = {"tables": {}}
        for tname, ts in self._engine.tables.items():
            host = ts.host
            base = self._base_step.get(tname)
            need_base = (base is None
                         or self._saves_since.get(tname, 0) + 1 >= base_every
                         or not os.path.exists(
                             self._base_path(manager.root, tname, base)))
            if need_base:
                arrays = {f"shard_{i}": sh
                          for i, sh in enumerate(host.shards)}
                arrays["bounds"] = host.bounds
                _atomic_savez(self._base_path(manager.root, tname, step),
                              **arrays)
                host.clear_dirty()
                self._base_step[tname] = base = step
                self._saves_since[tname] = 0
                self._gc_bases(manager.root, tname)
            else:
                self._saves_since[tname] = self._saves_since.get(tname, 0) + 1
            rows = host.dirty_rows()
            np.savez(os.path.join(tmp_dir, f"emb_{tname}.delta.npz"),
                     rows=rows, values=host.gather(rows) if rows.size
                     else np.zeros((0, host.dim), host.dtype))
            frag["tables"][tname] = {
                "base_step": int(base),
                "delta_rows": int(rows.size),
                "vocab": host.vocab, "dim": host.dim,
            }
        return frag

    # -- restore --------------------------------------------------------------
    def restore_state(self, manager, step_dir: str, step: int,
                      frag: dict | None, executor=None, program=None,
                      scope=None) -> None:
        if not frag:
            return
        for tname, tfrag in (frag.get("tables") or {}).items():
            ts = self._engine.tables.get(tname)
            if ts is None:
                continue
            host = ts.host
            base_step = int(tfrag["base_step"])
            base_path = self._base_path(manager.root, tname, base_step)
            if not os.path.exists(base_path):
                raise FileNotFoundError(
                    f"tiered table '{tname}': base snapshot for step "
                    f"{base_step} is gone ({base_path}) — this checkpoint's "
                    f"delta is unrestorable")
            with np.load(base_path) as z:
                shards = [z[f"shard_{i}"].astype(host.dtype, copy=True)
                          for i in range(len(z.files) - 1)]
                bounds = z["bounds"].astype(np.int64)
            if sum(len(s) for s in shards) != host.vocab:
                raise ValueError(
                    f"tiered table '{tname}': base snapshot rows "
                    f"!= vocab {host.vocab}")
            # adopt the snapshot's shard layout wholesale — a changed
            # FLAGS_emb_host_shards between runs must not corrupt a restore
            host.shards = shards
            host.bounds = bounds
            host.num_shards = len(shards)
            with np.load(os.path.join(step_dir,
                                      f"emb_{tname}.delta.npz")) as z:
                rows, values = z["rows"], z["values"]
            if rows.size:
                host.scatter(rows, values)
            host.set_dirty(rows)
            self._base_step[tname] = base_step
            self._saves_since[tname] = 0
        self._engine.reset_cache()
