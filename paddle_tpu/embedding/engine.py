"""Tiered-embedding engine: the host-side half of the HBM hot-ID cache.

The compiled step only ever sees a `[slots+1, dim]` cache scope var, a batch
of cache-slot indices, and a fixed-width prefetch buffer (ops
`emb_cache_install` / `tiered_lookup`, rewritten in at minimize() time by
passes.rewrite_tiered_embeddings). Everything that involves host memory
happens HERE, off the step:

  * resolve — the DeviceLoader's background thread (or Executor._run_impl,
    synchronously, when a feed arrives unresolved) extracts the batch's
    unique-ID set, maps hits through the slot table, assigns slots to misses
    (free list, then frequency-based eviction with LRU tie-break), gathers
    the missed rows from the host tier, and attaches three derived feeds —
    per-ids slot indices, prefetch rows, prefetch slots — so the step gathers
    straight from HBM;
  * write-back — `emb_cache_install` emits the PRE-install contents of the
    slots it overwrites as a step output. Because steps execute in dispatch
    order on one stream, those values carry every optimizer update the
    evicted rows ever received, regardless of how many batches the resolver
    ran ahead; the engine matches them to the (slot -> old row) record of
    that batch's resolution and lands them in the host tier when the device
    array materializes — asynchronously, unless the row is re-missed first
    (then the resolver blocks on exactly that one write-back: the only
    synchronization point in the design, and it only fires when a row
    bounces out and back within the in-flight window).

Resolution order IS dispatch order (single producer feeding a single
consumer), which is what makes the slot-map bookkeeping correct without any
device synchronization.
"""
from __future__ import annotations

import collections
import threading
import warnings

import numpy as np

from .. import flags, profiler
from .. import observability as obs
from .host_tier import HostShardedTable

__all__ = ["TieredEmbeddingEngine", "TICKET_KEY"]

# reserved feed key carrying the resolution ticket from the resolver thread
# to the dispatching executor; never staged, never part of a compile signature
TICKET_KEY = "<emb_ticket>"

# how long a forced write-back flush waits for its step to be dispatched +
# complete before giving up (stale host rows beat a deadlocked trainer)
_WB_TIMEOUT_S = 120.0


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


class _Record:
    """One batch's resolution: which slots were installed, and which evicted
    rows the step's `@EVICTED` output must be written back to."""

    __slots__ = ("ticket", "tables", "event", "flushed", "flush_lock")

    def __init__(self, ticket: int):
        self.ticket = ticket
        # table name -> {"evict_pairs": [(install_idx, old_row)],
        #                "evict_var": str, "handle": jax.Array|None}
        self.tables: dict[str, dict] = {}
        self.event = threading.Event()  # set when the step is dispatched
        self.flushed = False
        # the resolver (conflict flush) and the dispatch thread
        # (opportunistic flush) can race to land the same record
        self.flush_lock = threading.Lock()


class _TableState:
    """Slot-map + frequency bookkeeping for one tiered table."""

    def __init__(self, name: str, host: HostShardedTable, slots: int,
                 cache_var: str, rows_var: str, slots_var: str,
                 evict_var: str, prefetch_rows: int = 0):
        self.name = name
        self.host = host
        self.slots = int(slots)
        self.scratch = int(slots)  # cache row [slots] is the masked scratch
        self.cache_var = cache_var
        self.rows_var = rows_var
        self.slots_var = slots_var
        self.evict_var = evict_var
        # ids feed name -> (slot feed name, padding_idx or None)
        self.ids_feeds: dict[str, tuple[str, int | None]] = {}
        self.prefetch_rows = int(prefetch_rows)  # 0 = auto from first batch
        self.lock = threading.RLock()
        self.slot2row = np.full(self.slots, -1, np.int64)
        self.row2slot: dict[int, int] = {}
        self.slot_freq = np.zeros(self.slots, np.float64)
        self.slot_used = np.zeros(self.slots, np.int64)
        self.free: list[int] = list(range(self.slots - 1, -1, -1))
        self.seen: dict[int, int] = {}  # admission counter (hot-ID history)
        self.pending_wb: dict[int, _Record] = {}  # evicted row -> its record
        self.tick = 0
        self.stats = collections.Counter()


class TieredEmbeddingEngine:
    """Per-program engine (stored as `program._tiered_engine`); one instance
    owns every tiered table of that program."""

    def __init__(self, program=None):
        self._program = program
        self.tables: dict[str, _TableState] = {}
        self._records: dict[int, _Record] = {}
        self._dispatched: collections.deque[_Record] = collections.deque()
        self._next_ticket = 0
        self._lock = threading.Lock()

    # -- registration (passes.rewrite_tiered_embeddings) ---------------------
    def add_table(self, name: str, host: HostShardedTable, slots: int,
                  cache_var: str, rows_var: str, slots_var: str,
                  evict_var: str, prefetch_rows: int = 0) -> _TableState:
        ts = _TableState(name, host, slots, cache_var, rows_var, slots_var,
                         evict_var, prefetch_rows)
        self.tables[name] = ts
        return ts

    def add_lookup(self, table: str, ids_feed: str, slot_feed: str,
                   padding_idx: int | None) -> None:
        pad = None if padding_idx is None or padding_idx < 0 else int(
            padding_idx)
        self.tables[table].ids_feeds[ids_feed] = (slot_feed, pad)

    # -- the resolver (producer thread / inline) ------------------------------
    def resolve_feed(self, feed: dict) -> dict:
        """Return a NEW feed dict with the derived tiered feeds (+ ticket)
        attached. Pure host work — safe on the DeviceLoader thread."""
        from ..resilience.faults import InjectedFault, fault_point

        try:
            fault_point("emb_host_stall")
        except InjectedFault:
            # simulated host-tier wedge (hung remote shard / page-in storm):
            # the resolver parks forever so the consumer-side stall watchdog
            # must surface it with queue depths; the parked daemon thread
            # dies with the process
            threading.Event().wait()
        with self._lock:
            self._next_ticket += 1
            ticket = self._next_ticket
        rec = _Record(ticket)
        out = dict(feed)
        resolved = False
        for ts in self.tables.values():
            resolved |= self._resolve_table(ts, out, rec)
        if resolved:
            with self._lock:
                self._records[ticket] = rec
            out[TICKET_KEY] = ticket
        return out

    def _resolve_table(self, ts: _TableState, feed: dict,
                       rec: _Record) -> bool:
        ids_arrays = {n: np.asarray(feed[n])
                      for n in ts.ids_feeds if n in feed}
        if not ids_arrays:
            return False
        # conflict pre-pass: a missed row whose write-back is still in
        # flight must not be refetched from the (stale) host tier — block
        # on exactly those records first, outside the table lock
        while True:
            with ts.lock:
                flat_all = np.concatenate(
                    [a.reshape(-1).astype(np.int64)
                     for a in ids_arrays.values()])
                conflicts = {ts.pending_wb[int(r)]
                             for r in np.unique(flat_all)
                             if int(r) in ts.pending_wb}
            if not conflicts:
                break
            for crec in conflicts:
                self._flush_record(crec, wait=True)

        with ts.lock:
            ts.tick += 1
            tick = ts.tick
            parts = []
            for name, arr in ids_arrays.items():
                pad = ts.ids_feeds[name][1]
                f = arr.reshape(-1).astype(np.int64)
                parts.append(f[f != pad] if pad is not None else f)
            union = np.concatenate(parts) if parts else \
                np.zeros(0, np.int64)
            uniq, counts = np.unique(union, return_counts=True)
            if uniq.size and (uniq[0] < 0 or uniq[-1] >= ts.host.vocab):
                bad = uniq[(uniq < 0) | (uniq >= ts.host.vocab)][:8]
                raise IndexError(
                    f"tiered table '{ts.name}': ids {bad.tolist()} outside "
                    f"[0, {ts.host.vocab})")
            uslots = np.empty(uniq.size, np.int64)
            miss_idx = []
            for i in range(uniq.size):
                uid = int(uniq[i])
                slot = ts.row2slot.get(uid)
                if slot is None:
                    miss_idx.append(i)
                else:
                    uslots[i] = slot
                    ts.slot_freq[slot] += counts[i]
                    ts.slot_used[slot] = tick
            n_miss = len(miss_idx)
            hit_occ = int(counts.sum()) - int(counts[miss_idx].sum())
            ts.stats["hit_ids"] += hit_occ
            ts.stats["miss_ids"] += int(counts[miss_idx].sum())
            ts.stats["batches"] += 1
            obs.counter_inc("emb.hit_ids", hit_occ,
                            labels={"table": ts.name})
            obs.counter_inc("emb.miss_ids", int(counts[miss_idx].sum()),
                            labels={"table": ts.name})

            # victims for misses beyond the free list: lowest frequency
            # first, LRU tie-break; slots referenced THIS batch are pinned
            need = n_miss - len(ts.free)
            victims: list[int] = []
            if need > 0:
                cand = np.nonzero((ts.slot2row >= 0)
                                  & (ts.slot_used < tick))[0]
                if cand.size < need:
                    raise RuntimeError(
                        f"tiered table '{ts.name}': cache of {ts.slots} "
                        f"slots cannot hold one batch's working set "
                        f"({n_miss} new + pinned ids) — raise "
                        f"FLAGS_emb_cache_slots / FLAGS_emb_hbm_budget_mb")
                order = np.lexsort((ts.slot_used[cand], ts.slot_freq[cand]))
                victims = [int(s) for s in cand[order[:need]]]

            admit_min = int(flags.get_flag("emb_admit_min_freq"))
            evict_pairs: list[tuple[int, int]] = []
            install_slots = np.empty(n_miss, np.int64)
            vq = collections.deque(victims)
            for j, i in enumerate(miss_idx):
                uid = int(uniq[i])
                if ts.free:
                    slot = ts.free.pop()
                else:
                    slot = vq.popleft()
                    old = int(ts.slot2row[slot])
                    ts.row2slot.pop(old, None)
                    evict_pairs.append((j, old))
                    ts.pending_wb[old] = rec
                    ts.stats["evictions"] += 1
                    obs.counter_inc("emb.evictions",
                                    labels={"table": ts.name})
                seen = ts.seen.get(uid, 0) + int(counts[i])
                ts.seen[uid] = seen
                ts.row2slot[uid] = slot
                ts.slot2row[slot] = uid
                # probation admission: an id still below the hot threshold
                # enters with zero accumulated frequency, so it is the first
                # eviction candidate until it proves itself
                ts.slot_freq[slot] = float(counts[i]) if seen >= admit_min \
                    else 0.0
                ts.slot_used[slot] = tick
                install_slots[j] = slot
                uslots[i] = slot
            if len(ts.seen) > 8 * ts.slots:
                # bound the admission history: keep the hotter half
                keep = sorted(ts.seen.items(), key=lambda kv: -kv[1])
                ts.seen = dict(keep[:4 * ts.slots])

            # fixed-width prefetch buffer: the compile signature must not
            # change per batch, so pad to the configured (or auto, growing)
            # capacity — padding installs zero rows into the masked scratch
            cap = ts.prefetch_rows
            if cap <= 0 or n_miss > cap:
                cap = _pow2(max(1, n_miss))
                if ts.prefetch_rows and n_miss > ts.prefetch_rows:
                    ts.stats["prefetch_grows"] += 1
                ts.prefetch_rows = max(ts.prefetch_rows, cap)
                cap = ts.prefetch_rows
            miss_rows = ts.host.gather(uniq[miss_idx])
            rows_buf = np.zeros((cap, ts.host.dim), ts.host.dtype)
            rows_buf[:n_miss] = miss_rows
            slots_buf = np.full(cap, ts.scratch, np.int32)
            slots_buf[:n_miss] = install_slots

            # per-ids-feed slot indices (padding positions -> scratch)
            for name, arr in ids_arrays.items():
                slot_feed, pad = ts.ids_feeds[name]
                flat = arr.reshape(-1).astype(np.int64)
                if uniq.size:
                    idx = np.searchsorted(uniq, flat)
                    idxc = np.clip(idx, 0, uniq.size - 1)
                    valid = uniq[idxc] == flat
                    sl = np.where(valid, uslots[idxc], ts.scratch)
                else:
                    sl = np.full(flat.shape, ts.scratch, np.int64)
                feed[slot_feed] = sl.reshape(arr.shape).astype(np.int32)
            feed[ts.rows_var] = rows_buf
            feed[ts.slots_var] = slots_buf
            if evict_pairs:
                rec.tables[ts.name] = {"evict_pairs": evict_pairs,
                                       "evict_var": ts.evict_var,
                                       "handle": None}
        profiler.bump("emb.resolved_batches")
        return True

    # -- the executor side ----------------------------------------------------
    def prepare_feed(self, feed: dict):
        """Called by Executor._run_impl before signature analysis: pop the
        ticket (it must not reach the compile key) or resolve inline when the
        feed arrived raw. Returns (feed, ticket|None)."""
        if TICKET_KEY in feed:
            ticket = int(np.asarray(feed.pop(TICKET_KEY)))
            with self._lock:
                known = ticket in self._records
            if known:
                return feed, ticket
            # stale ticket (a resolved dict reused across runs): the slot
            # map has moved on — re-resolve against current state
        if not any(n in feed for ts in self.tables.values()
                   for n in ts.ids_feeds):
            return feed, None
        out = self.resolve_feed(feed)
        ticket = out.pop(TICKET_KEY, None)
        return out, ticket

    def note_dispatched(self, ticket: int, scope) -> None:
        """Called by the executor right after the step is dispatched: grab
        the step's `@EVICTED` output handles (device arrays — no sync) and
        opportunistically land any write-backs that already materialized."""
        with self._lock:
            rec = self._records.pop(ticket, None)
        if rec is None:
            return
        for tname, t in rec.tables.items():
            t["handle"] = scope.find_var(t["evict_var"])
        rec.event.set()
        if rec.tables:
            with self._lock:
                self._dispatched.append(rec)
        self._flush_ready()

    def _flush_ready(self) -> None:
        while True:
            with self._lock:
                if not self._dispatched:
                    return
                rec = self._dispatched[0]
                ready = all(
                    getattr(t["handle"], "is_ready", lambda: True)()
                    for t in rec.tables.values())
                deep = len(self._dispatched)
            if not ready and deep <= 64:
                return
            # head ready (or the backlog is deep enough to force the point)
            self._flush_record(rec, wait=True)

    def _flush_record(self, rec: _Record, wait: bool) -> None:
        if rec.flushed:
            return
        if not rec.event.wait(_WB_TIMEOUT_S if wait else 0):
            if wait:
                warnings.warn(
                    f"tiered embedding: write-back record {rec.ticket} was "
                    f"never dispatched within {_WB_TIMEOUT_S}s — dropping it "
                    f"(the evicted rows keep their last host-tier values)",
                    stacklevel=3)
                rec.flushed = True
            return
        with rec.flush_lock:
            if rec.flushed:
                return
            for tname, t in rec.tables.items():
                ts = self.tables[tname]
                arr = np.asarray(t["handle"])  # sync point: step completed
                idxs = [j for j, _ in t["evict_pairs"]]
                rows = [r for _, r in t["evict_pairs"]]
                with ts.lock:
                    ts.host.scatter(rows, arr[idxs])
                    ts.stats["writebacks"] += len(rows)
                    obs.counter_inc("emb.writebacks", len(rows),
                                    labels={"table": ts.name})
                    for r in rows:
                        if ts.pending_wb.get(r) is rec:
                            del ts.pending_wb[r]
            rec.flushed = True
        with self._lock:
            try:
                self._dispatched.remove(rec)
            except ValueError:
                pass

    # -- lifecycle ------------------------------------------------------------
    def flush_all(self) -> None:
        """Land every dispatched write-back (blocking). Records resolved but
        never dispatched (abandoned prefetch) are dropped."""
        while True:
            with self._lock:
                rec = self._dispatched[0] if self._dispatched else None
                if rec is None:
                    stale = list(self._records.values())
                    self._records.clear()
                    break
            self._flush_record(rec, wait=True)
        for rec in stale:
            for tname, t in rec.tables.items():
                ts = self.tables[tname]
                with ts.lock:
                    for _, r in t["evict_pairs"]:
                        if ts.pending_wb.get(r) is rec:
                            del ts.pending_wb[r]

    def flush_cache(self, scope) -> None:
        """Write every resident row's CURRENT device value back to the host
        tier (checkpoint/export time; the caller must have drained in-flight
        steps — Executor.wait())."""
        self.flush_all()
        for ts in self.tables.values():
            v = scope.find_var(ts.cache_var)
            if v is None:
                continue
            arr = np.asarray(v)
            with ts.lock:
                occ = np.nonzero(ts.slot2row >= 0)[0]
                if occ.size:
                    ts.host.scatter(ts.slot2row[occ], arr[occ])

    def reset_cache(self) -> None:
        """Cold-start the device cache mapping (checkpoint restore: the host
        tier is authoritative, every slot refills on first touch)."""
        with self._lock:
            self._records.clear()
            self._dispatched.clear()
        for ts in self.tables.values():
            with ts.lock:
                ts.slot2row[:] = -1
                ts.row2slot.clear()
                ts.slot_freq[:] = 0.0
                ts.slot_used[:] = 0
                ts.free = list(range(ts.slots - 1, -1, -1))
                ts.pending_wb.clear()
                ts.tick = 0

    def export_dense(self, table: str, scope=None) -> np.ndarray:
        """Full [vocab, dim] table (host tier + current cache contents) —
        the small-scale parity oracle's view."""
        if scope is not None:
            self.flush_cache(scope)
        return self.tables[table].host.to_dense()

    def stats(self, table: str | None = None) -> dict:
        def one(ts: _TableState) -> dict:
            with ts.lock:
                s = dict(ts.stats)
                total = s.get("hit_ids", 0) + s.get("miss_ids", 0)
                s["hit_rate"] = round(s.get("hit_ids", 0) / total, 4) \
                    if total else None
                s["resident_rows"] = int((ts.slot2row >= 0).sum())
                s["slots"] = ts.slots
                s["prefetch_rows"] = ts.prefetch_rows
                s["host_bytes"] = ts.host.nbytes
            return s

        if table is not None:
            return one(self.tables[table])
        return {name: one(ts) for name, ts in self.tables.items()}
