"""Tiered giant-embedding engine (ISSUE 10).

Tables above FLAGS_emb_hbm_budget_mb become two-tier at minimize() time
(passes.rewrite_tiered_embeddings): the full table lives in host-memory
shards (host_tier.HostShardedTable) behind a device-resident hot-ID cache —
a `[slots+1, dim]` persistable scope var the compiled step gathers from,
scatter-adds slot gradients into, and updates in place via donation. Miss
resolution and eviction write-back run OFF the step on the feed pipeline
(engine.TieredEmbeddingEngine); checkpointing streams base + dirty-row
deltas through the CheckpointManager manifest (checkpoint.py).
"""
from .engine import TICKET_KEY, TieredEmbeddingEngine
from .checkpoint import EmbeddingStateProvider
from .host_tier import HostShardedTable

__all__ = ["TieredEmbeddingEngine", "EmbeddingStateProvider",
           "HostShardedTable", "TICKET_KEY"]
