"""Fleet API: role makers + the `fleet` singleton.

TPU-native re-design of /root/reference/python/paddle/fluid/incubate/fleet/
base/fleet_base.py (Fleet:38, fleet singleton, distributed_optimizer:222) and
base/role_maker.py (MPIRoleMaker:111, PaddleCloudRoleMaker, UserDefinedRole-
Maker). On TPU a "worker" is a JAX process in a multi-host pod; rendezvous is
jax.distributed (PjRt coordination service) instead of MPI/gen_nccl_id RPC.
"""
from __future__ import annotations

import os

__all__ = [
    "Role",
    "UserDefinedRoleMaker",
    "PaddleCloudRoleMaker",
    "Fleet",
    "fleet",
]


class Role:
    WORKER = 1
    SERVER = 2


class RoleMakerBase:
    def __init__(self):
        self._worker_endpoints = []
        self._server_endpoints = []
        self._role = Role.WORKER
        self._current_id = 0

    def is_worker(self):
        return self._role == Role.WORKER

    def is_server(self):
        return self._role == Role.SERVER

    def is_first_worker(self):
        return self.is_worker() and self._current_id == 0

    def worker_index(self):
        return self._current_id

    def server_index(self):
        return self._current_id

    def worker_num(self):
        return max(len(self._worker_endpoints), 1)

    def get_trainer_endpoints(self):
        return self._worker_endpoints

    def get_pserver_endpoints(self):
        return self._server_endpoints

    def generate_role(self):
        pass


class UserDefinedRoleMaker(RoleMakerBase):
    def __init__(self, current_id=0, role=Role.WORKER, worker_num=1, server_endpoints=None):
        super().__init__()
        self._current_id = current_id
        self._role = role
        self._worker_endpoints = [f"127.0.0.1:{6170 + i}" for i in range(worker_num)]
        self._server_endpoints = server_endpoints or []


class PaddleCloudRoleMaker(RoleMakerBase):
    """Env-var driven (reference role_maker.py PaddleCloudRoleMaker): reads
    PADDLE_TRAINER_ID / PADDLE_TRAINER_ENDPOINTS / pserver envs; also accepts
    the JAX multi-process envs (JAX_PROCESS_ID/JAX_NUM_PROCESSES)."""

    def generate_role(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._worker_endpoints = eps.split(",") if eps else []
        self._server_endpoints = [
            e for e in os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "").split(",") if e
        ]
        role = os.environ.get("TRAINING_ROLE", "TRAINER")
        if role == "PSERVER":
            self._role = Role.SERVER
            self._current_id = int(os.environ.get("PADDLE_PSERVER_ID", 0))
        else:
            self._role = Role.WORKER
            self._current_id = int(
                os.environ.get("PADDLE_TRAINER_ID", os.environ.get("JAX_PROCESS_ID", 0))
            )
        if not self._worker_endpoints:
            n = int(os.environ.get("PADDLE_TRAINERS_NUM", os.environ.get("JAX_NUM_PROCESSES", 1)))
            self._worker_endpoints = [f"127.0.0.1:{6170 + i}" for i in range(n)]


class Fleet:
    """The collective-mode fleet facade (reference fleet_base.py:38 +
    collective/__init__.py:139 CollectiveOptimizer)."""

    def __init__(self):
        self._role_maker: RoleMakerBase | None = None
        self._mesh = None
        self._nrings = 1

    def init(self, role_maker=None, mesh=None):
        self._role_maker = role_maker or UserDefinedRoleMaker()
        self._role_maker.generate_role()
        self._mesh = mesh

    def is_first_worker(self):
        return self._role_maker.is_first_worker()

    def worker_index(self):
        return self._role_maker.worker_index()

    def worker_num(self):
        return self._role_maker.worker_num()

    def is_worker(self):
        return self._role_maker.is_worker()

    def is_server(self):
        return self._role_maker.is_server()

    @property
    def worker_endpoints(self):
        return self._role_maker.get_trainer_endpoints()

    @property
    def server_endpoints(self):
        return self._role_maker.get_pserver_endpoints()

    def distributed_optimizer(self, optimizer, strategy=None):
        return CollectiveOptimizer(self, optimizer, strategy)

    def compiled_program(self, main_program=None, mesh=None):
        """CompiledProgram wired for the collective (shard_map) regime."""
        from ...compiler import CompiledProgram
        from ...framework import default_main_program
        from ...parallel.mesh import make_mesh

        prog = main_program or default_main_program()
        return CompiledProgram(prog).with_collective(
            mesh=mesh or self._mesh or make_mesh()
        )

    # checkpoint passthroughs (reference fleet save_inference_model etc.)
    def save_persistables(self, executor, dirname, main_program=None):
        from ... import io

        io.save_persistables(executor, dirname, main_program)

    def init_worker(self):
        pass

    def stop_worker(self):
        pass

    def barrier_worker(self):
        pass


class DistributedStrategy:
    """Knobs (reference DistributedStrategy in fleet collective mode)."""

    def __init__(self):
        self.nrings = 1
        self.mode = "grad_allreduce"  # or "local_sgd"
        self.local_sgd_k = 1
        # collective-overlap knobs (parallel/collective.py): None defers to
        # FLAGS_allreduce_bucket_mb / the tuning DB and FLAGS_zero1
        self.allreduce_bucket_mb = None
        self.zero1 = None


class CollectiveOptimizer:
    """Wrap an Optimizer: minimize() then GradAllReduce-transpile the program
    (reference incubate/fleet/collective/__init__.py:139)."""

    def __init__(self, fleet_obj: Fleet, inner, strategy: DistributedStrategy | None):
        self._fleet = fleet_obj
        self._inner = inner
        self._strategy = strategy or DistributedStrategy()

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        from ...framework import default_main_program, default_startup_program
        from ...parallel.collective import GradAllReduce, LocalSGD

        ops, pgs = self._inner.minimize(loss, startup_program, parameter_list, no_grad_set)
        nranks = self._fleet.worker_num()
        if self._fleet._mesh is not None:
            import numpy as np

            nranks = int(np.prod(list(self._fleet._mesh.shape.values())))
        if self._strategy.mode == "local_sgd":
            t = LocalSGD(self._strategy.nrings, self._strategy.local_sgd_k)
        else:
            t = GradAllReduce(self._strategy.nrings,
                              bucket_mb=self._strategy.allreduce_bucket_mb,
                              zero1=self._strategy.zero1)
        t.transpile(
            startup_program or default_startup_program(),
            loss.block.program,
            rank=self._fleet.worker_index(),
            nranks=nranks,
        )
        return ops, pgs


fleet = Fleet()
