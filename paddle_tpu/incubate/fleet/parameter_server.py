"""Fleet parameter-server mode.

TPU-native re-design of the reference's transpiler-based fleet
(/root/reference/python/paddle/fluid/incubate/fleet/parameter_server/
distribute_transpiler/__init__.py: DistributedTranspiler fleet,
TranspilerOptimizer): same lifecycle —

    fleet.init(role_maker)
    optimizer = fleet.distributed_optimizer(inner, strategy)
    optimizer.minimize(loss)
    # servers:  fleet.init_server(); fleet.run_server()
    # trainers: fleet.init_worker(); train(fleet.main_program); fleet.stop_worker()

— riding this repo's DistributeTranspiler + host TCP variable service
(distributed/ps_rpc.py) instead of gRPC/BRPC: dense math stays on the chip,
parameter slices and sparse SelectedRows grads travel over DCN.
"""
from __future__ import annotations

from .base import PaddleCloudRoleMaker, Role, RoleMakerBase, UserDefinedRoleMaker

__all__ = ["fleet", "ParameterServerFleet", "TranspilerOptimizer"]


class ParameterServerFleet:
    """reference fleet_base.py:38 facade, pserver flavor."""

    def __init__(self):
        self._role_maker: RoleMakerBase | None = None
        self._transpiler = None
        self._origin_main = None
        self._origin_startup = None

    def init(self, role_maker=None):
        self._role_maker = role_maker or PaddleCloudRoleMaker()
        self._role_maker.generate_role()

    # -- role views ----------------------------------------------------------
    def is_worker(self):
        return self._role_maker.is_worker()

    def is_server(self):
        return self._role_maker.is_server()

    def is_first_worker(self):
        return self._role_maker.is_first_worker()

    def worker_index(self):
        return self._role_maker.worker_index()

    def worker_num(self):
        return self._role_maker.worker_num()

    def server_index(self):
        return self._role_maker.server_index()

    @property
    def server_endpoints(self):
        return self._role_maker.get_pserver_endpoints()

    # -- programs ------------------------------------------------------------
    @property
    def main_program(self):
        """The transpiled trainer program (reference fleet.main_program)."""
        if self._transpiler is None:
            raise RuntimeError("call distributed_optimizer(...).minimize first")
        return self._transpiler.get_trainer_program()

    @property
    def startup_program(self):
        return self._origin_startup

    def distributed_optimizer(self, optimizer, strategy=None):
        return TranspilerOptimizer(self, optimizer, strategy)

    # -- server lifecycle ----------------------------------------------------
    def init_server(self, model_dir: str | None = None, **kwargs):
        """Initialize this server's parameter slices; with model_dir, resume
        from the pserver-<endpoint>.npz written by save_persistables'
        checkpoint_notify (reference init_server(model_dir) load path)."""
        import os

        import numpy as np

        from ...executor import Executor, global_scope

        from ...resilience.retry import io_policy

        exe = Executor()
        exe.run(self._transpiler.get_startup_program())
        if model_dir:
            safe_ep = self._current_endpoint().replace(":", "_").replace(
                "/", "_")
            path = os.path.join(model_dir, f"pserver-{safe_ep}.npz")
            if not os.path.exists(path):
                raise FileNotFoundError(
                    f"init_server: no checkpoint for this endpoint at {path}")
            scope = global_scope()
            # a shared-filesystem read races the writer's rename on real
            # clusters — retry transient I/O before giving up
            try:
                data = io_policy().call(np.load, path)
            except Exception as e:
                raise IOError(
                    f"init_server: checkpoint at {path} is unreadable "
                    f"({type(e).__name__}: {e})") from e
            for n in data.files:
                scope.set_var(n, data[n])

    def run_server(self):
        """Blocks serving send/get/barrier until every trainer completes
        (reference run_server -> listen_and_serv)."""
        from ...executor import Executor

        ep = self._current_endpoint()
        exe = Executor()
        exe.run(self._transpiler.get_pserver_program(ep))

    def _current_endpoint(self):
        eps = self.server_endpoints
        return eps[self._role_maker.server_index()]

    def save_persistables(self, executor, dirname, main_program=None):
        """Trainer-side persistables locally + checkpoint_notify so every
        pserver saves ITS parameter slices in place (reference fleet
        save_persistables + checkpoint_notify — slices never travel)."""
        from ... import io
        from ...distributed.ps_rpc import PSClient
        from ...resilience.retry import rpc_policy

        io.save_persistables(executor, dirname,
                             main_program or self._origin_main)
        client = PSClient.get(tuple(self.server_endpoints),
                              self.worker_index())
        # the notify itself is idempotent (each pserver rewrites its own
        # slice file atomically), so a retried RPC is safe
        rpc_policy().call(client.checkpoint_notify, dirname)

    # -- worker lifecycle ----------------------------------------------------
    def init_worker(self):
        """Sync mode: connections are lazy (PSClient.get on first send).
        Async mode: build + start the Communicator (reference fleet
        init_worker -> communicator init/start)."""
        t = self._transpiler
        if t is None or t.sync_mode:
            return
        from ...distributed.communicator import Communicator
        from ...distributed.ps_rpc import PSClient
        from ...executor import global_scope

        send_ctx, recv_ctx = t.get_communicator_context()
        client = PSClient.get(tuple(self.server_endpoints),
                              self.worker_index())
        self._communicator = Communicator(send_ctx, recv_ctx, client,
                                          global_scope())
        self._communicator.start()

    def stop_worker(self):
        from ...executor import Executor

        comm = getattr(self, "_communicator", None)
        if comm is not None:
            comm.stop()  # drain send queues + final param pull
            self._communicator = None
        Executor().close()  # send_complete to every pserver


class TranspilerOptimizer:
    """reference parameter_server TranspilerOptimizer: minimize() then
    DistributeTranspiler rewrite against the fleet's role layout."""

    def __init__(self, fleet_obj: ParameterServerFleet, inner, strategy=None):
        self._fleet = fleet_obj
        self._inner = inner
        self._config = strategy  # DistributeTranspilerConfig or None

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from ...framework import default_startup_program
        from ...transpiler import DistributeTranspiler

        ops, pgs = self._inner.minimize(loss, startup_program,
                                        parameter_list, no_grad_set)
        f = self._fleet
        f._origin_main = loss.block.program
        f._origin_startup = startup_program or default_startup_program()
        t = DistributeTranspiler(config=self._config)
        t.transpile(
            trainer_id=max(f.worker_index(), 0),
            program=f._origin_main,
            pservers=",".join(f.server_endpoints),
            trainers=f.worker_num(),
            sync_mode=getattr(self._config, "sync_mode", True)
            if self._config is not None else True,
            startup_program=f._origin_startup,
        )
        f._transpiler = t
        return ops, pgs


fleet = ParameterServerFleet()
