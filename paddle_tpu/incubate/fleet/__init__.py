from .base import (  # noqa: F401
    CollectiveOptimizer,
    DistributedStrategy,
    Fleet,
    PaddleCloudRoleMaker,
    Role,
    UserDefinedRoleMaker,
    fleet,
)
from . import parameter_server  # noqa: F401
