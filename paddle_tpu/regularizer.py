"""Weight-decay regularizers appended during apply_gradients.

Reference: /root/reference/python/paddle/fluid/regularizer.py
(append_regularization_ops:30, L2DecayRegularizer:120, L1DecayRegularizer:180).
"""
from __future__ import annotations

from .layer_helper import LayerHelper

__all__ = ["L1Decay", "L2Decay", "L1DecayRegularizer", "L2DecayRegularizer", "append_regularization_ops"]


class WeightDecayRegularizer:
    def append_regularization_op(self, param, grad, helper):
        raise NotImplementedError


class L2DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def append_regularization_op(self, param, grad, helper):
        decay = helper.create_variable_for_type_inference(grad.dtype)
        helper.append_op(
            "scale",
            inputs={"X": [param]},
            outputs={"Out": [decay]},
            attrs={"scale": self._coeff},
        )
        return decay


class L1DecayRegularizer(WeightDecayRegularizer):
    def __init__(self, regularization_coeff=0.0):
        self._coeff = regularization_coeff

    def append_regularization_op(self, param, grad, helper):
        sign = helper.create_variable_for_type_inference(grad.dtype)
        helper.append_op("sign", inputs={"X": [param]}, outputs={"Out": [sign]})
        decay = helper.create_variable_for_type_inference(grad.dtype)
        helper.append_op(
            "scale",
            inputs={"X": [sign]},
            outputs={"Out": [decay]},
            attrs={"scale": self._coeff},
        )
        return decay


def append_regularization_ops(params_grads, regularization=None):
    """grad += coeff * decay(param) for each param (reference :30). Per-param
    regularizer (ParamAttr) overrides the global one."""
    out = []
    helper = LayerHelper("regularization")
    for param, grad in params_grads:
        reg = getattr(param, "regularizer", None) or regularization
        if reg is None or grad is None:
            out.append((param, grad))
            continue
        decay = reg.append_regularization_op(param, grad, helper)
        new_grad = helper.create_variable_for_type_inference(grad.dtype)
        helper.append_op("sum", inputs={"X": [grad, decay]}, outputs={"Out": [new_grad]})
        out.append((param, new_grad))
    return out


L1Decay = L1DecayRegularizer
L2Decay = L2DecayRegularizer
