"""Global flags registry.

TPU-native equivalent of the reference's gflags hub
(/root/reference/paddle/fluid/platform/flags.cc) + the Python env bootstrap
(/root/reference/python/paddle/fluid/__init__.py:152 read_env_flags): flags are
declared here with defaults, overridden from the environment (`FLAGS_<name>`)
at import, and adjustable at runtime via `set_flags`.

Only flags that DO something on this runtime are declared; CUDA/allocator
knobs from the reference are subsumed by XLA and intentionally absent.
"""
from __future__ import annotations

import os
from typing import Any

_FLAGS: dict[str, Any] = {}
_DEFS: dict[str, tuple[type, str]] = {}


def _define(name: str, default, help: str):
    ftype = type(default)
    _DEFS[name] = (ftype, help)
    env = os.environ.get("FLAGS_" + name)
    if env is not None:
        _FLAGS[name] = _parse(ftype, env)
    else:
        _FLAGS[name] = default


def _parse(ftype, text: str):
    if ftype is bool:
        return text.strip().lower() in ("1", "true", "yes", "on")
    return ftype(text)


def get_flag(name: str):
    if name not in _FLAGS:
        raise KeyError(f"unknown flag '{name}'; known: {sorted(_FLAGS)}")
    return _FLAGS[name]


def set_flags(flags: dict):
    """Runtime override (reference fluid.core.init_gflags analogue)."""
    for k, v in flags.items():
        k = k[len("FLAGS_"):] if k.startswith("FLAGS_") else k
        if k not in _DEFS:
            raise KeyError(f"unknown flag '{k}'; known: {sorted(_DEFS)}")
        _FLAGS[k] = _parse(_DEFS[k][0], str(v)) if not isinstance(v, _DEFS[k][0]) else v


def all_flags() -> dict:
    return dict(_FLAGS)


# -- declarations ------------------------------------------------------------
_define("conv_implicit_gemm", "auto",
        "lower eligible conv2d ops as implicit-GEMM im2col matmuls: the "
        "contraction dim folds C*kh*kw (e.g. 64*9=576 — full 128-lane MXU "
        "fill where the direct conv contracts K=C=64). 'auto' (default) "
        "enables per shape where the tile-fill-vs-HBM cost model in "
        "ops/nn_ops.py predicts a win (narrow-channel convs; the model's "
        "constants are the measured PERF.md rooflines); 'on' forces every "
        "groups=1 conv (incl. 1x1-as-matmul) for A/B runs; 'off' disables")
_define("bn_fuse_stats", True,
        "fuse conv2d -> batch_norm(training) pairs into one conv2d_bn op at "
        "minimize() time (passes.fuse_conv_bn_stats): E[x]/E[x2] batch "
        "statistics are computed in the conv's epilogue from the fp32 GEMM "
        "accumulator (one pass, fp32 statistics per the AMP gray-list "
        "discipline) instead of a separate HBM traversal of the conv "
        "output — the measured 17-35%% BN-stats share of ResNet stage time "
        "(PERF.md r5)")
_define("tuning_mode", "off",
        "framework-wide autotuner (paddle_tpu/tuning/): 'off' keeps every "
        "lever on its pre-tuner logic; 'consult' resolves tunable decisions "
        "(conv lowering, attention backend, conv+BN fusion, AMP gray ops, "
        "bucket boundaries) through the tier policy exact-DB-hit -> "
        "learned cost model -> analytic prior -> conservative default; "
        "'sweep' resolves analytically but records every distinct decision "
        "key into the DB as a candidate so tools/tune.py knows what to "
        "measure; 'explore' is consult plus bounded online measurement — "
        "tuning/learned/explore.py probes one recorded candidate every "
        "FLAGS_tuning_explore_every executor steps and promotes "
        "out-of-interference-band verdicts to swept entries")
_define("tuning_db", "",
        "path of the persistent tuning decision database (schema-versioned "
        "JSON, atomic temp+rename writes; tuning/db.py). Empty = no DB: "
        "consult mode degrades to the analytic priors. A corrupt/missing "
        "file warns once and falls back to analytic — never an error")
_define("tuning_measurements", "",
        "path of the append-only JSONL measurement store "
        "(tuning/learned/store.py) the sweeps, A/B harnesses, bench rounds "
        "and explore probes append raw per-arm window timings to — the "
        "learned cost model's training set. Empty = derived from "
        "FLAGS_tuning_db (<db stem>.measurements.jsonl next to it); with "
        "no DB either, nothing records")
_define("tuning_record", "auto",
        "measurement-store gate (tuning/learned/store.py): 'auto' "
        "(default) records from the tools (tune.py sweeps, the A/B "
        "harnesses) whenever a store path resolves but from the runtime "
        "only under tuning_mode sweep/explore; 'on' always records; 'off' "
        "never records")
_define("tuning_model", "",
        "path of the trained cost-model artifact (tools/costmodel.py "
        "train; tuning/learned/model.py). Empty = derived from "
        "FLAGS_tuning_db (<db stem>.model.json next to it). Missing file "
        "= no learned tier; a corrupt file warns once and the policy "
        "falls back to the analytic prior — never an error")
_define("tuning_explore_every", 64,
        "explore-mode pacing: probe at most one candidate key per this "
        "many executor steps (tuning/learned/explore.py). Each probe is a "
        "few tiny timed windows in the async window-drain gap; verdicts "
        "inside the interference band never overwrite the analytic "
        "decision. <= 0 disables probing even in explore mode")
_define("pallas_epilogue", "auto",
        "fused normalize+affine+activation(+residual) epilogue kernels "
        "(ops/pallas_kernels/epilogue.py). 'auto' (default): when "
        "FLAGS_tuning_mode is consult/sweep, minimize() rewrites eligible "
        "batch_norm/conv2d_bn/layer_norm -> activation (-> residual-add) "
        "chains into one op whose epilogue DISPATCHES through the tuning "
        "DB — the analytic prior is XLA (the plain jnp composition, "
        "bit-identical to the unfused chain), so the Pallas kernel engages "
        "only where a swept verdict keeps it; with tuning off, 'auto' "
        "changes nothing. 'on' forces the kernel wherever it can run (the "
        "A/B arms); 'off' disables the rewrite entirely")
_define("attention_force_backend", "",
        "A/B-harness override for the fused-attention dispatch: force every "
        "attention_backend decision to this arm ('xla', 'pallas_short', "
        "'pallas_short128', 'flash_bundled') regardless of the tuning DB "
        "and the analytic prior. A forced backend the platform/shape cannot "
        "run still degrades to the XLA reference at dispatch (so an arm is "
        "honest about where its kernel engaged). Empty (default) = normal "
        "three-tier dispatch")
_define("pallas_xent", False,
        "route large-vocab hard-label softmax_with_cross_entropy through "
        "the Pallas TPU kernel (ops/pallas_kernels/xent.py). Default OFF: "
        "measured 8.5% SLOWER end-to-end than XLA's in-model fusion at "
        "BERT shapes (PERF.md r5) — kept as a measured-and-retired lever")
_define("check_nan_inf", False,
        "run eagerly and validate every op's floating outputs are finite, "
        "raising with op attribution (reference operator.cc:949)")
_define("op_callstack", True,
        "capture the Python creation stack of every Operator for error "
        "attribution (reference framework/op_call_stack.cc)")
_define("benchmark", False,
        "block on the device after every Executor.run for timing-accurate "
        "debugging (reference operator.cc:926)")
_define("cpu_deterministic", False,
        "request deterministic XLA reductions (maps to XLA determinism; "
        "reference flags.cc:98)")
_define("profiler_dir", "/tmp/paddle_tpu_profile",
        "default trace output directory for profiler.profiler()")
# unified telemetry layer (observability/: registry, exporters, spans, SLO)
_define("obs_enable", True,
        "the observability layer's histogram/event/span machinery "
        "(observability/registry.py): ON records streaming-percentile "
        "histograms, the structured event ring, and TraceAnnotation+JSONL "
        "spans alongside every counter; OFF reduces the layer to the bare "
        "counter/gauge/stage accumulators (exactly the pre-ISSUE-13 cost — "
        "profiler.stage_counters() and the serving stats keep working "
        "either way). bench.py measures the on-vs-off overhead on the "
        "timed-window protocol; tools/gate.py --obs fails it above 2%")
_define("obs_jsonl_dir", "",
        "directory for the JSONL telemetry stream: when set, every event "
        "and span record appends atomically to <dir>/obs.jsonl (rotated at "
        "FLAGS_obs_jsonl_rotate_mb to obs.jsonl.1). Empty (default) "
        "disables the stream; tools/obs.py tails/summarizes the file")
_define("obs_jsonl_rotate_mb", 8.0,
        "size trigger in MB for rotating the FLAGS_obs_jsonl_dir stream "
        "(os.replace to <path>.1 — the live path always holds a complete "
        "stream)")
_define("obs_prometheus_path", "",
        "when set, observability.export_prometheus() writes the registry "
        "snapshot here in Prometheus text exposition format (atomic "
        "temp+rename). Empty (default) disables the file export")
_define("obs_http_port", 0,
        "serve the live registry snapshot at http://127.0.0.1:<port>"
        "/metrics (Prometheus text) from a stdlib daemon thread; "
        "0 (default) disables the endpoint")
_define("obs_max_events", 1024,
        "capacity of the in-memory structured-event ring the registry "
        "keeps for snapshot()['events'] (the JSONL stream is unbounded; "
        "this only caps what a snapshot carries)")
_define("obs_slo_p99_ms", 0.0,
        "SLO monitor (observability/slo.py): warn/alert when the "
        "serving.request_s p99 exceeds this many milliseconds over the "
        "rolling window; <=0 (default) disables the latency rule")
_define("obs_slo_min_hit_rate", 0.0,
        "SLO monitor: warn/alert when the prefix-cache hit rate "
        "(prefix_hit_tokens over all prefill tokens) falls below this "
        "floor; <=0 (default) disables the rule")
_define("obs_slo_max_leaked_pages", 0,
        "SLO monitor: warn/alert when the serving.leaked_pages gauge "
        "exceeds this count (default 0 — any leak breaches, matching the "
        "gate's zero-leak invariant)")
# multichip collective-overlap knobs (parallel/collective.py, sharding.py,
# pipeline.py — the measured scaling campaign, see README "Multichip")
_define("allreduce_bucket_mb", 4.0,
        "gradient-bucket size in MB for the collective (shard_map) regime: "
        "GradAllReduce coalesces grads into reverse-topological buckets of "
        "about this many megabytes and inserts each bucket's mean-allreduce "
        "right where its last gradient is produced, so the reduce of "
        "already-finished buckets overlaps the remaining backward compute "
        "instead of serializing after it. <=0 restores the per-gradient "
        "allreduce inserted before the optimizer ops (the overlap-off A/B "
        "arm). Under FLAGS_tuning_mode=consult the size is resolved through "
        "the tuning DB ('collective|mesh=..|payload=..' keys, this flag is "
        "the analytic prior); tools/_mc_ab.py sweeps and records verdicts")
_define("zero1", False,
        "ZeRO-1 optimizer-state sharding for the collective regime "
        "(parallel/sharding.py apply_zero1): each eligible gradient is "
        "reduce-scattered over the data axis, the optimizer op updates only "
        "this rank's 1/nranks shard of the parameter (and of its moment "
        "accumulators), and the updated shards are allgathered back — the "
        "gathers sit at the program tail so with FLAGS_max_inflight_steps>1 "
        "they overlap the next step's first buckets. Parameters whose "
        "leading dim does not divide by nranks fall back to the bucketed "
        "allreduce path")
_define("pipeline_schedule", "1f1b",
        "default microbatch schedule for PipelineOptimizer / "
        "build_pipeline_plan when none is passed explicitly: '1f1b' "
        "(PipeDream-flush steady state — at most ~n_stages microbatches in "
        "flight, boundary stash freed as each backward completes) or "
        "'gpipe' (naive fill-drain: all forwards then all backwards, stash "
        "grows with num_microbatches). Both are numerically identical; "
        "PipelinePlan.last_bubble records the per-stage bubble accounting "
        "either way")
# async Communicator knobs (reference python/paddle/fluid/__init__.py:65-71)
_define("communicator_max_merge_var_num", 20,
        "max gradients merged into one send (reference "
        "communicator_max_merge_var_num)")
_define("communicator_send_queue_size", 20,
        "per-gradient send queue capacity; push blocks when full")
_define("communicator_independent_recv_thread", True,
        "run the parameter recv thread independently of sends")
_define("communicator_min_send_grad_num_before_recv", 20,
        "grads sent before the recv thread starts pulling params")
_define("communicator_send_wait_times", 5,
        "short waits the send thread spends collecting grads to merge")
# async feed/dispatch pipeline knobs (pipeline/, executor.run_async)
_define("max_inflight_steps", 4,
        "Executor.run_async window: dispatched-but-undrained steps allowed "
        "before the host blocks on the oldest step's completion token. "
        "1 = fully synchronous per step; <=0 = unbounded runahead")
_define("device_prefetch_depth", 2,
        "DeviceLoader: batches staged into device memory ahead of the "
        "consumer by the background transfer thread (train_from_dataset "
        "and PyReader use_double_buffer); <=0 disables device prefetch in "
        "train_from_dataset")
_define("feed_bucketing", False,
        "pad ragged tail batches up to the bucket size (DataFeeder bucket / "
        "Dataset batch_size) and attach a '<batch_mask>' row-mask feed so "
        "the (program, feed-signature) compile cache is hit instead of "
        "recompiling the last batch of every epoch; loss/metric ops must "
        "honor the mask for exact numerics (see README)")
# LLM serving runtime knobs (serving/: paged KV cache + continuous batching)
_define("serving_page_size", 16,
        "KV-cache page size in token slots (serving/kv_cache.py): every "
        "request's context is stored in fixed-size pages of the "
        "preallocated HBM pool, so no request ever owns a max-seq-len "
        "buffer. Larger pages waste tail slots; smaller pages grow the "
        "page-table/bookkeeping overhead per decode step")
_define("serving_pool_pages", 512,
        "total pages in the preallocated KV pool (per layer, K and V "
        "each). Pool bytes per layer = 2 * pages * page_size * num_heads * "
        "head_dim * dtype_size. When the free list runs dry, admission "
        "backpressures (requests queue) and mid-decode growth preempts the "
        "youngest request back to the waiting queue (recompute on "
        "re-admission)")
_define("serving_max_inflight", 8,
        "continuous-batching scheduler: max requests decoding concurrently "
        "(the decode batch bucket's ceiling). Admission stops at this many "
        "running requests even when KV pages remain")
_define("serving_sched_policy", "fcfs",
        "admission order for waiting requests: 'fcfs' (arrival order) or "
        "'sjf' (shortest context first — minimizes queue latency under "
        "mixed lengths at the cost of starving long prompts under "
        "sustained load)")
_define("serving_prefix_cache", True,
        "copy-on-write prefix caching (serving/kv_cache.PrefixCache): "
        "prompts are indexed at page granularity and later requests "
        "sharing a prefix map the cached pages with a refcount bump "
        "instead of re-prefilling; the first write to a shared page "
        "copy-on-writes it. Cached pages are evicted LRU-first under pool "
        "pressure, so the cache can only ever trade idle HBM for prefill "
        "compute")
_define("serving_draft_k", 0,
        "speculative decoding draft length (serving/engine): each decode "
        "step self-drafts k tokens per request (n-gram continuation of "
        "its own history) and verifies all k+1 positions in one batched "
        "window step — exact under greedy decoding, accepting 1..k+1 "
        "tokens per step. 0 disables (plain one-token decode)")
_define("serving_tp", 1,
        "tensor-parallel degree for the serving engine: attention heads "
        "and the KV pool shard over a `tp` device mesh "
        "(parallel/mesh.make_tp_mesh + GSPMD annotations); "
        "paged_decode_attention keys the tuning DB on the per-shard "
        "(nh/tp) shape. Must divide the model's num_heads; 1 disables")
# serving resilience knobs (deadlines, shedding, degradation, supervision —
# see README "Serving resilience")
_define("serving_deadline_s", 0.0,
        "default per-request TTL in seconds, measured from submit: a "
        "request past its deadline is expired at admission and between "
        "decode steps with every KV page returned, surfaced as the "
        "'deadline_exceeded' terminal state (partial tokens kept). "
        "Per-request `deadline_s=` on submit overrides; <=0 (default) "
        "means no deadline")
_define("serving_priority_default", 1,
        "priority class assigned to requests submitted without an explicit "
        "priority (higher = more important). Under overload the shedder "
        "evicts lowest-priority WAITING requests first; ties shed the "
        "youngest")
_define("serving_shed_occupancy", 0.0,
        "admission-control floor on KV pool occupancy: when pages_in_use / "
        "num_pages crosses this fraction, new submits shed lower-priority "
        "waiters or are rejected with a retry-after hint "
        "(AdmissionRejected) instead of queueing unboundedly. <=0 "
        "(default) disables the occupancy trigger")
_define("serving_shed_queue_depth", 0,
        "admission-control floor on waiting-queue depth: a submit that "
        "would leave more than this many requests WAITING sheds "
        "lower-priority waiters or is rejected (AdmissionRejected). <=0 "
        "(default) disables the depth trigger")
_define("serving_shed_ttft_p99_ms", 0.0,
        "SLO floor on p99 time-to-first-token in milliseconds, read from "
        "the serving.ttft_s histogram via the SloMonitor: while p99 TTFT "
        "sits above this, new submits shed or reject exactly as under the "
        "occupancy/depth triggers. Needs FLAGS_obs_enable for the "
        "histogram to populate; <=0 (default) disables the SLO trigger")
_define("serving_degrade_after", 4,
        "graceful-degradation ladder patience: consecutive overloaded "
        "scheduler steps before climbing one rung (disable speculative "
        "decode -> shrink decode lookahead -> evict prefix-cache LRU tail "
        "-> shed waiters), and consecutive calm steps before descending "
        "one. Each climb is counted (serving.ladder.*) and evented")
_define("serving_step_retries", 3,
        "engine supervisor: max attempts for one compiled "
        "prefill/decode/window/COW dispatch under the serving RetryPolicy "
        "(transient transport faults retry with millisecond backoff; the "
        "compiled step writes fixed slots so a retry is idempotent). "
        "Exhaustion triggers the recovery pass: quarantine poisoned "
        "requests, audit + rebuild the pool, replay survivors from their "
        "prompts")
_define("serving_audit_every", 16,
        "run the PagedKVPool.check_consistency invariant audit (free list "
        "and mapped ordinals partition the pool; refcounts equal live "
        "holder counts) every N scheduler steps; a dirty audit triggers "
        "the recovery pass. 1 audits every step (chaos drills); <=0 "
        "disables the periodic audit")
# serving fleet knobs (serving/fleet/: router + N engine replicas with
# failure-domain isolation — see README "Serving fleet")
_define("fleet_replicas", 1,
        "default replica count for FleetRouter(): N independent engine "
        "replicas (each its own KV pool, prefix cache, compile caches — "
        "one failure domain each) behind the health-checked router. "
        "Constructor argument overrides; 1 degenerates to a supervised "
        "single engine")
_define("fleet_heartbeat_s", 2.0,
        "per-replica heartbeat deadline in seconds: a replica whose last "
        "beat (stamped after every pump iteration, skipped by the "
        "fleet_heartbeat_slow/hang/kill fault sites) is older than this is "
        "declared DEAD and its in-flight requests fail over to survivors. "
        "Scaled by FLAGS_watchdog_scale so loaded CI boxes widen the "
        "margin without editing chaos plans; <=0 disables health checking "
        "(replicas only die by explicit retire)")
_define("fleet_failover_budget", 3,
        "max failover re-placements per request over its lifetime (the "
        "fleet RetryPolicy's max_attempts): each replica death costs the "
        "request one attempt; past the budget the request lands in the "
        "'failed' terminal state instead of hopping forever between dying "
        "replicas")
_define("fleet_affinity", True,
        "prefix-cache-affinity placement: requests hash their prompt head "
        "(FLAGS_fleet_affinity_tokens tokens) to a preferred replica so "
        "same-system-prompt traffic lands on the replica already holding "
        "those pages; an unhealthy/rejecting target degrades to "
        "least-loaded. False = pure least-loaded placement")
_define("fleet_affinity_tokens", 16,
        "prompt-head length (tokens) hashed for affinity placement; "
        "prompts shorter than this hash whole. Align to the page size so "
        "requests sharing cached pages share a routing key")
# disaggregated prefill/decode serving (serving/fleet/handoff.py — see
# README "Disaggregated serving")
_define("disagg_prefill_replicas", 0,
        "split the fleet into roles: the first N replicas become "
        "prefill-only engines and the rest decode engines, all over ONE "
        "shared PagedKVPool, with prefill->decode KV handoff via TTL'd "
        "leases (FleetRouter roles= overrides; must leave at least one "
        "decode replica). 0 = co-located serving, every replica does both "
        "stages")
_define("disagg_lease_ttl_s", 2.0,
        "KV handoff lease time-to-live in seconds: a PREPARED lease whose "
        "commit has not arrived within the TTL is reaped — its page pin "
        "returns to the shared pool and the router replays the prompt "
        "under the normal failover budget. Scaled by FLAGS_watchdog_scale "
        "(slow CI must not reap healthy handoffs); commits that lose the "
        "expiry race are rejected atomically, never half-adopted")
# learned serving control (serving/control/ — see README "Learned serving
# control")
_define("serve_control_mode", "shadow",
        "the learned serving controller: 'off' disables observation "
        "entirely; 'shadow' (default) observes regimes, proposes knob "
        "configs and logs/counts them but never applies one; 'apply' "
        "stages confident proposals for adoption at the next safe "
        "boundary (engine idle gap / router epoch tick), re-running "
        "warmup_decode when the decode bucket geometry changes")
_define("serve_control_store", "",
        "measurement-store path for serving.control regime rows; empty "
        "falls back to the tuning store (FLAGS_tuning_measurements / "
        "derived from FLAGS_tuning_db) — kernels and regimes share one "
        "append-only dataset unless split out")
_define("serve_control_model", "",
        "trained control-model artifact; empty falls back to "
        "FLAGS_tuning_model (the serving.control group ships inside the "
        "same tools/costmodel.py artifact). Missing = hand flags; corrupt "
        "warns once and fails open to the hand flags")
_define("serve_control_conf", 0.6,
        "confidence threshold: a control proposal stands only when the "
        "trained group's holdout rank accuracy clears this floor (the "
        "stricter of this and the model-wide gate); below it every "
        "regime serves the hand-flag config")
_define("serve_control_epoch_s", 5.0,
        "controller epoch interval in seconds: regimes are observed, "
        "realized goodput recorded and proposals made at most once per "
        "epoch per engine. <=0 disables the tick entirely")
# tiered giant-embedding knobs (paddle_tpu/embedding/, the minimize()-time
# rewrite in passes.rewrite_tiered_embeddings — see README "Tiered
# embeddings")
_define("emb_hbm_budget_mb", 0.0,
        "per-table HBM budget in MB for embedding tables: at minimize() "
        "time every lookup_table whose table exceeds this is rewritten onto "
        "the two-tier path — host-memory shards behind a device-resident "
        "hot-ID cache sized to the budget, with miss prefetch resolved off "
        "the step on the feed pipeline. <=0 (default) disables tiering "
        "entirely: every table compiles to the existing single-gather path "
        "bitwise-unchanged")
_define("emb_cache_slots", 0,
        "hot-ID cache rows per tiered table; 0 (default) derives the slot "
        "count from FLAGS_emb_hbm_budget_mb / row bytes through the tuning "
        "DB ('embedding|table=..' keys — a swept verdict overrides the "
        "budget-derived prior). A positive value is a hard per-run force "
        "(A/B arms, tools/tune.py --what embedding)")
_define("emb_prefetch_rows", 0,
        "fixed width of the per-step miss-prefetch buffer (the install feed "
        "is part of the compile signature, so it cannot vary per batch); "
        "0 = auto — pow2 of the first batch's miss count, growing (one "
        "recompile) if a later batch overflows. A positive value forces the "
        "width; batches missing more rows still grow it rather than fail")
_define("emb_admit_min_freq", 1,
        "frequency-based cache admission: an id seen fewer than this many "
        "times total enters the cache on probation (zero accumulated "
        "frequency, first in line for eviction) instead of with its batch "
        "count — keeps one-shot ids from displacing hot rows. 1 (default) "
        "admits every miss at full weight; eviction is min-frequency with "
        "LRU tie-break either way")
_define("emb_host_shards", 1,
        "contiguous row shards per host-tier table (one numpy allocation "
        "each) — the in-process analogue of the per-pserver row partition, "
        "and the placement unit for a future multi-host tier")
_define("emb_ckpt_base_every", 4,
        "streaming delta checkpoints: a full host-tier base snapshot is "
        "written every this-many saves (atomically, to the checkpoint "
        "root); the saves between write only the rows dirtied since the "
        "base (cumulative delta in the step directory; restore = base + "
        "that one delta)")
# distributed liveness knobs (distributed/ps_rpc.py, resilience/watchdog.py)
_define("rpc_deadline", 180000,
        "pserver RPC deadline in MILLISECONDS (reference FLAGS_rpc_deadline, "
        "python/paddle/fluid/__init__.py:65-71): bounds pserver connects, "
        "every request/reply round, and — doubled, to leave the server room "
        "to evict a dead peer first — the sync barrier wait. The server's "
        "liveness monitor also derives its dead-trainer eviction deadline "
        "from this when FLAGS_heartbeat_timeout_ms is 0")
_define("heartbeat_interval_ms", 500,
        "trainer->pserver heartbeat cadence (PSClient daemon thread, "
        "auto-started at the first sync barrier); <=0 disables heartbeats")
_define("heartbeat_timeout_ms", 0,
        "server-side liveness deadline: a trainer holding up a sync round "
        "whose last heartbeat (or RPC) is older than this is EVICTED from "
        "the barrier; 0 = derive from FLAGS_rpc_deadline")
_define("watchdog_stall_s", 600.0,
        "hang watchdog window for Executor.run_async/wait completion-token "
        "drains and DeviceLoader batch waits: if no progress within this "
        "many seconds a StallError carrying the in-flight state dump is "
        "raised instead of blocking forever; <=0 disables the watchdog")
_define("watchdog_scale", 1.0,
        "global multiplier on every watchdog/heartbeat deadline "
        "(FLAGS_watchdog_stall_s windows and the fleet's "
        "FLAGS_fleet_heartbeat_s): set >1 on loaded/slow CI runners so "
        "chaos tests don't flake on scheduling noise without rewriting "
        "per-site deadlines; values <1 are clamped to 1 (the margin only "
        "ever widens)")
# resilience runtime knobs (resilience/: faults, retry, checkpoint, runner)
_define("fault_plan", "",
        "deterministic fault-injection plan for the named runtime sites "
        "(resilience/faults.py grammar, e.g. 'ckpt.write:2;ps.send:1' or "
        "'rand:p=0.1,seed=7,max=5'); empty = injection off")
# numeric guardrail knobs (resilience/guardrails.py, ops health_sentinel)
_define("guard_numerics", False,
        "append the in-graph health sentinel to every minimize(): loss "
        "finiteness, global grad norm and found_inf are computed INSIDE the "
        "compiled step (emitted with the async completion token, ~zero "
        "cost), and a non-finite/spiking step's parameter update is skipped "
        "branchlessly (the AMP found_inf skip generalized to fp32)")
_define("guard_bad_step_budget", 3,
        "StepGuard: consecutive bad (skipped) steps tolerated before the "
        "guard rewinds to the last good checkpoint; the skip itself is "
        "always in-graph and free")
_define("guard_spike_factor", 0.0,
        "health sentinel loss-spike gate: a finite loss greater than this "
        "factor times the in-graph loss EMA counts as a bad step and skips "
        "the update (e.g. 10.0); <=0 disables spike gating (non-finite "
        "gating is always on under FLAGS_guard_numerics). Baked into the "
        "program at minimize() time")
_define("guard_lr_backoff", 0.5,
        "StepGuard: multiply the learning rate by this factor after each "
        "rewind (recovery ladder: skip -> rewind -> LR backoff -> surface); "
        "1.0 disables the backoff")
_define("guard_max_rewinds", 3,
        "StepGuard: rewinds tolerated across a run before the guard stops "
        "recovering and surfaces GuardError")
_define("feed_skip_corrupt", False,
        "reader robustness: a sample/batch whose ndarray conversion raises "
        "(corrupt record) is skipped and counted on the profiler "
        "'feed.skip_corrupt' counter instead of killing the epoch "
        "(DataFeeder.feed, train_from_dataset, DeviceLoader placement)")
_define("retry_max_attempts", 4,
        "RetryPolicy: attempts per call for transient RPC/IO failures")
_define("retry_base_delay_ms", 50,
        "RetryPolicy: first backoff delay in milliseconds")
_define("retry_max_delay_ms", 2000,
        "RetryPolicy: backoff ceiling in milliseconds")
_define("retry_deadline_s", 30.0,
        "RetryPolicy: wall-clock budget for all attempts of one call; "
        "0 = unbounded")
_define("ckpt_keep_last_k", 3,
        "CheckpointManager: versioned step directories kept after GC")
_define("ckpt_save_every", 10,
        "CheckpointedRunner: checkpoint cadence in steps")
_define("runner_max_retries", 5,
        "CheckpointedRunner: per-step recovery attempts (restore+retry, "
        "cache invalidation, disable_jit) before the error surfaces")
