"""Host-side weighted averaging across fetched batch metrics.

Parity with /root/reference/python/paddle/fluid/average.py: `WeightedAverage`
accumulates (value, weight) pairs — typically per-batch losses fetched from
`Executor.run` with their batch sizes — and reports the running weighted
mean. Pure host bookkeeping; nothing here touches the device.
"""
from __future__ import annotations

import numpy as np

__all__ = ["WeightedAverage"]


def _is_number_or_matrix(x) -> bool:
    return isinstance(x, (int, float, complex, np.number, np.ndarray))


class WeightedAverage:
    """reference average.py:36 — add(value, weight), eval(); reset() clears."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.numerator = None
        self.denominator = None

    def add(self, value, weight):
        if not _is_number_or_matrix(value):
            raise ValueError(
                "The 'value' must be a number(int, float) or a numpy ndarray")
        if not _is_number_or_matrix(weight):
            raise ValueError("The 'weight' must be a number(int, float)")
        value = np.mean(np.asarray(value, dtype=np.float64))
        weight = float(np.asarray(weight, dtype=np.float64).reshape(-1)[0])
        if self.numerator is None or self.denominator is None:
            self.numerator = value * weight
            self.denominator = weight
        else:
            self.numerator += value * weight
            self.denominator += weight

    def eval(self):
        if self.numerator is None or self.denominator is None:
            raise ValueError(
                "There is no data to be averaged in WeightedAverage")
        if self.denominator == 0:
            raise ValueError("The total weight is zero, can not average")
        return self.numerator / self.denominator
