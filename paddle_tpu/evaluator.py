"""In-program metric evaluators with persistent accumulation state.

Parity with /root/reference/python/paddle/fluid/evaluator.py (Evaluator:40,
ChunkEvaluator:118, EditDistance:189): each evaluator appends its per-batch
metric ops AND running-sum accumulator updates to the current main program at
construction time, so every `Executor.run` of the program advances the
states; `reset()` zeroes them between passes and `eval()` folds the
accumulated counts into the epoch metric. The reference deprecation note
holds here too — `metrics.py` classes are the host-side successors — but the
in-program form stays useful when the metric must ride the compiled step
(one fetch per epoch instead of per batch).

Departure: `eval()` reads the accumulated state from the scope and finishes
the arithmetic on host instead of building a second program — the states are
a handful of scalars, and this keeps eval() callable mid-epoch without
recompilation.
"""
from __future__ import annotations

import numpy as np

from . import layers
from .framework import Program, program_guard
from .initializer import Constant
from .layer_helper import LayerHelper

__all__ = ["Evaluator", "ChunkEvaluator", "EditDistance"]


class Evaluator:
    """Base evaluator (reference evaluator.py:40): owns persistable state
    vars updated by ops this evaluator appended to the main program."""

    def __init__(self, name, **kwargs):
        self.helper = LayerHelper(name, **kwargs)
        self.states: list = []
        self.metrics: list = []

    def _create_state(self, suffix, dtype, shape):
        state = self.helper.create_or_get_global_variable(
            f"{self.helper.name}.{suffix}", list(shape), dtype,
            initializer=Constant(0.0))
        self.states.append(state)
        return state

    def reset(self, executor, reset_program=None):
        """Zero every accumulation state (reference evaluator.py:57)."""
        if reset_program is None:
            reset_program = Program()
        with program_guard(reset_program):
            for state in self.states:
                layers.fill_constant(
                    shape=state.shape, dtype=state.dtype.value, value=0.0,
                    out=state)
        executor.run(reset_program)

    def eval(self, executor, eval_program=None):
        raise NotImplementedError()

    def _state_value(self, state) -> np.ndarray:
        from .executor import global_scope

        v = global_scope().find_var(state.name)
        if v is None:
            raise RuntimeError(
                f"evaluator state '{state.name}' not initialized — run the "
                f"startup program (or reset()) first")
        return np.asarray(v)


class ChunkEvaluator(Evaluator):
    """Accumulate chunk counts across batches and report epoch-level
    precision/recall/F1 (reference evaluator.py:118 over chunk_eval)."""

    def __init__(self, input, label, chunk_scheme, num_chunk_types,
                 excluded_chunk_types=None):
        super().__init__("chunk_eval")
        (precision, recall, f1, num_infer, num_label,
         num_correct) = layers.chunk_eval(
            input=input, label=label, chunk_scheme=chunk_scheme,
            num_chunk_types=num_chunk_types,
            excluded_chunk_types=excluded_chunk_types)
        # accumulate in float32: the per-batch counts are int64, and the
        # runtime's int path truncates to int32 — chunk counts fit f32
        # exactly up to 2^24 per epoch
        self.num_infer_chunks = self._create_state(
            "num_infer_chunks", "float32", [1])
        self.num_label_chunks = self._create_state(
            "num_label_chunks", "float32", [1])
        self.num_correct_chunks = self._create_state(
            "num_correct_chunks", "float32", [1])
        for state, batch in ((self.num_infer_chunks, num_infer),
                             (self.num_label_chunks, num_label),
                             (self.num_correct_chunks, num_correct)):
            inc = layers.cast(batch, "float32")
            self.helper.append_op(
                "elementwise_add", {"X": [state], "Y": [inc]},
                {"Out": [state]}, {})
        self.metrics = [precision, recall, f1]

    def eval(self, executor, eval_program=None):
        infer = float(self._state_value(self.num_infer_chunks)[0])
        label = float(self._state_value(self.num_label_chunks)[0])
        correct = float(self._state_value(self.num_correct_chunks)[0])
        precision = correct / infer if infer else 0.0
        recall = correct / label if label else 0.0
        f1 = (2 * precision * recall / (precision + recall)
              if precision + recall else 0.0)
        return (np.array([precision], np.float32),
                np.array([recall], np.float32),
                np.array([f1], np.float32))


class EditDistance(Evaluator):
    """Accumulate edit distances across batches (reference evaluator.py:189):
    eval() returns the average distance and the fraction of sequences with
    at least one error."""

    def __init__(self, input, label, ignored_tokens=None):
        super().__init__("edit_distance")
        distances, seq_num = layers.edit_distance(
            input=input, label=label, ignored_tokens=ignored_tokens)
        self.total_distance = self._create_state(
            "total_distance", "float32", [1])
        self.seq_num = self._create_state("seq_num", "float32", [1])
        self.instance_error = self._create_state(
            "instance_error", "float32", [1])
        batch_dist = layers.reduce_sum(distances)
        # distances are >= 0, so sign() is the per-sequence error indicator
        batch_err = layers.reduce_sum(layers.sign(distances))
        for state, inc in ((self.total_distance, batch_dist),
                           (self.seq_num, layers.cast(seq_num, "float32")),
                           (self.instance_error, batch_err)):
            self.helper.append_op(
                "elementwise_add", {"X": [state], "Y": [inc]},
                {"Out": [state]}, {})
        self.metrics = [distances, seq_num]

    def eval(self, executor, eval_program=None):
        total = float(self._state_value(self.total_distance)[0])
        n = float(self._state_value(self.seq_num)[0])
        err = float(self._state_value(self.instance_error)[0])
        avg = total / n if n else 0.0
        rate = err / n if n else 0.0
        return (np.array([avg], np.float32), np.array([rate], np.float32))
