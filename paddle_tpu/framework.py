"""Declarative Program IR: Variable / Operator / Block / Program.

This is the TPU-native re-design of the reference's graph-construction core
(/root/reference/python/paddle/fluid/framework.py: Variable:383, Operator:1034,
Block:1483, Program:2826) and its C++/proto IR
(/root/reference/paddle/fluid/framework/framework.proto).

Key contract kept from the reference:
  * A Program is a list of Blocks; a Block is an ordered list of Operators over
    named Variables; parameters are persistable Variables in block 0.
  * Layers append Operators; autodiff (`append_backward`) and distributed
    transpilers are *program transformations* that append/rewrite ops.
  * `program_guard` switches the default main/startup programs.

Key TPU-first departures:
  * No protobuf / no C++ OpDesc mirror: ops and vars are light Python objects
    serializable to JSON (`Program.to_dict`). The executor lowers a whole block
    to one XLA computation via JAX tracing, so there is no per-op C++ runtime
    descriptor to keep in sync.
  * No LoD: variable-length data is handled by padding/bucketing + segment ids
    (XLA requires static shapes); `Variable.shape` may use -1 only for the
    leading (batch) dim, which becomes a distinct compile-cache entry per
    concrete shape.
  * Each Variable may carry a `sharding` annotation (a tuple of mesh-axis names
    or None per dim) consumed by the GSPMD lowering in executor/compiler —
    this replaces the reference's multi-device SSA graph replication
    (/root/reference/paddle/fluid/framework/ir/multi_devices_graph_pass/).
"""
from __future__ import annotations

import contextlib
import copy
from typing import Any, Sequence

import numpy as np

from . import unique_name
from .core.types import DType, VarKind, np_dtype, np_feed_dtype

__all__ = [
    "Variable",
    "Parameter",
    "Operator",
    "Block",
    "Program",
    "default_main_program",
    "default_startup_program",
    "program_guard",
    "grad_var_name",
    "name_scope",
]

GRAD_SUFFIX = "@GRAD"


def grad_var_name(name: str) -> str:
    return name + GRAD_SUFFIX


_PKG_DIR = __file__.rsplit("/", 1)[0] + "/"


def _capture_callstack():
    """Trimmed user-code creation stack for one Operator (reference
    framework/op_call_stack.cc attaches this to runtime errors). Frames inside
    paddle_tpu itself are dropped so the stack points at the line of *user*
    code that built the op; capped at 8 frames. Disable via
    FLAGS_op_callstack=0 (costs ~10us/op at build time)."""
    from . import flags

    if not flags.get_flag("op_callstack"):
        return None
    import traceback

    frames = []
    for f, ln, fn, txt in traceback.extract_stack()[:-2]:
        if f.startswith(_PKG_DIR):
            continue
        frames.append((f, ln, fn, txt))
    return frames[-8:]


_name_scope_stack: list[str] = []


class Variable:
    """A named, typed, statically-shaped value in a Block.

    Reference: framework.py:383. A Variable is pure metadata — the runtime
    value lives in a Scope (executor.py) keyed by name.
    """

    def __init__(
        self,
        block: "Block",
        name: str | None = None,
        shape: Sequence[int] | None = None,
        dtype="float32",
        kind: VarKind = VarKind.DENSE_TENSOR,
        persistable: bool = False,
        stop_gradient: bool = False,
        is_data: bool = False,
        initializer=None,
        sharding: tuple | None = None,
    ):
        self.block = block
        self.name = name if name is not None else unique_name.generate("_generated_var")
        self.shape = tuple(int(s) for s in shape) if shape is not None else ()
        self.dtype = DType.parse(dtype)
        self.kind = kind
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        self.initializer = initializer
        self.sharding = sharding  # per-dim mesh axis names (GSPMD annotation)
        self.op: "Operator | None" = None  # op that (last) writes this var

    # -- introspection ------------------------------------------------------
    @property
    def np_dtype(self):
        return np_dtype(self.dtype)

    @property
    def np_feed_dtype(self):
        """Dtype FEED arrays cast to: int64/float64 declarations narrow to
        their 32-bit runtime forms when jax x64 is off (core.types
        .np_feed_dtype) — the explicit form of the truncation device_put
        would apply anyway, minus jax's per-astype warning."""
        return np_feed_dtype(self.dtype)

    @property
    def ndim(self):
        return len(self.shape)

    def to_dict(self):
        d = {
            "name": self.name,
            "shape": list(self.shape),
            "dtype": self.dtype.value,
            "kind": self.kind.value,
            "persistable": self.persistable,
            "stop_gradient": self.stop_gradient,
            "is_data": self.is_data,
            "sharding": list(self.sharding) if self.sharding else None,
            "is_parameter": isinstance(self, Parameter),
        }
        if getattr(self, "is_opt_state", False):
            d["is_opt_state"] = True  # ZeRO tag must survive serialization
        return d

    def __repr__(self):
        return (
            f"Var({self.name}: {self.dtype.value}{list(self.shape)}"
            + (", persistable" if self.persistable else "")
            + ")"
        )

    # -- operator sugar (builds ops in the var's block) ---------------------
    def _binary(self, other, op):
        from .layers import nn as _nn  # lazy, avoids cycle

        return _nn._elementwise_binary(op, self, other)

    def __add__(self, other):
        return self._binary(other, "elementwise_add")

    def __radd__(self, other):
        return self._binary(other, "elementwise_add")

    def __sub__(self, other):
        return self._binary(other, "elementwise_sub")

    def __mul__(self, other):
        return self._binary(other, "elementwise_mul")

    def __rmul__(self, other):
        return self._binary(other, "elementwise_mul")

    def __truediv__(self, other):
        return self._binary(other, "elementwise_div")

    def __rsub__(self, other):
        from .layers import nn as _nn

        return _nn._elementwise_binary("elementwise_sub", other, self)

    def __rtruediv__(self, other):
        from .layers import nn as _nn

        return _nn._elementwise_binary("elementwise_div", other, self)

    def __neg__(self):
        from .layers import nn as _nn

        return _nn.scale(self, scale=-1.0)


class Parameter(Variable):
    """A trainable persistable Variable (reference framework.py:3651)."""

    def __init__(self, block, shape, dtype, **kwargs):
        self.trainable = kwargs.pop("trainable", True)
        self.regularizer = kwargs.pop("regularizer", None)
        self.gradient_clip_attr = kwargs.pop("gradient_clip_attr", None)
        self.do_model_average = kwargs.pop("do_model_average", None)
        self.optimize_attr = kwargs.pop("optimize_attr", {"learning_rate": 1.0})
        super().__init__(
            block, shape=shape, dtype=dtype, persistable=True, **kwargs
        )

    def __repr__(self):
        return f"Param({self.name}: {self.dtype.value}{list(self.shape)})"


class Operator:
    """One op invocation: type + named input/output slots + attrs.

    Reference: framework.py:1034 / framework.proto OpDesc:43. Inputs/outputs
    map slot name -> list of variable names. Attrs are JSON-serializable
    values; a `sub_block` attr holds a Block index (control flow).
    """

    def __init__(
        self,
        block: "Block",
        type: str,
        inputs: dict[str, list[str]] | None = None,
        outputs: dict[str, list[str]] | None = None,
        attrs: dict[str, Any] | None = None,
    ):
        self.block = block
        self.type = type
        self.inputs = {k: list(v) for k, v in (inputs or {}).items()}
        self.outputs = {k: list(v) for k, v in (outputs or {}).items()}
        self.attrs = dict(attrs or {})
        # diagnostics (filled by Block.append_op — NOT here, so clone() and
        # from_dict() don't overwrite stacks or pick up foreign name scopes):
        # Python creation stack (reference op_call_stack.cc) + recorded
        # shape-inference failure, attached to later runtime errors
        self._callstack: list | None = None
        self._infer_error: str | None = None

    def callstack_str(self) -> str:
        """Render the creation stack (user frames) for error messages."""
        if not self._callstack:
            return "  <op creation stack not captured; FLAGS_op_callstack=0>"
        return "".join(
            f"  File \"{f}\", line {ln}, in {fn}\n    {txt}\n"
            for f, ln, fn, txt in self._callstack
        ).rstrip("\n")

    def input(self, slot: str) -> list[str]:
        return self.inputs.get(slot, [])

    def output(self, slot: str) -> list[str]:
        return self.outputs.get(slot, [])

    @property
    def input_names(self) -> list[str]:
        return [n for ns in self.inputs.values() for n in ns]

    @property
    def output_names(self) -> list[str]:
        return [n for ns in self.outputs.values() for n in ns]

    def attr(self, name: str, default=None):
        return self.attrs.get(name, default)

    def to_dict(self):
        def _clean(v):
            if isinstance(v, (np.integer,)):
                return int(v)
            if isinstance(v, (np.floating,)):
                return float(v)
            if isinstance(v, (list, tuple)):
                return [_clean(x) for x in v]
            return v

        return {
            "type": self.type,
            "inputs": self.inputs,
            "outputs": self.outputs,
            "attrs": {k: _clean(v) for k, v in self.attrs.items()},
        }

    def __repr__(self):
        ins = {k: v for k, v in self.inputs.items()}
        outs = {k: v for k, v in self.outputs.items()}
        return f"Op({self.type}, in={ins}, out={outs})"


class OpError(RuntimeError):
    """An op failed to lower/execute; carries the op's Python creation stack
    (the reference's EnforceNotMet + op_call_stack.cc attribution)."""

    def __init__(self, op: "Operator", cause: BaseException):
        self.op = op
        self.cause = cause
        scope = op.attrs.get("op_namescope")
        parts = [
            f"Operator '{op.type}'" + (f" (scope {scope})" if scope else "")
            + f" failed: {type(cause).__name__}: {cause}",
            f"  op: {op!r}",
        ]
        if op._infer_error is not None:
            parts.append(
                f"  note: shape inference had already failed at build time "
                f"with: {op._infer_error}")
        parts.append("Op creation stack (most recent call last):")
        parts.append(op.callstack_str())
        super().__init__("\n".join(parts))


class Block:
    """Ordered op list + var table (reference framework.py:1483)."""

    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: dict[str, Variable] = {}
        self.ops: list[Operator] = []

    @property
    def parent_block(self) -> "Block | None":
        if self.parent_idx < 0:
            return None
        return self.program.blocks[self.parent_idx]

    # -- var management -----------------------------------------------------
    def create_var(self, **kwargs) -> Variable:
        name = kwargs.get("name")
        if name is not None and name in self.vars:
            return self.vars[name]
        v = Variable(self, **kwargs)
        self.vars[v.name] = v
        return v

    def create_parameter(self, shape, dtype, **kwargs) -> Parameter:
        # parameters always live in the top block (reference block.py semantics)
        top = self.program.blocks[0]
        p = Parameter(top, shape, dtype, **kwargs)
        top.vars[p.name] = p
        return p

    def var(self, name: str) -> Variable:
        """Find a var here or in ancestor blocks (scope-chain lookup)."""
        b: Block | None = self
        while b is not None:
            if name in b.vars:
                return b.vars[name]
            b = b.parent_block
        raise KeyError(f"Variable '{name}' not found in block {self.idx}")

    def has_var(self, name: str) -> bool:
        try:
            self.var(name)
            return True
        except KeyError:
            return False

    def all_parameters(self) -> list[Parameter]:
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    # -- op management ------------------------------------------------------
    def append_op(self, type: str, inputs=None, outputs=None, attrs=None) -> Operator:
        op = Operator(self, type, inputs, outputs, attrs)
        op._callstack = _capture_callstack()
        if _name_scope_stack:
            op.attrs.setdefault("op_namescope", "/".join(_name_scope_stack))
        self.ops.append(op)
        for name in op.output_names:
            if name in self.vars:
                self.vars[name].op = op
        self.program._bump_version()
        # eager shape/dtype inference so layers can chain immediately
        from .ops.registry import infer_op  # lazy import

        infer_op(op, self)
        return op

    def _insert_op(self, index: int, type: str, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type, inputs, outputs, attrs)
        op._callstack = _capture_callstack()
        if _name_scope_stack:
            op.attrs.setdefault("op_namescope", "/".join(_name_scope_stack))
        self.ops.insert(index, op)
        self.program._bump_version()
        from .ops.registry import infer_op

        infer_op(op, self)
        return op

    def prepend_op(self, type: str, inputs=None, outputs=None, attrs=None):
        return self._insert_op(0, type, inputs, outputs, attrs)

    def to_dict(self):
        return {
            "idx": self.idx,
            "parent_idx": self.parent_idx,
            "vars": {n: v.to_dict() for n, v in self.vars.items()},
            "ops": [op.to_dict() for op in self.ops],
        }


class Program:
    """A whole trainable/serializable program (reference framework.py:2826)."""

    def __init__(self):
        self.blocks: list[Block] = [Block(self, 0)]
        self._current_block_idx = 0
        self.random_seed = 0
        self._version = 0  # bumped on mutation; part of the executor compile key
        self._lr_schedulers = []  # populated by learning_rate_scheduler layers

    # -- block management ---------------------------------------------------
    @property
    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[self._current_block_idx]

    def _create_block(self, parent_idx: int | None = None) -> Block:
        parent = self._current_block_idx if parent_idx is None else parent_idx
        b = Block(self, len(self.blocks), parent)
        self.blocks.append(b)
        self._current_block_idx = b.idx
        return b

    def _rollback(self):
        self._current_block_idx = self.current_block().parent_idx

    def _bump_version(self):
        self._version += 1

    # -- queries ------------------------------------------------------------
    def all_parameters(self) -> list[Parameter]:
        return self.global_block.all_parameters()

    def list_vars(self):
        for blk in self.blocks:
            yield from blk.vars.values()

    # -- clone / serialization ---------------------------------------------
    def clone(self, for_test: bool = False) -> "Program":
        """Deep-copy the program. With for_test=True, flip training-only attrs
        (is_test) the way the reference's clone(for_test=True) does."""
        p = Program.__new__(Program)
        p.blocks = []
        p._current_block_idx = self._current_block_idx
        p.random_seed = self.random_seed
        p._version = 0
        p._lr_schedulers = list(self._lr_schedulers)
        for blk in self.blocks:
            nb = Block(p, blk.idx, blk.parent_idx)
            p.blocks.append(nb)
        for blk, nb in zip(self.blocks, p.blocks):
            for name, v in blk.vars.items():
                nv = copy.copy(v)
                nv.block = nb
                nb.vars[name] = nv
            for op in blk.ops:
                nop = Operator(nb, op.type, op.inputs, op.outputs, copy.deepcopy(op.attrs))
                nop._callstack = op._callstack  # keep original creation site
                nop._infer_error = op._infer_error
                if for_test and "is_test" in nop.attrs:
                    nop.attrs["is_test"] = True
                if for_test and nop.type == "dropout":
                    nop.attrs["is_test"] = True
                nb.ops.append(nop)
        return p

    def to_dict(self):
        return {
            "version": 1,
            "random_seed": self.random_seed,
            "blocks": [b.to_dict() for b in self.blocks],
        }

    @staticmethod
    def from_dict(d: dict) -> "Program":
        p = Program.__new__(Program)
        p.blocks = []
        p._current_block_idx = 0
        p.random_seed = d.get("random_seed", 0)
        p._version = 0
        p._lr_schedulers = []
        for bd in d["blocks"]:
            b = Block(p, bd["idx"], bd["parent_idx"])
            p.blocks.append(b)
        for bd, b in zip(d["blocks"], p.blocks):
            for name, vd in bd["vars"].items():
                common = dict(
                    name=vd["name"],
                    kind=VarKind(vd["kind"]),
                    stop_gradient=vd["stop_gradient"],
                    is_data=vd.get("is_data", False),
                    sharding=tuple(vd["sharding"]) if vd.get("sharding") else None,
                )
                if vd.get("is_parameter"):
                    v = Parameter(b, vd["shape"], vd["dtype"], **common)
                else:
                    v = Variable(b, shape=vd["shape"], dtype=vd["dtype"],
                                 persistable=vd["persistable"], **common)
                if vd.get("is_opt_state"):
                    v.is_opt_state = True
                b.vars[name] = v
            for od in bd["ops"]:
                b.ops.append(Operator(b, od["type"], od["inputs"], od["outputs"], od["attrs"]))
        return p

    def __repr__(self):
        n_ops = sum(len(b.ops) for b in self.blocks)
        return f"Program(blocks={len(self.blocks)}, ops={n_ops}, version={self._version})"


# -- default program machinery (reference framework.py:3790+) ---------------
_main_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


def switch_main_program(program: Program) -> Program:
    global _main_program
    old = _main_program
    _main_program = program
    return old


def switch_startup_program(program: Program) -> Program:
    global _startup_program
    old = _startup_program
    _startup_program = program
    return old


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Program | None = None):
    old_main = switch_main_program(main_program)
    old_startup = None
    if startup_program is not None:
        old_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(old_main)
        if old_startup is not None:
            switch_startup_program(old_startup)


@contextlib.contextmanager
def name_scope(prefix: str):
    """Debug/profiling name scoping (reference framework.py name_scope): ops
    appended inside carry an `op_namescope` attr ("outer/inner"), visible in
    serialized programs and error messages. It must NOT reset the unique-name
    counters, or re-entering the same scope would collide parameter names."""
    _name_scope_stack.append(str(prefix))
    try:
        yield
    finally:
        _name_scope_stack.pop()
