"""DeviceLoader: background-thread prefetch that stages batches into HBM.

The reference overlaps host->device transfer with compute in
buffered_reader.cc (double buffering on a dedicated stream). Here the same
overlap comes from a python thread calling `jax.device_put` ahead of the
consumer: while the device runs step i, the thread is already transferring
the feeds of steps i+1..i+K (K = depth). The thread/queue contract is
`reader._prefetch_iter`'s — producer exceptions re-raise in the consumer and
an abandoned iteration unblocks and stops the producer (no leaked threads).

Placement is pluggable: the default casts host arrays to their declared var
dtypes and `jax.device_put`s them to the default device; `Executor.feed_placer`
builds a placement that re-uses the compiled entry's feed shardings on a mesh
(lifting this process's shard to a global array with
`jax.make_array_from_process_local_data` on multi-process meshes).
"""
from __future__ import annotations

import time

import jax
import numpy as np

from .. import flags, profiler
from ..reader import _prefetch_iter

__all__ = ["DeviceLoader", "default_placement"]


def default_placement(feed_vars=None, device=None):
    """Placement fn for programs run without a mesh: cast each host array to
    its feed var's declared dtype (the same cast Executor.run applies, so the
    compile-cache signature is identical either way) and commit it to the
    device. jax.Arrays and SelectedRows pass through untouched."""
    from ..core.selected_rows import is_selected_rows

    dtypes = {v.name: v.np_feed_dtype for v in (feed_vars or [])}

    def place(feed: dict) -> dict:
        out = {}
        for name, v in feed.items():
            if isinstance(v, jax.Array) or is_selected_rows(v):
                out[name] = v
                continue
            arr = np.asarray(v)
            if name in dtypes:
                arr = arr.astype(dtypes[name], copy=False)
            t0 = time.perf_counter()
            out[name] = jax.device_put(arr, device)
            profiler.record_stage("pipeline.device_put",
                                  time.perf_counter() - t0)
        return out

    return place


class DeviceLoader:
    """Iterate `source` (a zero-arg callable returning a generator of feed
    dicts) with up to `depth` batches staged in device memory ahead of the
    consumer. Usable directly in a `for feed in loader:` loop."""

    def __init__(self, source, depth: int | None = None, placement=None,
                 feed_vars=None):
        if depth is None:
            depth = int(flags.get_flag("device_prefetch_depth"))
        self._source = source
        self.depth = max(1, int(depth))
        self._place = placement or default_placement(feed_vars)

    def __iter__(self):
        source, place = self._source, self._place

        def staged():
            import threading

            from ..resilience.faults import InjectedFault, fault_point

            it = iter(source())
            while True:
                try:
                    fault_point("pipeline_stall")
                except InjectedFault:
                    # simulated wedge: the producer parks forever (hung I/O
                    # stand-in) so the consumer-side stall watchdog must
                    # fire; the parked daemon thread dies with the process
                    threading.Event().wait()
                t0 = time.perf_counter()
                try:
                    feed = next(it)
                except StopIteration:
                    return
                profiler.record_stage("pipeline.host_ingest",
                                      time.perf_counter() - t0)
                try:
                    staged_feed = place(feed)
                except (ValueError, TypeError):
                    # corrupt record: the batch died in the dtype cast /
                    # device_put — under FLAGS_feed_skip_corrupt count it
                    # and keep prefetching instead of killing the epoch
                    # through the consumer's re-raise
                    if not flags.get_flag("feed_skip_corrupt"):
                        raise
                    profiler.bump("feed.skip_corrupt")
                    continue
                yield staged_feed

        from ..resilience.watchdog import stall_window_s

        return _prefetch_iter(staged, self.depth,
                              stall_window=stall_window_s() or None,
                              stall_what="DeviceLoader batch wait")

    # reader-creator calling convention (paddle readers are zero-arg callables)
    __call__ = __iter__
