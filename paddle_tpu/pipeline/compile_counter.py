"""Compile-count hook: observe XLA compiles of the executor's step function.

The executor caches one compiled executable per (program, feed-signature);
feed bucketing exists precisely so a ragged tail batch hits that cache
instead of triggering a fresh compile. This hook turns "how many compiles
actually happened" into something a regression test can assert: it enables
jax's log_compiles reporting and counts the whole-block compile events (the
executor's lowered closure is named `fn`, so its compile log lines are
distinguishable from the small utility jits jax compiles around a run).
"""
from __future__ import annotations

import contextlib
import logging

import jax

__all__ = ["jit_compile_counter"]

# loggers that announce "Compiling <name> ..." under jax_log_compiles; the
# module moved across jax versions, so listen on both spellings
_COMPILE_LOGGERS = (
    "jax._src.interpreters.pxla",
    "jax.interpreters.pxla",
)


class _CompileCount:
    def __init__(self):
        self.events: list[str] = []

    @property
    def count(self) -> int:
        return len(self.events)


@contextlib.contextmanager
def jit_compile_counter(fn_name: str = "fn"):
    """Count XLA compiles of jitted functions named `fn_name` inside the
    `with` block. Default "fn" matches the executor's whole-block closure, so
    `counter.count` is the number of (program, signature) compile-cache
    misses the block produced."""
    result = _CompileCount()
    prefix = f"Compiling {fn_name} "

    class _Handler(logging.Handler):
        def emit(self, record):
            msg = record.getMessage()
            if msg.startswith(prefix):
                result.events.append(msg)
                from .. import observability as obs

                obs.counter_inc("train.jit_compiles")

    handler = _Handler(level=logging.DEBUG)
    touched = []
    for name in _COMPILE_LOGGERS:
        logger = logging.getLogger(name)
        logger.addHandler(handler)
        # the compile announcement is logged at WARNING; make sure an
        # application logging config set above WARNING doesn't eat it
        old_level = logger.level
        if logger.getEffectiveLevel() > logging.WARNING:
            logger.setLevel(logging.WARNING)
        touched.append((logger, old_level))
    old_flag = jax.config.jax_log_compiles
    jax.config.update("jax_log_compiles", True)
    try:
        yield result
    finally:
        jax.config.update("jax_log_compiles", old_flag)
        for logger, old_level in touched:
            logger.removeHandler(handler)
            logger.setLevel(old_level)
