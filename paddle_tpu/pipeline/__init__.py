"""Async input pipeline: device prefetch + compile accounting.

TPU-native analogue of the reference's buffered double-buffer reader
(/root/reference/paddle/fluid/operators/reader/buffered_reader.cc): a
background thread stages the next K batches into device memory so the
host->HBM transfer overlaps the running step, and the executor's async
dispatch window (Executor.run_async + FLAGS_max_inflight_steps) keeps the
XLA stream fed without unbounded host runahead.
"""
from .compile_counter import jit_compile_counter  # noqa: F401
from .device_loader import DeviceLoader, default_placement  # noqa: F401

__all__ = ["DeviceLoader", "default_placement", "jit_compile_counter"]
