"""Optimizers: program transformation appending per-param update ops.

TPU-native re-design of /root/reference/python/paddle/fluid/optimizer.py
(Optimizer.minimize:586 = backward:442 + apply_gradients:502;
_create_optimization_pass:339; SGD/Momentum/Adagrad/Adam/Adamax/DecayedAdagrad/
Adadelta/RMSProp/Ftrl/Lamb:627-2263; ExponentialMovingAverage:2453;
ModelAverage:2263). Contract kept: `minimize(loss)` appends grad ops (via
append_backward) then one optimizer op per parameter, with accumulator
variables created in both main and startup programs. The reference's
fuse_optimizer_ops pass is unnecessary — all update ops live in one XLA block
and fuse at compile time.
"""
from __future__ import annotations

import numpy as np

from . import unique_name
from .backward import append_backward
from .clip import append_gradient_clip_ops, error_clip_callback
from .framework import Program, Variable, default_main_program, default_startup_program
from .initializer import Constant
from .layer_helper import LayerHelper
from .regularizer import append_regularization_ops

__all__ = [
    "PipelineOptimizer",
    "SGD",
    "SGDOptimizer",
    "Momentum",
    "MomentumOptimizer",
    "DGCMomentumOptimizer",
    "Adagrad",
    "AdagradOptimizer",
    "Adam",
    "AdamOptimizer",
    "Adamax",
    "AdamaxOptimizer",
    "DecayedAdagrad",
    "DecayedAdagradOptimizer",
    "Adadelta",
    "AdadeltaOptimizer",
    "RMSProp",
    "RMSPropOptimizer",
    "Ftrl",
    "FtrlOptimizer",
    "Lamb",
    "LambOptimizer",
    "LarsMomentum",
    "LarsMomentumOptimizer",
    "ModelAverage",
    "LookaheadOptimizer",
    "RecomputeOptimizer",
    "ExponentialMovingAverage",
]


class Optimizer:
    """Base optimizer (reference optimizer.py:60)."""

    def __init__(self, learning_rate, regularization=None, name=None):
        self.regularization = regularization
        self._name = name
        self._learning_rate = learning_rate
        self._learning_rate_map: dict[Program, Variable] = {}
        # accumulator name -> {param name -> Variable}
        self._accumulators: dict[str, dict[str, Variable]] = {}
        self.helper: LayerHelper | None = None

    # -- learning rate ------------------------------------------------------
    def _create_global_learning_rate(self):
        program = default_main_program()
        lr = self._learning_rate_map.get(program)
        if lr is not None:
            return
        if isinstance(self._learning_rate, Variable):
            # a scheduler already produced an LR variable in this program
            self._learning_rate_map[program] = self._learning_rate
            return
        helper = LayerHelper("learning_rate")
        self._learning_rate_map[program] = helper.create_or_get_global_variable(
            unique_name.generate("learning_rate"),
            [1],
            "float32",
            initializer=Constant(float(self._learning_rate)),
        )

    def _global_learning_rate(self, program=None) -> Variable:
        program = program or default_main_program()
        return self._learning_rate_map[program]

    def _create_param_lr(self, param):
        base_lr = self._global_learning_rate()
        mult = param.optimize_attr.get("learning_rate", 1.0) if param.optimize_attr else 1.0
        if mult == 1.0:
            return base_lr
        from .layers import nn as L

        return L.scale(base_lr, scale=float(mult))

    # -- accumulators -------------------------------------------------------
    def _add_accumulator(self, name, param, dtype="float32", fill_value=0.0, shape=None):
        if name in self._accumulators and param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        helper = LayerHelper(name)
        var = helper.create_or_get_global_variable(
            unique_name.generate(f"{param.name}_{name}"),
            shape if shape is not None else list(param.shape),
            dtype,
            initializer=Constant(fill_value),
        )
        # tag for ZeRO-style sharding (BuildStrategy.sharded_optimizer_states):
        # the compiler may shard these over the dp axis
        var.is_opt_state = True
        self._accumulators.setdefault(name, {})[param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    # -- the transformation pipeline ----------------------------------------
    def backward(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        # the numeric guardrail (resilience/guardrails.py) needs the loss
        # var to build its in-graph health vector; record it on the program
        # (the AMP decorator overwrites this with the UNSCALED loss)
        default_main_program()._guard_loss_name = loss.name
        # graph rewrites that must precede append_backward (fused ops derive
        # their gradients via vjp over the fused lowering) and follow any AMP
        # rewrite (AMP's decorator calls into this backward after its own)
        from .passes import apply_minimize_passes
        from .tuning import on_minimize

        # force the tuning-DB load at minimize() time: a corrupt/missing DB
        # warns HERE (once, attached to the graph build) and every decision
        # below — fusion gating now, conv/attention dispatch at trace —
        # resolves against one consistent snapshot
        on_minimize(default_main_program())
        apply_minimize_passes(default_main_program())
        return append_backward(loss, parameter_list, no_grad_set)

    def apply_gradients(self, params_grads):
        """clip -> regularize -> [health sentinel] -> per-param update ops
        (optimizer.py:502). Under FLAGS_guard_numerics every gradient is
        routed through the in-graph health sentinel AFTER clipping (a NaN
        that a global-norm clip smeared over all grads is still caught), so
        a bad step's update ops see zeros and skip branchlessly."""
        from .resilience import guardrails

        params_grads = sorted(params_grads, key=lambda pg: pg[0].name)
        params_grads = append_gradient_clip_ops(params_grads)
        params_grads = append_regularization_ops(params_grads, self.regularization)
        if guardrails.enabled():
            params_grads = guardrails.append_health_sentinel(params_grads)
        ops = self._create_optimization_pass(params_grads)
        # the StepGuard's rewind rung backs the LR off through the scope;
        # record where the LR lives (scheduler LR vars qualify too)
        try:
            default_main_program()._guard_lr_name = (
                self._global_learning_rate().name)
        except (KeyError, AttributeError):
            pass
        return ops

    def _create_optimization_pass(self, params_grads):
        self.helper = LayerHelper(self.__class__.__name__)
        self._create_global_learning_rate()
        self._create_accumulators(
            default_main_program().global_block, [p for p, _ in params_grads]
        )
        ops = []
        for param, grad in params_grads:
            if grad is None or not getattr(param, "trainable", True):
                continue
            ops.append(self._append_optimize_op(default_main_program().global_block, (param, grad)))
        self._finish_update()
        return ops

    def _create_accumulators(self, block, parameters):
        pass

    def _finish_update(self):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        from . import dygraph as _dy

        if _dy.enabled():
            return self._dygraph_minimize(loss, parameter_list)
        params_grads = self.backward(loss, startup_program, parameter_list, no_grad_set)
        optimize_ops = self.apply_gradients(params_grads)
        return optimize_ops, params_grads

    # -- dygraph (imperative) path ------------------------------------------
    def _dygraph_minimize(self, loss, parameter_list=None):
        """Apply updates to eager parameters after loss.backward() (reference
        dygraph flow: backward() fills VarBase grads, minimize applies).

        parameter_list: VarBase list; defaults to every persistable VarBase
        that participated in the current tape with a gradient.
        """
        import jax.numpy as jnp

        from . import dygraph as _dy

        if parameter_list is None:
            parameter_list = _dy._state.get("last_params") or []
        if not hasattr(self, "_dy_state"):
            self._dy_state = {}
        lr = self._dygraph_lr()
        updated = []
        for p in parameter_list:
            if p._grad is None:
                continue
            g = jnp.asarray(p._grad, p._value.dtype)
            g = self._dygraph_regularize(p._value, g)
            state = self._dy_state.setdefault(p.name, {})
            p._value = self._dygraph_step(p._value, g, lr, state)
            updated.append(p)
        return updated, []

    def _dygraph_regularize(self, value, grad):
        """Weight decay on the eager path (mirror of
        append_regularization_ops in apply_gradients)."""
        from .regularizer import L1DecayRegularizer, L2DecayRegularizer

        reg = self.regularization
        if reg is None:
            return grad
        import jax.numpy as jnp

        if isinstance(reg, L2DecayRegularizer):
            return grad + reg._coeff * value
        if isinstance(reg, L1DecayRegularizer):
            return grad + reg._coeff * jnp.sign(value)
        raise NotImplementedError(
            f"dygraph regularization for {type(reg).__name__}")

    def _dygraph_lr(self):
        lr = self._learning_rate
        if callable(lr):
            lr = lr()
        if isinstance(lr, Variable):
            raise TypeError(
                "dygraph mode needs a float learning rate (schedulers build "
                "static-graph variables)")
        return float(lr)

    def _dygraph_step(self, value, grad, lr, state):
        raise NotImplementedError(
            f"{type(self).__name__} has no dygraph update rule "
            "(SGD/Momentum/Adam support dygraph)")


class SGDOptimizer(Optimizer):
    def __init__(self, learning_rate, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "sgd"

    def _dygraph_step(self, value, grad, lr, state):
        return value - lr * grad

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        return block.append_op(
            "sgd",
            inputs={
                "Param": [param.name],
                "Grad": [grad.name],
                "LearningRate": [self._create_param_lr(param).name],
            },
            outputs={"ParamOut": [param.name]},
        )


class MomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum, use_nesterov=False, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "momentum"
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _dygraph_step(self, value, grad, lr, state):
        import jax.numpy as jnp

        v = state.get("velocity")
        if v is None:
            v = jnp.zeros_like(value)
        v = self._momentum * v + grad
        state["velocity"] = v
        if self._use_nesterov:
            return value - lr * (grad + self._momentum * v)
        return value - lr * v

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        velocity = self._get_accumulator("velocity", param)
        return block.append_op(
            "momentum",
            inputs={
                "Param": [param.name],
                "Grad": [grad.name],
                "Velocity": [velocity.name],
                "LearningRate": [self._create_param_lr(param).name],
            },
            outputs={"ParamOut": [param.name], "VelocityOut": [velocity.name]},
            attrs={"mu": self._momentum, "use_nesterov": self._use_nesterov},
        )


class DGCMomentumOptimizer(MomentumOptimizer):
    """Deep Gradient Compression momentum (reference optimizer.py:805,
    arXiv:1712.01887): each step the `dgc` op sparsifies the gradient to the
    top (1-sparsity) fraction by magnitude with momentum correction and
    error-feedback accumulators, then the regular momentum update consumes
    the sparsified gradient. Under the collective transpiler the allreduce
    rides on the mostly-zero GradOut — the fixed-shape TPU equivalent of the
    reference's sparse communication.

    The warmup rampup (reference __append_dgc_ops' get_sparsity schedule)
    is computed IN-GRAPH from a per-step counter — the same plumbing the LR
    schedules use (layers/learning_rate_scheduler.py): before
    rampup_begin_step sparsity is 0 (every gradient released = plain
    momentum via the error-feedback identity), then it steps through the
    `sparsity` list across rampup_step steps and holds the final value.
    Every dgc op also emits its effective per-step sparsity as a fetchable
    `...dgc_sparsity` var (the oracle tests/test_losses_and_quant.py
    follows).
    """

    def __init__(self, learning_rate, momentum, rampup_begin_step=0,
                 rampup_step=1, sparsity=(0.999,), use_nesterov=False,
                 local_grad_clip_norm=None, num_trainers=None,
                 regularization=None, name=None):
        super().__init__(learning_rate, momentum, use_nesterov,
                         regularization, name)
        self.type = "dgc_momentum"
        sp = (list(sparsity) if isinstance(sparsity, (list, tuple))
              else [sparsity])
        self._sparsity_ramp = [float(s) for s in sp]
        self._sparsity = self._sparsity_ramp[-1]
        self._rampup_begin_step = int(rampup_begin_step)
        self._rampup_step = max(1, int(rampup_step))

    def _dgc_step_counter(self):
        """Per-program float32 step counter incremented once per executor
        run, shared by every dgc op in the program (the LR schedulers'
        @LR_DECAY_COUNTER@ pattern with a private name, so a noam_decay
        schedule with a different counter origin can coexist)."""
        helper = LayerHelper("dgc_counter")
        program = default_main_program()
        name = "@DGC_COUNTER@"
        existed = name in program.global_block.vars
        counter = helper.create_or_get_global_variable(
            name, [1], "float32", initializer=Constant(-1.0))
        if not existed:
            # the increment precedes every dgc op in program order, so the
            # first executed step reads 0
            helper.append_op("increment", {"X": [counter]},
                             {"Out": [counter]}, {"step": 1.0})
        return counter

    def _create_accumulators(self, block, parameters):
        # no inherited velocity: momentum lives in dgc_u (the dgc op's
        # momentum correction); the post-compression update is plain sgd
        for p in parameters:
            self._add_accumulator("dgc_u", p)
            self._add_accumulator("dgc_v", p)

    def _dygraph_step(self, value, grad, lr, state):
        raise NotImplementedError(
            "DGCMomentumOptimizer has no dygraph update rule (falling back "
            "to plain momentum would silently drop the compression)")

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        u = self._get_accumulator("dgc_u", param)
        v = self._get_accumulator("dgc_v", param)
        step = self._dgc_step_counter()
        helper = LayerHelper("dgc")
        sparse_grad = helper.create_variable_for_type_inference(grad.dtype)
        cur_sparsity = helper.create_or_get_global_variable(
            unique_name.generate(f"{param.name}_dgc_sparsity"), [1],
            "float32", initializer=Constant(0.0))
        block.append_op(
            "dgc",
            inputs={"Grad": [grad.name], "U": [u.name], "V": [v.name],
                    "CurrentStep": [step.name]},
            outputs={"GradOut": [sparse_grad.name], "UOut": [u.name],
                     "VOut": [v.name], "Sparsity": [cur_sparsity.name]},
            attrs={"momentum": self._momentum,
                   "sparsity": self._sparsity,
                   "sparsity_ramp": self._sparsity_ramp,
                   "rampup_begin_step": self._rampup_begin_step,
                   "rampup_step": self._rampup_step,
                   "use_nesterov": self._use_nesterov},
        )
        # momentum is already folded into U by the dgc op (momentum
        # correction) — the released gradient applies as plain SGD, the
        # reference dgc_momentum op's post-rampup branch
        return block.append_op(
            "sgd",
            inputs={"Param": [param.name], "Grad": [sparse_grad.name],
                    "LearningRate": [self._create_param_lr(param).name]},
            outputs={"ParamOut": [param.name]},
            attrs={},
        )


class LarsMomentumOptimizer(Optimizer):
    def __init__(
        self,
        learning_rate,
        momentum,
        lars_coeff=0.001,
        lars_weight_decay=0.0005,
        regularization=None,
        name=None,
    ):
        super().__init__(learning_rate, regularization, name)
        self.type = "lars_momentum"
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        velocity = self._get_accumulator("velocity", param)
        return block.append_op(
            "lars_momentum",
            inputs={
                "Param": [param.name],
                "Grad": [grad.name],
                "Velocity": [velocity.name],
                "LearningRate": [self._create_param_lr(param).name],
            },
            outputs={"ParamOut": [param.name], "VelocityOut": [velocity.name]},
            attrs={
                "mu": self._momentum,
                "lars_coeff": self._lars_coeff,
                "lars_weight_decay": self._lars_weight_decay,
            },
        )


class AdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "adagrad"
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        moment = self._get_accumulator("moment", param)
        return block.append_op(
            "adagrad",
            inputs={
                "Param": [param.name],
                "Grad": [grad.name],
                "Moment": [moment.name],
                "LearningRate": [self._create_param_lr(param).name],
            },
            outputs={"ParamOut": [param.name], "MomentOut": [moment.name]},
            attrs={"epsilon": self._epsilon},
        )


class AdamOptimizer(Optimizer):
    def __init__(
        self,
        learning_rate=0.001,
        beta1=0.9,
        beta2=0.999,
        epsilon=1e-8,
        regularization=None,
        name=None,
        lazy_mode=False,
    ):
        super().__init__(learning_rate, regularization, name)
        self.type = "adam"
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _dygraph_step(self, value, grad, lr, state):
        import jax.numpy as jnp

        m = state.get("m", jnp.zeros_like(value))
        v = state.get("v", jnp.zeros_like(value))
        t = state.get("t", 0) + 1
        m = self._beta1 * m + (1 - self._beta1) * grad
        v = self._beta2 * v + (1 - self._beta2) * grad * grad
        state.update(m=m, v=v, t=t)
        lr_t = lr * (1 - self._beta2 ** t) ** 0.5 / (1 - self._beta1 ** t)
        return value - lr_t * m / (v ** 0.5 + self._epsilon)

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1, shape=[1])
            self._add_accumulator("beta2_pow_acc", p, fill_value=self._beta2, shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        m1 = self._get_accumulator("moment1", param)
        m2 = self._get_accumulator("moment2", param)
        b1p = self._get_accumulator("beta1_pow_acc", param)
        b2p = self._get_accumulator("beta2_pow_acc", param)
        return block.append_op(
            "adam",
            inputs={
                "Param": [param.name],
                "Grad": [grad.name],
                "Moment1": [m1.name],
                "Moment2": [m2.name],
                "Beta1Pow": [b1p.name],
                "Beta2Pow": [b2p.name],
                "LearningRate": [self._create_param_lr(param).name],
            },
            outputs={
                "ParamOut": [param.name],
                "Moment1Out": [m1.name],
                "Moment2Out": [m2.name],
                "Beta1PowOut": [b1p.name],
                "Beta2PowOut": [b2p.name],
            },
            attrs={"beta1": self._beta1, "beta2": self._beta2, "epsilon": self._epsilon},
        )


class AdamaxOptimizer(Optimizer):
    def __init__(
        self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, regularization=None, name=None
    ):
        super().__init__(learning_rate, regularization, name)
        self.type = "adamax"
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1, shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        return block.append_op(
            "adamax",
            inputs={
                "Param": [param.name],
                "Grad": [grad.name],
                "Moment": [self._get_accumulator("moment", param).name],
                "InfNorm": [self._get_accumulator("inf_norm", param).name],
                "Beta1Pow": [self._get_accumulator("beta1_pow_acc", param).name],
                "LearningRate": [self._create_param_lr(param).name],
            },
            outputs={
                "ParamOut": [param.name],
                "MomentOut": [self._get_accumulator("moment", param).name],
                "InfNormOut": [self._get_accumulator("inf_norm", param).name],
            },
            attrs={"beta1": self._beta1, "beta2": self._beta2, "epsilon": self._epsilon},
        )

    def _finish_update(self):
        # beta1_pow *= beta1 after each step (reference optimizer.py adamax)
        block = default_main_program().global_block
        for param_name, b1p in self._accumulators.get("beta1_pow_acc", {}).items():
            block.append_op(
                "scale",
                inputs={"X": [b1p.name]},
                outputs={"Out": [b1p.name]},
                attrs={"scale": self._beta1},
            )


class DecayedAdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "decayed_adagrad"
        self._decay, self._epsilon = decay, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        moment = self._get_accumulator("moment", param)
        return block.append_op(
            "decayed_adagrad",
            inputs={
                "Param": [param.name],
                "Grad": [grad.name],
                "Moment": [moment.name],
                "LearningRate": [self._create_param_lr(param).name],
            },
            outputs={"ParamOut": [param.name], "MomentOut": [moment.name]},
            attrs={"decay": self._decay, "epsilon": self._epsilon},
        )


class AdadeltaOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "adadelta"
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("avg_squared_grad", p)
            self._add_accumulator("avg_squared_update", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        ag = self._get_accumulator("avg_squared_grad", param)
        au = self._get_accumulator("avg_squared_update", param)
        return block.append_op(
            "adadelta",
            inputs={
                "Param": [param.name],
                "Grad": [grad.name],
                "AvgSquaredGrad": [ag.name],
                "AvgSquaredUpdate": [au.name],
                "LearningRate": [self._create_param_lr(param).name],
            },
            outputs={
                "ParamOut": [param.name],
                "AvgSquaredGradOut": [ag.name],
                "AvgSquaredUpdateOut": [au.name],
            },
            attrs={"epsilon": self._epsilon, "rho": self._rho},
        )


class RMSPropOptimizer(Optimizer):
    def __init__(
        self,
        learning_rate,
        rho=0.95,
        epsilon=1e-6,
        momentum=0.0,
        centered=False,
        regularization=None,
        name=None,
    ):
        super().__init__(learning_rate, regularization, name)
        self.type = "rmsprop"
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("momentum", p)
            self._add_accumulator("mean_square", p)
            self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        mom = self._get_accumulator("momentum", param)
        ms = self._get_accumulator("mean_square", param)
        mg = self._get_accumulator("mean_grad", param)
        return block.append_op(
            "rmsprop",
            inputs={
                "Param": [param.name],
                "Grad": [grad.name],
                "Moment": [mom.name],
                "MeanSquare": [ms.name],
                "MeanGrad": [mg.name],
                "LearningRate": [self._create_param_lr(param).name],
            },
            outputs={
                "ParamOut": [param.name],
                "MomentOut": [mom.name],
                "MeanSquareOut": [ms.name],
                "MeanGradOut": [mg.name],
            },
            attrs={
                "decay": self._rho,
                "epsilon": self._epsilon,
                "momentum": self._momentum,
                "centered": self._centered,
            },
        )


class FtrlOptimizer(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, regularization=None, name=None):
        super().__init__(learning_rate, regularization, name)
        self.type = "ftrl"
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        sq = self._get_accumulator("squared", param)
        lin = self._get_accumulator("linear", param)
        return block.append_op(
            "ftrl",
            inputs={
                "Param": [param.name],
                "Grad": [grad.name],
                "SquaredAccumulator": [sq.name],
                "LinearAccumulator": [lin.name],
                "LearningRate": [self._create_param_lr(param).name],
            },
            outputs={
                "ParamOut": [param.name],
                "SquaredAccumOut": [sq.name],
                "LinearAccumOut": [lin.name],
            },
            attrs={"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power},
        )


class LambOptimizer(Optimizer):
    def __init__(
        self,
        learning_rate=0.001,
        lamb_weight_decay=0.01,
        beta1=0.9,
        beta2=0.999,
        epsilon=1e-6,
        regularization=None,
        name=None,
    ):
        super().__init__(learning_rate, regularization, name)
        self.type = "lamb"
        self._weight_decay = lamb_weight_decay
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1, shape=[1])
            self._add_accumulator("beta2_pow_acc", p, fill_value=self._beta2, shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        param, grad = param_and_grad
        m1 = self._get_accumulator("moment1", param)
        m2 = self._get_accumulator("moment2", param)
        b1p = self._get_accumulator("beta1_pow_acc", param)
        b2p = self._get_accumulator("beta2_pow_acc", param)
        return block.append_op(
            "lamb",
            inputs={
                "Param": [param.name],
                "Grad": [grad.name],
                "Moment1": [m1.name],
                "Moment2": [m2.name],
                "Beta1Pow": [b1p.name],
                "Beta2Pow": [b2p.name],
                "LearningRate": [self._create_param_lr(param).name],
            },
            outputs={
                "ParamOut": [param.name],
                "Moment1Out": [m1.name],
                "Moment2Out": [m2.name],
                "Beta1PowOut": [b1p.name],
                "Beta2PowOut": [b2p.name],
            },
            attrs={
                "beta1": self._beta1,
                "beta2": self._beta2,
                "epsilon": self._epsilon,
                "weight_decay": self._weight_decay,
            },
        )


class RecomputeOptimizer:
    """Activation recompute / gradient checkpointing.

    Reference lineage: the fleet DistributedStrategy forward_recompute flag
    and the later fluid RecomputeOptimizer; the TPU-native mechanism here is
    segment-level `jax.checkpoint`. `_set_checkpoints([vars])` names the
    segment boundaries (typically each transformer layer's output); at
    minimize() the forward block is split at those vars, each segment moves
    into a sub-block behind one `recompute` op, and the derived
    `recompute_grad` replays the segment under jax.checkpoint — XLA then
    drops the segment's interior activations after the forward and
    rematerializes them in the backward, trading ~1 extra forward of FLOPs
    for O(#checkpoints) instead of O(#ops) live activation memory.

        opt = RecomputeOptimizer(pt.optimizer.Adam(1e-4))
        opt._set_checkpoints([layer1_out, layer2_out, ...])
        opt.minimize(loss)

    Constraint: RNG-consuming ops (dropout) inside a segment would draw
    different numbers in the replay, so the rewrite rejects them.
    """

    def __init__(self, optimizer):
        self._inner = optimizer
        self._checkpoints = []

    def _set_checkpoints(self, checkpoints):
        self._checkpoints = list(checkpoints)

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        self._rewrite(loss)
        return self._inner.backward(loss, startup_program, parameter_list,
                                    no_grad_set)

    def apply_gradients(self, params_grads):
        return self._inner.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        self._rewrite(loss)
        return self._inner.minimize(loss, startup_program, parameter_list,
                                    no_grad_set)

    # -- the program rewrite -------------------------------------------------
    def _rewrite(self, loss):
        from .ops.registry import get_op_def, has_op

        if not self._checkpoints:
            return
        program = loss.block.program
        block = program.global_block
        ck_names = [getattr(c, "name", c) for c in self._checkpoints]
        ck_set = set(ck_names)
        if getattr(program, "_recompute_done", False):
            return

        # split the forward op list into segments ending at checkpoint defs
        segments, cur = [], []
        matched_any = False
        for op in block.ops:
            cur.append(op)
            if any(n in ck_set for n in op.output_names):
                matched_any = True
                segments.append(cur)
                cur = []
        if cur:
            segments.append(cur)  # tail (loss head) stays inline if short
        if not matched_any:
            raise ValueError(
                "RecomputeOptimizer: no checkpoint variable matched any op "
                "output in this program — the checkpoints likely came from a "
                "different program build (transformer.last_layer_outputs "
                "holds the MOST RECENT build's vars)")
        # suffix read sets in ONE reverse pass (O(total ops), not
        # O(segments x ops)): reads_after[si] = names read in segments > si
        reads_after = [set() for _ in segments]
        acc: set = set()
        for si in range(len(segments) - 1, -1, -1):
            reads_after[si] = set(acc)
            for op in segments[si]:
                for n in op.input_names:
                    if n:
                        acc.add(n)

        new_ops = []
        for si, seg in enumerate(segments[:-1]):
            wrap = [op for op in seg if op.type not in ("feed", "fetch")]
            passthrough = [op for op in seg if op.type in ("feed", "fetch")]
            new_ops.extend(passthrough)
            if len(wrap) < 2:
                new_ops.extend(wrap)
                continue
            for op in wrap:
                if has_op(op.type) and get_op_def(op.type).needs_rng:
                    raise ValueError(
                        f"RecomputeOptimizer: op '{op.type}' consumes RNG "
                        "inside a recompute segment — its replay would draw "
                        "different numbers. Move it out of the segment "
                        "(e.g. dropout=0 under recompute).")
            # names defined inside vs read from outside (insertion-ordered:
            # slot ordering must not depend on PYTHONHASHSEED — program dumps
            # and compile-cache keys have to be reproducible)
            defined: dict = {}
            ext_reads, outs = [], []
            for op in wrap:
                for n in op.input_names:
                    if n and n not in defined and n not in ext_reads:
                        ext_reads.append(n)
                for n in op.output_names:
                    if n:
                        defined[n] = True
            later_reads = reads_after[si]

            def _persistable(n):
                try:
                    return block.var(n).persistable
                except KeyError:
                    return False

            # persistable outputs (batch_norm running stats, counters) must
            # surface even when nothing later reads them — the executor's
            # scope write-back only scans top-level op outputs
            outs = [n for n in defined
                    if n in later_reads or n in ck_set or _persistable(n)]
            # move the segment into a sub-block
            sub = program._create_block()
            for op in wrap:
                sub.ops.append(op)
                op.block = sub
            program._rollback()
            from .framework import Operator

            rec = Operator(
                block, "recompute",
                {"Deps": list(ext_reads)},
                {"Out": list(outs)},
                {"sub_block": sub.idx,
                 "dep_names": list(ext_reads),
                 "out_names": list(outs)},
            )
            new_ops.append(rec)
        new_ops.extend(segments[-1])
        if not any(op.type == "recompute" for op in new_ops):
            raise ValueError(
                "RecomputeOptimizer: checkpoints matched but produced no "
                "recompute segment — each non-tail segment needs >= 2 ops "
                "(is the checkpoint the program's last op, e.g. the loss?)")
        block.ops[:] = new_ops
        program._recompute_done = True
        program._bump_version()


class ModelAverage(Optimizer):
    """Sliding-window parameter averaging (reference optimizer.py:2263).

    Construct AFTER the training optimizer's minimize(): accumulation ops
    append to the main program; `with model_average.apply(exe):` swaps
    parameters for their window averages (restored on exit, or call
    restore()). The reference's three-sum rotation collapses to one
    sum+count with max-window truncation — identical averages over the
    active window.
    """

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, regularization=None, name=None):
        super().__init__(0.0, regularization, name)
        self.average_window = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        self._params = list(default_main_program().all_parameters())
        self.helper = LayerHelper(self.__class__.__name__)
        total = self.helper.create_or_get_global_variable(
            unique_name.generate("ma_total_updates"), [1], "float32",
            initializer=Constant(0.0))
        default_main_program().global_block.append_op(
            "increment", {"X": [total.name]}, {"Out": [total.name]},
            {"step": 1.0})
        for p in self._params:
            s = self._add_accumulator("ma_sum", p, dtype=p.dtype)
            c = self._add_accumulator("ma_cnt", p, shape=[1])
            default_main_program().global_block.append_op(
                "model_average_accum",
                inputs={"Param": [p.name], "Sum": [s.name], "Cnt": [c.name],
                        "TotalUpdates": [total.name]},
                outputs={"SumOut": [s.name], "CntOut": [c.name]},
                attrs={"max_average_window": float(max_average_window),
                       "min_average_window": float(min_average_window),
                       "average_window_rate": float(average_window_rate)},
            )

    def _swap(self, executor, to_average: bool):
        import jax.numpy as jnp

        from .executor import global_scope

        scope = global_scope()
        for p in self._params:
            if to_average:
                s = np.asarray(scope.find_var(
                    self._accumulators["ma_sum"][p.name].name))
                c = float(np.asarray(scope.find_var(
                    self._accumulators["ma_cnt"][p.name].name)).reshape(-1)[0])
                self._backup[p.name] = scope.find_var(p.name)
                if c > 0:
                    scope.set_var(p.name, jnp.asarray(s / c, s.dtype))
            else:
                scope.set_var(p.name, self._backup[p.name])

    def apply(self, executor=None, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def guard():
            self._backup = {}
            self._swap(executor, True)
            try:
                yield
            finally:
                if need_restore:
                    self._swap(executor, False)

        return guard()

    def restore(self, executor=None):
        self._swap(executor, False)


class LookaheadOptimizer:
    """Lookahead wrapper (reference optimizer.py:2976, arXiv:1907.08610):
    the inner optimizer updates fast weights every step; every k steps the
    slow weights catch up and overwrite the fast ones."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        if inner_optimizer is None:
            raise ValueError("inner optimizer cannot be None")
        assert 0.0 <= alpha <= 1.0, "alpha should be in [0.0, 1.0]"
        assert isinstance(k, int) and k > 0, "k should be a positive integer"
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        ops, pgs = self.inner_optimizer.minimize(
            loss, startup_program, parameter_list, no_grad_set)
        helper = LayerHelper("lookahead")
        block = default_main_program().global_block
        step = helper.create_or_get_global_variable(
            unique_name.generate("lookahead_step"), [1], "float32",
            initializer=Constant(0.0))
        # increment ONCE, then every parameter's sync op reads the same tick
        block.append_op("increment", {"X": [step.name]},
                        {"Out": [step.name]}, {"step": 1.0})
        for p, g in pgs:
            if g is None:
                continue
            slow = helper.create_or_get_global_variable(
                unique_name.generate(p.name + "_slow"), list(p.shape),
                p.dtype, initializer=None)
            # slow starts equal to fast: copy in the startup program
            default_startup_program().global_block.append_op(
                "assign", {"X": [p.name]}, {"Out": [slow.name]}, {})
            block.append_op(
                "lookahead",
                inputs={"Param": [p.name], "SlowParam": [slow.name],
                        "Step": [step.name]},
                outputs={"ParamOut": [p.name], "SlowOut": [slow.name]},
                attrs={"alpha": self.alpha, "k": float(self.k)},
            )
        return ops, pgs


class ExponentialMovingAverage:
    """EMA of parameters (reference optimizer.py:2453).

    `update()` appends shadow-update ops (+ a step counter) to the main
    program; `apply(executor)` is a context manager that swaps bias-corrected
    shadow values into the params in the scope for eval and restores them on
    exit (the reference does the same via temp programs)."""

    def __init__(self, decay=0.999, name=None):
        self._decay = decay
        self._name = name or "ema"
        self._shadows: dict[str, Variable] = {}
        self._step_var: Variable | None = None

    def update(self):
        block = default_main_program().global_block
        helper = LayerHelper(self._name)
        self._step_var = helper.create_or_get_global_variable(
            f"{self._name}.step", [1], "float32", initializer=Constant(0.0)
        )
        block.append_op(
            "increment",
            inputs={"X": [self._step_var.name]},
            outputs={"Out": [self._step_var.name]},
            attrs={"step": 1.0},
        )
        for param in default_main_program().all_parameters():
            shadow = helper.create_or_get_global_variable(
                f"{param.name}.{self._name}", list(param.shape), param.dtype.value
            )
            self._shadows[param.name] = shadow
            # shadow = decay*shadow + (1-decay)*param, as ops
            tmp = helper.create_variable_for_type_inference(param.dtype)
            block.append_op(
                "scale",
                inputs={"X": [shadow.name]},
                outputs={"Out": [tmp.name]},
                attrs={"scale": self._decay},
            )
            tmp2 = helper.create_variable_for_type_inference(param.dtype)
            block.append_op(
                "scale",
                inputs={"X": [param.name]},
                outputs={"Out": [tmp2.name]},
                attrs={"scale": 1.0 - self._decay},
            )
            block.append_op(
                "sum", inputs={"X": [tmp.name, tmp2.name]}, outputs={"Out": [shadow.name]}
            )

    def apply(self, executor=None, need_restore=True):
        """Context manager: params <- shadow / (1 - decay^step) in the scope."""
        import contextlib

        from .executor import global_scope

        @contextlib.contextmanager
        def _ctx():
            scope = global_scope()
            step = float(np.asarray(scope.find_var(self._step_var.name))[0]) if self._step_var else 0.0
            correction = 1.0 - self._decay ** max(step, 1.0)
            backup = {}
            for pname, shadow in self._shadows.items():
                backup[pname] = scope.find_var(pname)
                sval = np.asarray(scope.find_var(shadow.name))
                scope.set_var(pname, (sval / correction).astype(sval.dtype))
            try:
                yield
            finally:
                if need_restore:
                    for pname, val in backup.items():
                        scope.set_var(pname, val)

        return _ctx()

    def restore(self, executor=None):
        pass  # restoration handled by the apply() context manager


class PipelineOptimizer:
    """Pipeline-parallel training (reference optimizer.py:2683).

    Wraps an inner optimizer; `minimize` cuts the forward program at
    `cut_list` variables into stages and attaches a GPipe microbatch plan
    (parallel/pipeline.py). `Executor.run` on the program then executes the
    full schedule: per-microbatch forward, rematerialized backward with
    gradient accumulation, one inner-optimizer step.

    `place_list` maps one device per stage (reference SectionConfig places,
    trainer_desc.proto:74): stage parameters/optimizer state live on that
    device, boundary tensors transfer device-to-device, and the microbatch
    loop runs in clock-cycle order so stages overlap (SectionWorker
    concurrency via XLA async dispatch). Entries: jax.Device, int ordinal,
    or TPUPlace/CUDAPlace-style objects with `device_id`.
    `concurrency_list`/`queue_size`/`start_cpu_core_id` are accepted for
    reference API parity; XLA async dispatch replaces section threads and
    scope queues.
    """

    def __init__(self, optimizer, cut_list=None, place_list=None,
                 concurrency_list=None, queue_size=30, sync_steps=1,
                 start_cpu_core_id=0, num_microbatches=4, schedule=None):
        self._inner_opt = optimizer
        self._cut_list = cut_list or []
        self._place_list = place_list
        self._num_microbatches = num_microbatches
        # "1f1b" (default via FLAGS_pipeline_schedule) or "gpipe"; both are
        # numerically identical — 1f1b bounds the boundary stash at ~n_stages
        # microbatches where gpipe's grows with num_microbatches
        self._schedule = schedule

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from .parallel.pipeline import build_pipeline_plan

        if isinstance(self._inner_opt._learning_rate, Variable):
            raise NotImplementedError(
                "PipelineOptimizer does not support LR-scheduler Variables "
                "yet: the scheduler ops live in the sliced forward program "
                "and would never run for the stage update programs. Use a "
                "float learning rate.")
        cuts = []
        for group in self._cut_list:
            cuts.extend(group if isinstance(group, (list, tuple)) else [group])
        program = loss.block.program
        program._pipeline = build_pipeline_plan(
            program, loss, cuts, self._inner_opt, self._num_microbatches,
            startup_program, devices=self._place_list,
            schedule=self._schedule)
        return [], []


# short aliases matching the reference's public names (optimizer.py:2988+)
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
Lamb = LambOptimizer
LarsMomentum = LarsMomentumOptimizer
