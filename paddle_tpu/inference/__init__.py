"""Inference Predictor API.

TPU-native re-design of the reference's inference engine surface:
  * PaddlePredictor / NativeConfig
    (/root/reference/paddle/fluid/inference/api/paddle_api.h:219 Run contract,
    :287 NativeConfig; api_impl.h:34 NativePaddlePredictor)
  * AnalysisPredictor + AnalysisConfig
    (analysis_predictor.h:46, paddle_analysis_config.h) — the reference runs
    ~20 IR passes (fusion, fp16, TensorRT subgraphs) before execution.

Here the "analysis" stage IS the XLA compiler: the loaded program lowers to
one jitted computation per input signature (fusion, layout, constant folding
come from XLA, not hand-written passes). What remains of AnalysisConfig are
the knobs with real TPU meaning — bf16 weight/computation precision (the
float16 inference mode the reference benchmarks in
paddle/contrib/float16/float16_transpiler.py) and buffer donation.

Contract: predictor.run([named numpy arrays]) -> [named numpy arrays], plus
a zero-copy-ish dict API (run_dict) for Python callers.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Sequence

import numpy as np

__all__ = [
    "PaddleTensor",
    "NativeConfig",
    "AnalysisConfig",
    "create_paddle_predictor",
    "Predictor",
]


@dataclass
class PaddleTensor:
    """reference paddle_api.h:145 — a named ndarray (LoD collapses to
    padding per the framework-wide design)."""

    name: str
    data: Any = None

    @property
    def shape(self):
        return list(np.asarray(self.data).shape)


@dataclass
class NativeConfig:
    """reference paddle_api.h:287 (model paths + device). `model_dir` expects
    the save_inference_model layout."""

    model_dir: str = ""
    prog_file: str = ""
    params_file: str = ""
    use_tpu: bool = True  # device selection is jax's; kept for API parity


@dataclass
class AnalysisConfig(NativeConfig):
    """reference paddle_analysis_config.h — knobs that survive the XLA
    redesign. enable_bf16: cast params + compute to bfloat16 (the float16
    inference mode of paddle/contrib/float16/, retargeted at TPU's native
    dtype)."""

    enable_bf16: bool = False
    # no-op parity knobs: XLA always fuses/optimizes; donation is automatic
    ir_optim: bool = True
    memory_optim: bool = True
    _extra: dict = field(default_factory=dict)

    def switch_ir_optim(self, flag: bool = True):
        self.ir_optim = flag

    def enable_memory_optim(self, flag: bool = True):
        self.memory_optim = flag


class Predictor:
    """Executes a saved inference model (reference api_impl.h:34 /
    analysis_predictor.h:46). One compile per input-shape signature, cached
    by the Executor; repeated run() calls hit the cache."""

    def __init__(self, config: NativeConfig):
        from ..executor import Executor, Scope, scope_guard
        from .. import io

        self._config = config
        self._exe = Executor()
        self._scope = Scope()
        with scope_guard(self._scope):
            if config.model_dir:
                prog, feeds, fetches = io.load_inference_model(
                    config.model_dir, self._exe)
            else:
                prog, feeds, fetches = io.load_inference_model(
                    os.path.dirname(config.prog_file) or ".", self._exe,
                    model_filename=os.path.basename(config.prog_file),
                    params_filename=(os.path.basename(config.params_file)
                                     or None))
        self._program = prog
        self._feed_names = list(feeds)
        self._fetch_names = [v if isinstance(v, str) else v.name
                             for v in fetches]
        if getattr(config, "enable_bf16", False):
            self._to_bf16()

    # -- reference Run() contract -------------------------------------------
    def run(self, inputs: Sequence[PaddleTensor]) -> list[PaddleTensor]:
        feed = {t.name: t.data for t in inputs}
        outs = self.run_dict(feed)
        return [PaddleTensor(name=n, data=o)
                for n, o in zip(self._fetch_names, outs)]

    def run_dict(self, feed: dict) -> list[np.ndarray]:
        missing = [n for n in self._feed_names if n not in feed]
        if missing:
            raise ValueError(f"predictor missing feeds: {missing}")
        # pass the scope explicitly instead of via scope_guard: the guard
        # mutates a process-global scope stack, which is exactly what a
        # cloned predictor running on a second thread must not touch
        return self._exe.run(self._program, feed=feed,
                             fetch_list=self._fetch_names,
                             scope=self._scope)

    def get_input_names(self) -> list[str]:
        return list(self._feed_names)

    def get_output_names(self) -> list[str]:
        return list(self._fetch_names)

    def clone(self) -> "Predictor":
        """reference PaddlePredictor::Clone — a second handle on the SAME
        loaded model: the program, the parameter scope (jax arrays are
        immutable, so sharing is read-safe) and, critically, the Executor's
        compiled-function cache are all shared. The clone's first run() is a
        cache HIT, not a recompile — re-wrapping the program (the old
        behavior) paid a full XLA compile per clone, which defeats the
        serve-from-N-threads pattern Clone exists for. Inference programs
        write no state, so concurrent run()s from the parent and its clones
        are safe (run_dict never touches the global scope stack)."""
        new = object.__new__(Predictor)
        new._config = self._config
        new._exe = self._exe
        new._scope = self._scope
        new._program = self._program
        new._feed_names = list(self._feed_names)
        new._fetch_names = list(self._fetch_names)
        return new

    # -- bf16 inference mode -------------------------------------------------
    def _to_bf16(self):
        """Cast float params and float compute to bf16 (float16_transpiler.py
        contract, bf16 because that is the TPU-native half type)."""
        import jax.numpy as jnp
        import numpy as _np

        from ..core.types import DType

        for name in list(self._scope.var_names()):
            v = self._scope.find_var(name)
            arr = _np.asarray(v)
            if arr.dtype == _np.float32:
                self._scope.set_var(name, jnp.asarray(arr, jnp.bfloat16))
        for block in self._program.blocks:
            for var in block.vars.values():
                if var.dtype == DType.FP32:
                    var.dtype = DType.BF16
            for op in block.ops:
                if op.attrs.get("dtype") == DType.FP32:
                    op.attrs["dtype"] = DType.BF16


def create_paddle_predictor(config: NativeConfig) -> Predictor:
    """reference paddle_api.h CreatePaddlePredictor<ConfigT>."""
    return Predictor(config)
