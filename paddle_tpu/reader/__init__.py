"""Reader composition toolkit — plain-python generators + decorators.

Reference: /root/reference/python/paddle/reader/decorator.py (map_readers:28,
shuffle:64, chain:95, compose:135, buffered:190, firstn:238, xmap_readers:272,
cache:47-ish) and /root/reference/python/paddle/batch.py (batch:17).

A "reader creator" is a zero-arg callable returning a generator of samples.
These compose host-side; the TPU feed path batches them into padded numpy
arrays (DataFeeder / PyReader) before the XLA step.
"""
from __future__ import annotations

import itertools
import queue
import random as _random
import threading

__all__ = ["batch", "map_readers", "shuffle", "chain", "compose", "buffered",
           "firstn", "cache", "xmap_readers", "multiprocess_reader"]


def batch(reader, batch_size, drop_last=False):
    """Group samples into lists of `batch_size` (reference batch.py:17)."""

    def batch_reader():
        b = []
        for sample in reader():
            b.append(sample)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b

    if batch_size <= 0:
        raise ValueError("batch_size must be a positive integer")
    return batch_reader


def map_readers(func, *readers):
    """Apply func to the items of several readers zipped together."""

    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size):
    """Shuffle within a sliding buffer of `buf_size` samples."""

    def shuffled_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            _random.shuffle(buf)
            yield from buf

    return shuffled_reader


def chain(*readers):
    """Concatenate readers back to back."""

    def reader():
        for r in readers:
            yield from r()

    return reader


def compose(*readers, check_alignment=True):
    """Zip readers into tuple samples (flattening tuple elements)."""

    def _flatten(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        if check_alignment:
            for outputs in itertools.zip_longest(*rs):
                if any(o is None for o in outputs):
                    raise RuntimeError("readers have different lengths")
                yield sum((_flatten(o) for o in outputs), ())
        else:
            for outputs in zip(*rs):
                yield sum((_flatten(o) for o in outputs), ())

    return reader


class _End:
    pass


_END = _End()


def _prefetch_iter(source_gen_fn, size, stall_window=None,
                   stall_what="prefetch consumer"):
    """Shared bounded-queue prefetch: propagates producer exceptions to the
    consumer and unblocks/stops the producer if the consumer abandons the
    iteration (no leaked threads stuck on q.put).

    stall_window (seconds, optional): bound the consumer's wait for the
    next staged batch — a producer that wedges without raising (hung I/O,
    a deadlocked transform) raises `resilience.StallError` with a queue
    state dump after the window instead of hanging the training loop
    forever (DeviceLoader passes FLAGS_watchdog_stall_s here)."""
    q: queue.Queue = queue.Queue(maxsize=size)
    err: list = []
    stop = threading.Event()

    def fill():
        try:
            for d in source_gen_fn():
                while not stop.is_set():
                    try:
                        q.put(d, timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if stop.is_set():
                    return
        except BaseException as e:
            err.append(e)
        finally:
            while not stop.is_set():
                try:
                    q.put(_END, timeout=0.1)
                    break
                except queue.Full:
                    continue

    t = threading.Thread(target=fill, daemon=True)
    t.start()

    def _get_bounded():
        import time

        deadline = time.monotonic() + stall_window
        while True:
            try:
                return q.get(timeout=0.1)
            except queue.Empty:
                if time.monotonic() > deadline:
                    from ..resilience.watchdog import (StallError,
                                                       runtime_state)

                    raise StallError(
                        stall_what, stall_window,
                        runtime_state(queue_depth=q.qsize(),
                                      queue_capacity=size,
                                      producer_alive=t.is_alive()))

    try:
        while True:
            e = (_get_bounded() if stall_window and stall_window > 0
                 else q.get())
            if e is _END:
                if err:
                    raise err[0]
                return
            yield e
    finally:
        stop.set()


def buffered(reader, size):
    """Prefetch up to `size` samples in a background thread. Producer
    exceptions re-raise in the consumer (a swallowed error would read as a
    silently short epoch)."""

    def buffered_reader():
        yield from _prefetch_iter(reader, size)

    return buffered_reader


def firstn(reader, n):
    def firstn_reader():
        for i, item in enumerate(reader()):
            if i >= n:
                return
            yield item

    return firstn_reader


def cache(reader):
    """Materialize the full reader in memory on first COMPLETE pass. The
    cache commits atomically at the end of a pass, so a partially-consumed
    first iteration (e.g. peeking one sample) never poisons later epochs."""
    state = {"data": None}

    def cached_reader():
        if state["data"] is None:
            collecting = []
            for item in reader():
                collecting.append(item)
                yield item
            state["data"] = collecting
        else:
            yield from state["data"]

    return cached_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map over a reader with `process_num` worker THREADS
    (reference uses threads too despite the name). Order-preserving mode
    tags samples with sequence ids."""

    end = object()

    def xreader():
        in_q: queue.Queue = queue.Queue(buffer_size)
        out_q: queue.Queue = queue.Queue(buffer_size)
        errors: list = []
        stop = threading.Event()

        def _put(q, item) -> bool:
            """Bounded put that gives up when the consumer abandoned us."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def read_into():
            try:
                for i, sample in enumerate(reader()):
                    if not _put(in_q, (i, sample)):
                        return
            except BaseException as e:
                errors.append(e)
            finally:
                # always deliver every worker its end marker, even after an
                # error — a missing sentinel deadlocks the whole pipeline
                for _ in range(process_num):
                    if not _put(in_q, end):
                        return

        def work():
            try:
                while not stop.is_set():
                    try:
                        item = in_q.get(timeout=0.1)
                    except queue.Empty:
                        continue
                    if item is end:
                        return
                    i, sample = item
                    if not _put(out_q, (i, mapper(sample))):
                        return
            except BaseException as e:
                errors.append(e)
            finally:
                _put(out_q, end)

        threading.Thread(target=read_into, daemon=True).start()
        workers = [threading.Thread(target=work, daemon=True) for _ in range(process_num)]
        for w in workers:
            w.start()

        try:
            finished = 0
            if order:
                pending: dict = {}
                next_i = 0
                while finished < process_num:
                    item = out_q.get()
                    if item is end:
                        finished += 1
                        continue
                    i, mapped = item
                    pending[i] = mapped
                    while next_i in pending:
                        yield pending.pop(next_i)
                        next_i += 1
                for i in sorted(pending):
                    yield pending[i]
            else:
                while finished < process_num:
                    item = out_q.get()
                    if item is end:
                        finished += 1
                        continue
                    yield item[1]
            if errors:
                raise errors[0]
        finally:
            stop.set()

    return xreader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """API-parity shim: runs the readers with thread workers (python
    multiprocessing brings no benefit for numpy-producing readers feeding a
    single-process XLA client; the reference targets CPU-bound python
    preprocessing)."""
    return buffered(chain(*readers), queue_size)
