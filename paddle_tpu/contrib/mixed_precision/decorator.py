"""AMP optimizer decorator.

Reference: /root/reference/python/paddle/fluid/contrib/mixed_precision/
decorator.py (OptimizerWithMixedPrecision:26, decorate:205). Contract kept:
`decorate(optimizer)` returns a wrapper whose minimize() rewrites the forward
program to low precision, scales the loss, unscales/checks the grads, and
maintains dynamic loss scaling.

TPU-first default: bfloat16, loss scaling OFF — bf16 shares float32's
exponent range, so scaling exists only for float16 parity and for users who
ask for it. Overflow steps zero the gradients (branchless skip; moments still
decay, matching the reference-era behavior rather than Paddle 2.x SkipUpdate).
"""
from __future__ import annotations

from ... import layers as L
from ...framework import default_main_program
from ...initializer import Constant
from ...layer_helper import LayerHelper
from .fp16_lists import AutoMixedPrecisionLists
from .fp16_utils import rewrite_program

__all__ = ["decorate", "OptimizerWithMixedPrecision"]


class OptimizerWithMixedPrecision:
    def __init__(self, optimizer, amp_lists, init_loss_scaling,
                 use_dynamic_loss_scaling, incr_every_n_steps,
                 decr_every_n_nan_or_inf, incr_ratio, decr_ratio, dest_dtype):
        self._optimizer = optimizer
        self._amp_lists = amp_lists or AutoMixedPrecisionLists()
        self._init_loss_scaling = float(init_loss_scaling)
        self._use_dynamic = use_dynamic_loss_scaling
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n_nan_or_inf = decr_every_n_nan_or_inf
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._dest_dtype = dest_dtype
        self._loss_scaling = None

    def get_loss_scaling(self):
        return self._loss_scaling

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        # gray-list entries are tunable decisions (tuning/): swept-DB
        # entries may promote/demote ops before the rewrite sees the lists
        from .fp16_lists import apply_tuning_overrides

        self._amp_lists = apply_tuning_overrides(self._amp_lists)
        rewrite_program(default_main_program(), self._amp_lists,
                        self._dest_dtype)
        helper = LayerHelper("loss_scaling")
        # scalar (rank-0) so elementwise_mul with a scalar loss is rank-legal
        self._loss_scaling = helper.create_or_get_global_variable(
            "@LOSS_SCALING@", [], "float32",
            initializer=Constant(self._init_loss_scaling))
        needs_scaling = self._use_dynamic or self._init_loss_scaling != 1.0
        scaled = (L.elementwise_mul(loss, self._loss_scaling)
                  if needs_scaling else loss)
        params_grads = self._optimizer.backward(
            scaled, startup_program, parameter_list, no_grad_set)
        params_grads = self._unscale_and_check(params_grads, helper,
                                               needs_scaling)
        # numeric guardrail composition (resilience/guardrails.py): the
        # health sentinel must judge the UNSCALED loss (the scaled one moves
        # with the dynamic scale, poisoning its spike EMA), and AMP's own
        # @FOUND_INF@ verdict ORs into the health vector so both skip
        # mechanisms agree — the inner backward recorded the scaled name
        default_main_program()._guard_loss_name = loss.name
        return params_grads

    def _unscale_and_check(self, params_grads, helper, needs_scaling):
        if not self._use_dynamic:
            if needs_scaling:
                inv = 1.0 / self._init_loss_scaling
                params_grads = [(p, L.scale(g, scale=inv))
                                for p, g in params_grads]
            return params_grads
        grads = [g for _, g in params_grads]
        found_inf = helper.create_or_get_global_variable(
            "@FOUND_INF@", [1], "bool", initializer=Constant(0.0))
        unscaled = [helper.create_variable_for_type_inference(g.dtype)
                    for g in grads]
        helper.append_op(
            "check_finite_and_unscale",
            {"X": [g.name for g in grads],
             "Scale": [self._loss_scaling.name]},
            {"Out": [u.name for u in unscaled],
             "FoundInfinite": [found_inf.name]},
            {},
        )
        # expose AMP's verdict to the guardrail sentinel (see backward)
        default_main_program()._guard_found_inf_name = found_inf.name
        good = helper.create_or_get_global_variable(
            "@GOOD_STEPS@", [1], "int32", initializer=Constant(0.0))
        bad = helper.create_or_get_global_variable(
            "@BAD_STEPS@", [1], "int32", initializer=Constant(0.0))
        helper.append_op(
            "update_loss_scaling",
            {"PrevLossScaling": [self._loss_scaling.name],
             "InGoodSteps": [good.name], "InBadSteps": [bad.name],
             "FoundInfinite": [found_inf.name]},
            {"LossScaling": [self._loss_scaling.name],
             "OutGoodSteps": [good.name], "OutBadSteps": [bad.name]},
            {"incr_every_n_steps": self._incr_every_n_steps,
             "decr_every_n_nan_or_inf": self._decr_every_n_nan_or_inf,
             "incr_ratio": self._incr_ratio,
             "decr_ratio": self._decr_ratio},
        )
        return [(p, u) for (p, _), u in zip(params_grads, unscaled)]

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        opt_ops = self._optimizer.apply_gradients(params_grads)
        return opt_ops, params_grads

    def __getattr__(self, item):
        return getattr(self._optimizer, item)


def decorate(optimizer, amp_lists=None, init_loss_scaling=1.0,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.5,
             use_dynamic_loss_scaling=False, dest_dtype="bfloat16"):
    """Wrap `optimizer` for mixed-precision training (decorator.py:205).
    Defaults are bf16-on-TPU sane; pass dest_dtype='float16' +
    use_dynamic_loss_scaling=True for the reference's fp16 regime."""
    return OptimizerWithMixedPrecision(
        optimizer, amp_lists, init_loss_scaling, use_dynamic_loss_scaling,
        incr_every_n_steps, decr_every_n_nan_or_inf, incr_ratio, decr_ratio,
        dest_dtype)
