"""Op lists steering automatic mixed precision.

Reference: /root/reference/python/paddle/fluid/contrib/mixed_precision/
fp16_lists.py (AutoMixedPrecisionLists:19, white_list:60, black_list:67,
gray_list:77). The split is the same idea retuned for TPU: white ops are the
MXU FLOP carriers (matmul/conv) that should run in bfloat16; black ops are
numerically-sensitive reductions/exponentials kept in float32; everything
else (gray) follows its inputs — our JAX kernels are dtype-polymorphic, so
gray needs no rewriting at all."""
from __future__ import annotations

__all__ = ["AutoMixedPrecisionLists", "white_list", "black_list", "gray_list",
           "apply_tuning_overrides"]

white_list = {
    "mul",
    "matmul",
    "conv2d",
    "depthwise_conv2d",
    "conv2d_transpose",
    # MXU carrier with fp32 softmax statistics inside the kernel
    "fused_attention",
}

black_list = {
    "exp",
    "square",
    "log",
    "mean",
    "sum",
    "cos_sim",
    "log_softmax",
    "sigmoid_cross_entropy_with_logits",
    "cross_entropy",
    "reduce_sum",
    "reduce_mean",
    "squared_l2_norm",
}

# layer_norm/softmax/batch_norm are gray, not black (a departure from the
# reference's CUDA lists): all three kernels already keep their statistics in
# fp32 registers internally (nn_ops.layer_norm and batch_norm upcast;
# softmax's max-subtraction bounds the bf16 exp), so forcing fp32 at the op
# BOUNDARY only added HBM-sized cast round-trips — around every BN in
# ResNet-50 this measured 2.7x slower than no AMP at all (PERF.md).
gray_list = {
    "elementwise_add", "elementwise_sub", "elementwise_mul", "elementwise_div",
    "relu", "gelu", "tanh", "sigmoid", "leaky_relu", "dropout", "pool2d",
    "transpose2", "reshape2", "concat", "split", "slice", "squeeze2",
    "unsqueeze2", "stack", "scale", "lookup_table", "lookup_table_v2",
    "layer_norm", "softmax", "softmax_mask_fuse_upper_triangle",
    "batch_norm",
    # fused conv+BN (passes.fuse_conv_bn_stats) normally post-dates the AMP
    # rewrite, but a manually-fused program must follow the batch_norm rule:
    # fp32 statistics live INSIDE the kernel, boundaries follow the inputs
    "conv2d_bn",
    # gray since r5: the op upcasts to fp32 INTERNALLY (classic path) or
    # keeps fp32 statistics in-kernel (Pallas path) — black-listing it
    # doubled the lm-head logits traffic at BERT vocab sizes
    "softmax_with_cross_entropy",
}


def apply_tuning_overrides(lists: "AutoMixedPrecisionLists"):
    """Gray-list membership as a tunable decision (FLAGS_tuning_mode):
    an op the hand lists leave gray ("follow your inputs") can be promoted
    to white (bf16 boundaries — more MXU/HBM savings) or demoted to black
    (fp32 boundaries — numerically fragile at some site) by a swept-DB
    entry, per device kind. Only ops still gray are touched, so a user's
    custom_white_list/custom_black_list moves always win; the analytic
    prior is "stay gray" (the measured hand-tuned split above), so with no
    DB entry the lists are byte-identical to the pre-tuner ones."""
    from ... import tuning

    if tuning.mode() == "off":
        return lists
    for op in sorted(lists.gray_list):
        key = tuning.canonical_key("amp_list", tuning.amp_key(op), "-",
                                   tuning.device_kind())
        decision, _tier = tuning.decide(
            "amp_list", key,
            prior=lambda: {"list": "gray"},
            default={"list": "gray"},
            validate=lambda dd: dd.get("list") in ("white", "black", "gray"))
        target = decision.get("list", "gray")
        if target != "gray":
            lists.gray_list.discard(op)
            (lists.white_list if target == "white"
             else lists.black_list).add(op)
    return lists


class AutoMixedPrecisionLists:
    def __init__(self, custom_white_list=None, custom_black_list=None):
        if custom_white_list and custom_black_list:
            both = set(custom_white_list) & set(custom_black_list)
            if both:
                raise ValueError(f"ops in both custom lists: {both}")
        self.white_list = set(white_list)
        self.black_list = set(black_list)
        self.gray_list = set(gray_list)
        if custom_white_list:
            for t in custom_white_list:
                self.white_list.add(t)
                self.black_list.discard(t)
        if custom_black_list:
            for t in custom_black_list:
                self.black_list.add(t)
                self.white_list.discard(t)
