"""Program rewriting for mixed precision: insert casts around white/black ops.

Reference: /root/reference/python/paddle/fluid/contrib/mixed_precision/
fp16_utils.py (rewrite_program:139, _insert_cast_op:60). Same transformation,
bfloat16-first: white ops get their float32 inputs cast to the low dtype
(cast vars are reused per (name, dtype)), black ops get low-dtype inputs cast
back to float32. Parameters stay float32 in the scope — the in-program cast
IS the master-weight scheme: the optimizer updates fp32 params, the forward
consumes their low-precision view, and XLA fuses the cast into the consumer.
"""
from __future__ import annotations

from ...core.types import DType
from ...framework import Operator, Program

__all__ = ["rewrite_program", "cast_var_suffix"]

_LOW = {"bfloat16": "@BF16", "float16": "@FP16"}

# Input slots that alias persistable running state (the op's stateful
# outputs write back to the same vars). Harmonize-down must NEVER cast
# these: a bf16 EMA update `mean*0.9 + x*0.1` rounds away increments below
# ~0.4% of the running value, so the statistics quantize/stall over
# training, and the "fp32" stat vars would flip dtype in checkpoints.
_STATE_SLOTS = {
    "batch_norm": {"Mean", "Variance"},
    "conv2d_bn": {"Mean", "Variance"},
    "fake_quantize_dequantize_moving_average_abs_max": {"InScale"},
}


def cast_var_suffix(dest_dtype: str) -> str:
    return _LOW.get(dest_dtype, "@LOW")


def _cast_input(block, op_idx, name, dest_dtype, cache):
    """Insert (or reuse) `cast(name) -> name@SUFFIX` before op_idx; returns
    the cast var name and how many ops were inserted (0 or 1)."""
    try:
        src = block.var(name)
    except KeyError:
        return name, 0
    if dest_dtype == "float32":
        if src.dtype not in (DType.BF16, DType.FP16):
            return name, 0
    elif src.dtype != DType.FP32:
        return name, 0  # only fp32 tensors get a low-precision view
    key = (name, dest_dtype)
    if key in cache:
        return cache[key], 0
    suffix = "@FP32" if dest_dtype == "float32" else cast_var_suffix(dest_dtype)
    cast_name = name + suffix
    if not block.has_var(cast_name):
        block.create_var(name=cast_name, shape=src.shape, dtype=dest_dtype,
                         stop_gradient=src.stop_gradient)
    block._insert_op(
        op_idx, "cast", {"X": [name]}, {"Out": [cast_name]},
        {"in_dtype": src.dtype.value, "out_dtype": dest_dtype},
    )
    cache[key] = cast_name
    return cast_name, 1


def rewrite_program(main_program: Program, amp_lists, dest_dtype="bfloat16"):
    """Walk every block's (forward) op list, casting white-op inputs to
    `dest_dtype` and black-op inputs back to float32. Returns the number of
    casts inserted. Must run BEFORE append_backward so grad ops derive
    through the casts. Control-flow sub-blocks are rewritten too — the FLOPs
    of an RNN/scan model live there."""
    n_casts = 0
    for block in main_program.blocks:
        n_casts += _rewrite_block(block, amp_lists, dest_dtype)
        _hoist_casts_through_layout(block)
    main_program._bump_version()
    return n_casts


# Dtype-transparent single-input ops that only move data. A down-cast
# sitting BELOW such an op is hoisted above it so the data movement happens
# at low precision: an fp32 2x2 space-to-depth repack of the 77 MB ResNet
# input measured +1.0 ms/step vs the same repack in bf16 (XLA does not sink
# converts through transposes on its own; /tmp probe, PERF.md r5).
_LAYOUT_OPS = {"reshape2", "transpose2", "squeeze2", "unsqueeze2",
               "flatten2", "space_to_depth", "depth_to_space",
               "pixel_shuffle", "shuffle_channel"}


def _hoist_casts_through_layout(block):
    from ...ops.registry import infer_op

    changed = True
    while changed:
        changed = False
        # producer index and consumer count per var name, current op order
        producer = {}
        consumers: dict = {}
        for idx, op in enumerate(block.ops):
            for n in op.input_names:
                consumers[n] = consumers.get(n, 0) + 1
            for n in op.output_names:
                producer[n] = idx
        for ci, op in enumerate(block.ops):
            if op.type != "cast":
                continue
            if op.attr("out_dtype") not in ("bfloat16", "float16"):
                continue
            (src,) = op.input("X")
            pi = producer.get(src)
            if pi is None:
                continue
            p = block.ops[pi]
            if p.type not in _LAYOUT_OPS or consumers.get(src, 0) != 1:
                continue
            (px,) = p.input("X")
            if not block.has_var(px) or block.var(px).dtype != DType.FP32:
                continue
            (dst,) = op.output("Out")
            # rewire: cast(px) ABOVE p; p consumes the cast and writes
            # directly into the cast op's output var; drop the old cast.
            # The hoisted cast var must be FRESH: px@BF16 may already exist
            # with its own producer (a white op elsewhere also consumes px),
            # and adding a second producer makes append_backward sum both
            # branches' cast_grads into px@GRAD — silently 1.5x gradients
            # (r5 code review, confirmed by repro).
            low = px + cast_var_suffix(op.attr("out_dtype")) + "@HOIST"
            n = 0
            while block.has_var(low + (f"{n}" if n else "")):
                n += 1
            low = low + (f"{n}" if n else "")
            src_var = block.var(px)
            block.create_var(name=low, shape=src_var.shape,
                             dtype=op.attr("out_dtype"),
                             stop_gradient=src_var.stop_gradient)
            del block.ops[ci]
            block._insert_op(pi, "cast", {"X": [px]}, {"Out": [low]},
                             {"in_dtype": "float32",
                              "out_dtype": op.attr("out_dtype")})
            p.inputs["X"] = [low]
            p.outputs["Out"] = [dst]
            infer_op(p, block)
            # keep the layout op's ORIGINAL fp32 output fetchable: a user
            # may fetch it by name even though no op consumes it. The
            # repair upcast is dead code unless fetched — XLA DCEs it.
            p_idx = block.ops.index(p)
            block._insert_op(p_idx + 1, "cast", {"X": [dst]},
                             {"Out": [src]},
                             {"in_dtype": op.attr("out_dtype"),
                              "out_dtype": "float32"})
            changed = True
            break


def _mixed_float_inputs(block, op) -> bool:
    """True when the op reads BOTH a low-precision and an fp32 float input —
    the case where jnp promotion would silently drag the activation back up."""
    seen = set()
    exempt = _STATE_SLOTS.get(op.type, ())
    for slot, names in op.inputs.items():
        if slot in exempt:
            continue
        for n in names:
            if not n or not block.has_var(n):
                continue
            dt = block.var(n).dtype
            if dt in (DType.FP32, DType.BF16, DType.FP16):
                seen.add(dt)
    return DType.FP32 in seen and (DType.BF16 in seen or DType.FP16 in seen)


def _rewrite_block(block, amp_lists, dest_dtype):
    from ...ops.registry import infer_op

    cache: dict = {}
    i = 0
    n_casts = 0
    while i < len(block.ops):
        op = block.ops[i]
        target = None
        if op.type in amp_lists.white_list:
            target = dest_dtype
        elif op.type in amp_lists.black_list:
            target = "float32"
        elif op.type != "cast" and _mixed_float_inputs(block, op):
            # gray/unlisted op mixing bf16 activations with fp32 side inputs
            # (bias add, residual add against an fp32 stream, LN gain/bias):
            # harmonize DOWN. Without this every such op promotes to fp32 and
            # the whole residual/FFN stream materializes at 2x width — the
            # single largest HBM cost found in the r2 perf audit (PERF.md).
            target = dest_dtype
        if target is None:
            # gray op: no casts, but RE-INFER its output dtype so bf16-ness
            # propagates through metadata — otherwise a black op downstream
            # of white->gray sees stale fp32 metadata and never casts back
            infer_op(op, block)
            _invalidate(cache, op)
            i += 1
            continue
        inserted_here = 0
        exempt = _STATE_SLOTS.get(op.type, ())
        for slot, names in list(op.inputs.items()):
            if slot in exempt:
                continue
            new_names = []
            for name in names:
                if not name:
                    new_names.append(name)
                    continue
                new_name, inserted = _cast_input(block, i, name, target, cache)
                new_names.append(new_name)
                inserted_here += inserted
                i += inserted
            op.inputs[slot] = new_names
        # re-infer this op's output dtype under the new input dtypes
        infer_op(op, block)
        _invalidate(cache, op)
        n_casts += inserted_here
        i += 1
    return n_casts


def _invalidate(cache: dict, op):
    """A redefined var's cached low-precision view is stale — drop it so the
    next consumer re-casts the NEW value."""
    for out in op.output_names:
        if not out:
            continue
        for key in [k for k in cache if k[0] == out]:
            del cache[key]
