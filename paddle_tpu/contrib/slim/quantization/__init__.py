from .quantization_pass import (  # noqa: F401
    ConvertToInt8Pass,
    QuantizationFreezePass,
    QuantizationTransformPass,
)
from .post_training_quantization import PostTrainingQuantization  # noqa: F401
