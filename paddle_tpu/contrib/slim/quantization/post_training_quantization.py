"""Post-training quantization: calibrate activation scales on sample data,
then insert static quantize-dequantize ops — no retraining.

TPU-native equivalent of the reference's post-training paths (contrib/slim
calibration + the int8 mkldnn calibrator, reference
contrib/slim/quantization/quantization_pass.py family): where QAT learns
moving-average scales during training, PTQ measures abs-max statistics by
RUNNING the trained inference program over a calibration set, then rewrites
the program with fixed-scale q/dq ops. `QuantizationFreezePass` +
`save_inference_model` afterwards produce the deployable quantized model
(optionally `ConvertToInt8Pass` for 1-byte weights).
"""
from __future__ import annotations

import numpy as np

from ....framework import Program

__all__ = ["PostTrainingQuantization"]

_DEFAULT_QUANTIZABLE = ("conv2d", "depthwise_conv2d", "mul", "matmul")


class PostTrainingQuantization:
    """Usage::

        ptq = PostTrainingQuantization(
            executor=exe, program=inference_program,
            sample_feeds=[{...}, ...],          # calibration batches
            scope=scope)                         # holds trained params
        quant_program = ptq.quantize()           # static-scale q/dq inserted
        QuantizationFreezePass(scope).apply(quant_program)
        io.save_inference_model(...)
    """

    def __init__(self, executor, program: Program, sample_feeds,
                 scope=None, quantizable_op_type=_DEFAULT_QUANTIZABLE,
                 weight_bits=8, activation_bits=8, algo="abs_max"):
        from ....executor import global_scope

        if algo != "abs_max":
            raise NotImplementedError(
                f"calibration algo '{algo}' — only abs_max is implemented")
        if not sample_feeds:
            raise ValueError("PTQ needs at least one calibration batch")
        self._exe = executor
        self._program = program
        self._feeds = list(sample_feeds)
        self._scope = scope or global_scope()
        self._types = tuple(quantizable_op_type)
        self._weight_bits = weight_bits
        self._activation_bits = activation_bits

    def quantize(self) -> Program:
        block = self._program.global_block
        params = {p.name for p in self._program.all_parameters()}

        # 1. the tensors feeding quantizable ops
        act_names, weight_names = [], []
        for op in block.ops:
            if op.type not in self._types:
                continue
            for names in op.inputs.values():
                for n in names:
                    if not n or not block.has_var(n):
                        continue
                    if n in params:
                        if n not in weight_names:
                            weight_names.append(n)
                    elif n not in act_names:
                        act_names.append(n)

        # 2. calibrate: abs-max of each activation over the sample batches
        act_scales = {n: 0.0 for n in act_names}
        from ....executor import scope_guard

        with scope_guard(self._scope):
            for feed in self._feeds:
                outs = self._exe.run(self._program, feed=feed,
                                     fetch_list=act_names)
                for n, v in zip(act_names, outs):
                    act_scales[n] = max(act_scales[n],
                                        float(np.abs(np.asarray(v)).max()))

        # 3. weight scales straight from the trained values
        weight_scales = {
            n: float(np.abs(np.asarray(self._scope.find_var(n))).max())
            for n in weight_names}

        # 4. rewrite: static q/dq in front of every quantizable op
        from .... import unique_name

        quantized: dict[str, str] = {}
        for op in list(block.ops):
            if op.type not in self._types:
                continue
            for slot, names in op.inputs.items():
                for i, n in enumerate(names):
                    if n in quantized:
                        names[i] = quantized[n]
                        continue
                    scale = weight_scales.get(n, act_scales.get(n))
                    if scale is None:
                        continue
                    bits = (self._weight_bits if n in weight_scales
                            else self._activation_bits)
                    var = block.var(n)
                    out = block.create_var(
                        name=unique_name.generate(n + ".ptq"),
                        shape=var.shape, dtype=var.dtype)
                    block._insert_op(
                        block.ops.index(op), "fake_quantize_dequantize_static",
                        {"X": [n]}, {"Out": [out.name]},
                        {"scale": max(scale, 1e-8), "bit_length": bits})
                    quantized[n] = out.name
                    names[i] = out.name
        self._program._bump_version()
        return self._program
