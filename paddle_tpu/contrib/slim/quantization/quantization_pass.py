"""Quantization-aware-training transform.

TPU-native re-design of the reference's QuantizationTransformPass
(/root/reference/python/paddle/fluid/contrib/slim/quantization/
quantization_pass.py:35): walk the program, and for each quantizable op
(conv2d/depthwise_conv2d/mul/matmul) insert fused quantize-dequantize ops on
its weight (abs_max) and activation input (moving-average abs_max). The
reference rewires an IrGraph; here the Program IR is rewritten directly —
the inserted ops carry straight-through gradients so minimize() after the
pass trains quantization-aware, and XLA folds the q/dq arithmetic into the
surrounding matmul at compile time.
"""
from __future__ import annotations

from ....framework import default_main_program

_QUANTIZABLE = ("conv2d", "depthwise_conv2d", "mul", "matmul")


class QuantizationTransformPass:
    """reference quantization_pass.py:35 (weight abs_max + activation
    moving_average_abs_max, the default W8A8 config)."""

    def __init__(self, scope=None, place=None, weight_bits=8,
                 activation_bits=8, moving_rate=0.9,
                 quantizable_op_type=_QUANTIZABLE,
                 skip_pattern="skip_quant"):
        self._weight_bits = weight_bits
        self._activation_bits = activation_bits
        self._moving_rate = moving_rate
        self._types = tuple(quantizable_op_type)
        self._skip = skip_pattern

    def apply(self, program=None, startup_program=None, for_test=False):
        """Insert q/dq ops in front of every quantizable op (mutates and
        returns `program`). Run BEFORE minimize() so the backward pass
        differentiates through the straight-through estimators."""
        program = program or default_main_program()
        block = program.global_block
        params = {p.name for p in program.all_parameters()}
        quantized: dict[str, str] = {}  # original name -> q/dq output name

        for op in list(block.ops):
            if op.type not in self._types or op.attrs.get(self._skip):
                continue
            for slot, names in op.inputs.items():
                for i, n in enumerate(names):
                    if n in quantized:
                        names[i] = quantized[n]
                        continue
                    try:
                        var = block.var(n)
                    except KeyError:
                        continue
                    if var.dtype.value not in ("float32", "bfloat16",
                                               "float16"):
                        continue
                    idx = block.ops.index(op)
                    q = self._insert_qdq(block, idx, var,
                                         is_weight=n in params,
                                         for_test=for_test,
                                         startup_program=startup_program)
                    quantized[n] = q
                    names[i] = q
        program._bump_version()
        return program

    def _insert_qdq(self, block, idx, var, is_weight, for_test,
                    startup_program=None):
        from .... import unique_name

        out = block.create_var(
            name=unique_name.generate(var.name + ".quantized"),
            shape=var.shape, dtype=var.dtype)
        if is_weight:
            scale = block.create_var(
                name=unique_name.generate(var.name + ".scale"),
                shape=(1,), dtype="float32")
            block._insert_op(
                idx, "fake_quantize_dequantize_abs_max",
                {"X": [var.name]},
                {"Out": [out.name], "OutScale": [scale.name]},
                {"bit_length": self._weight_bits})
        else:
            # moving-average activation scale: persistable running state,
            # zero-initialized by the STARTUP program (re-filling it in the
            # main program would reset the average every step). Bound to the
            # PASSED programs — LayerHelper would silently target the
            # defaults when apply() is given explicit programs.
            from ....framework import default_startup_program

            state = block.create_var(
                name=unique_name.generate(var.name + ".ma_scale"),
                shape=(1,), dtype="float32", persistable=True)
            sp = startup_program or default_startup_program()
            sblk = sp.global_block
            sblk.create_var(name=state.name, shape=(1,), dtype="float32",
                            persistable=True)
            sblk.append_op(
                "fill_constant", {}, {"Out": [state.name]},
                {"shape": [1], "dtype": "float32", "value": 0.0})
            block._insert_op(
                idx, "fake_quantize_dequantize_moving_average_abs_max",
                {"X": [var.name], "InScale": [state.name]},
                {"Out": [out.name], "OutScale": [state.name]},
                {"bit_length": self._activation_bits,
                 "moving_rate": self._moving_rate, "is_test": for_test})
        return out.name


_FAKE_WEIGHT_OPS = ("fake_quantize_dequantize_abs_max",)
_FAKE_ACT_OPS = ("fake_quantize_dequantize_moving_average_abs_max",)
_FAKE_STATIC = "fake_quantize_dequantize_static"


class QuantizationFreezePass:
    """Convert a QAT-trained (or PTQ-calibrated) program into an inference
    program (reference quantization_pass.py QuantizationFreezePass):

      * weight fake-q/dq ops are removed and the SCOPE weight is overwritten
        with its quantize-dequantized value — inference math equals the QAT
        forward exactly; the weight's abs-max scale is stored in a
        persistable `<w>@quant_scale` var for the int8 convert step;
      * activation fake ops are removed (consumers rewired to the raw
        input); the learned/calibrated scale is recorded on each consumer op
        as an `in_scales` attr — the quantization metadata an int8 engine
        needs at runtime, without burdening the fp simulation.

    Apply AFTER training, BEFORE save_inference_model."""

    def __init__(self, scope=None, place=None, weight_bits=8,
                 activation_bits=8):
        from ....executor import global_scope

        self._scope = scope or global_scope()
        self._weight_bits = weight_bits
        self._activation_bits = activation_bits

    def apply(self, program=None):
        import numpy as np

        from ....framework import default_main_program

        program = program or default_main_program()
        block = program.global_block
        params = {p.name for p in program.all_parameters()}
        replace: dict[str, str] = {}   # fake-out name -> original input
        act_scales: dict[str, float] = {}  # rewired input name -> scale
        new_ops = []
        for op in block.ops:
            if op.type in _FAKE_WEIGHT_OPS or (
                    op.type == _FAKE_STATIC
                    and op.inputs["X"][0] in params):
                w_name = op.inputs["X"][0]
                out_name = op.outputs["Out"][0]
                w = np.asarray(self._scope.find_var(w_name))
                n = float(2 ** (self._weight_bits - 1) - 1)
                scale = float(np.abs(w).max()) if op.type != _FAKE_STATIC \
                    else float(op.attrs["scale"])
                scale = max(scale, 1e-8)
                q = np.clip(np.round(w / scale * n), -n, n)
                self._scope.set_var(w_name, (q * scale / n).astype(w.dtype))
                sname = w_name + "@quant_scale"
                block.create_var(name=sname, shape=(1,), dtype="float32",
                                 persistable=True)
                self._scope.set_var(sname, np.asarray([scale], np.float32))
                replace[out_name] = w_name
                continue
            if op.type in _FAKE_ACT_OPS or (
                    op.type == _FAKE_STATIC
                    and op.inputs["X"][0] not in params):
                x_name = op.inputs["X"][0]
                out_name = op.outputs["Out"][0]
                if op.type == _FAKE_STATIC:
                    scale = float(op.attrs["scale"])
                else:
                    sv = self._scope.find_var(op.inputs["InScale"][0])
                    if sv is None:
                        raise RuntimeError(
                            f"QuantizationFreezePass: moving-average scale "
                            f"'{op.inputs['InScale'][0]}' not in the scope — "
                            "pass the scope QAT trained in (a silent 0.0 "
                            "scale would poison the in_scales metadata)")
                    scale = float(np.asarray(sv).reshape(-1)[0])
                replace[out_name] = x_name
                act_scales[x_name] = scale
                continue
            new_ops.append(op)
        for op in new_ops:
            scales = {}
            for slot, names in op.inputs.items():
                for i, nme in enumerate(names):
                    if nme in replace:
                        names[i] = replace[nme]
                    if names[i] in act_scales:
                        scales[names[i]] = act_scales[names[i]]
            if scales:
                op.attrs = {**op.attrs, "in_scales": scales}
        block.ops = new_ops
        program._bump_version()
        return program


class ConvertToInt8Pass:
    """Store frozen weights as int8 (reference ConvertToInt8Pass): each
    frozen-quantized weight var flips to int8 in program + scope, and a
    `dequantize_abs_max` op is inserted before its consumers — the saved
    model carries 1-byte weights and dequantizes at run time."""

    def __init__(self, scope=None, place=None, weight_bits=8):
        from ....executor import global_scope

        self._scope = scope or global_scope()
        self._weight_bits = weight_bits

    def apply(self, program=None):
        import numpy as np

        from .... import unique_name
        from ....framework import default_main_program

        program = program or default_main_program()
        block = program.global_block
        n = float(2 ** (self._weight_bits - 1) - 1)
        converted: dict[str, str] = {}  # weight -> dequantized var name
        for w_name in [v for v in list(block.vars)
                       if block.has_var(v + "@quant_scale")]:
            w = np.asarray(self._scope.find_var(w_name))
            scale = float(np.asarray(
                self._scope.find_var(w_name + "@quant_scale")).reshape(-1)[0])
            q = np.clip(np.round(w / max(scale, 1e-8) * n), -n, n)
            self._scope.set_var(w_name, q.astype(np.int8))
            from ....core.types import DType

            block.var(w_name).dtype = DType.INT8
            deq = block.create_var(
                name=unique_name.generate(w_name + ".deq"),
                shape=w.shape, dtype="float32")
            converted[w_name] = deq.name
        if not converted:
            return program
        # insert one dequantize per weight at the top; rewire consumers
        for i, (w_name, deq_name) in enumerate(sorted(converted.items())):
            block._insert_op(
                i, "dequantize_abs_max",
                {"X": [w_name], "Scale": [w_name + "@quant_scale"]},
                {"Out": [deq_name]}, {"bit_length": self._weight_bits})
        for op in block.ops:
            if op.type == "dequantize_abs_max":
                continue
            for slot, names in op.inputs.items():
                for j, nme in enumerate(names):
                    if nme in converted:
                        names[j] = converted[nme]
        program._bump_version()
        return program
