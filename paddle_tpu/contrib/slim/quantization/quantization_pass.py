"""Quantization-aware-training transform.

TPU-native re-design of the reference's QuantizationTransformPass
(/root/reference/python/paddle/fluid/contrib/slim/quantization/
quantization_pass.py:35): walk the program, and for each quantizable op
(conv2d/depthwise_conv2d/mul/matmul) insert fused quantize-dequantize ops on
its weight (abs_max) and activation input (moving-average abs_max). The
reference rewires an IrGraph; here the Program IR is rewritten directly —
the inserted ops carry straight-through gradients so minimize() after the
pass trains quantization-aware, and XLA folds the q/dq arithmetic into the
surrounding matmul at compile time.
"""
from __future__ import annotations

from ....framework import default_main_program

_QUANTIZABLE = ("conv2d", "depthwise_conv2d", "mul", "matmul")


class QuantizationTransformPass:
    """reference quantization_pass.py:35 (weight abs_max + activation
    moving_average_abs_max, the default W8A8 config)."""

    def __init__(self, scope=None, place=None, weight_bits=8,
                 activation_bits=8, moving_rate=0.9,
                 quantizable_op_type=_QUANTIZABLE,
                 skip_pattern="skip_quant"):
        self._weight_bits = weight_bits
        self._activation_bits = activation_bits
        self._moving_rate = moving_rate
        self._types = tuple(quantizable_op_type)
        self._skip = skip_pattern

    def apply(self, program=None, startup_program=None, for_test=False):
        """Insert q/dq ops in front of every quantizable op (mutates and
        returns `program`). Run BEFORE minimize() so the backward pass
        differentiates through the straight-through estimators."""
        program = program or default_main_program()
        block = program.global_block
        params = {p.name for p in program.all_parameters()}
        quantized: dict[str, str] = {}  # original name -> q/dq output name

        for op in list(block.ops):
            if op.type not in self._types or op.attrs.get(self._skip):
                continue
            for slot, names in op.inputs.items():
                for i, n in enumerate(names):
                    if n in quantized:
                        names[i] = quantized[n]
                        continue
                    try:
                        var = block.var(n)
                    except KeyError:
                        continue
                    if var.dtype.value not in ("float32", "bfloat16",
                                               "float16"):
                        continue
                    idx = block.ops.index(op)
                    q = self._insert_qdq(block, idx, var,
                                         is_weight=n in params,
                                         for_test=for_test,
                                         startup_program=startup_program)
                    quantized[n] = q
                    names[i] = q
        program._bump_version()
        return program

    def _insert_qdq(self, block, idx, var, is_weight, for_test,
                    startup_program=None):
        from .... import unique_name

        out = block.create_var(
            name=unique_name.generate(var.name + ".quantized"),
            shape=var.shape, dtype=var.dtype)
        if is_weight:
            scale = block.create_var(
                name=unique_name.generate(var.name + ".scale"),
                shape=(1,), dtype="float32")
            block._insert_op(
                idx, "fake_quantize_dequantize_abs_max",
                {"X": [var.name]},
                {"Out": [out.name], "OutScale": [scale.name]},
                {"bit_length": self._weight_bits})
        else:
            # moving-average activation scale: persistable running state,
            # zero-initialized by the STARTUP program (re-filling it in the
            # main program would reset the average every step). Bound to the
            # PASSED programs — LayerHelper would silently target the
            # defaults when apply() is given explicit programs.
            from ....framework import default_startup_program

            state = block.create_var(
                name=unique_name.generate(var.name + ".ma_scale"),
                shape=(1,), dtype="float32", persistable=True)
            sp = startup_program or default_startup_program()
            sblk = sp.global_block
            sblk.create_var(name=state.name, shape=(1,), dtype="float32",
                            persistable=True)
            sblk.append_op(
                "fill_constant", {}, {"Out": [state.name]},
                {"shape": [1], "dtype": "float32", "value": 0.0})
            block._insert_op(
                idx, "fake_quantize_dequantize_moving_average_abs_max",
                {"X": [var.name], "InScale": [state.name]},
                {"Out": [out.name], "OutScale": [state.name]},
                {"bit_length": self._activation_bits,
                 "moving_rate": self._moving_rate, "is_test": for_test})
        return out.name
