"""Light-NAS: simulated-annealing architecture search (reference
python/paddle/fluid/contrib/slim/nas/light_nas_strategy.py +
slim/searcher/controller.py SAController).

The reference splits the search across a controller server and client
agents (controller_server.py / search_agent.py) because its trials run in
separate GPU processes; here a trial is one jit-compiled short training
run on the chip, so the whole loop lives in-process — the controller
logic (Metropolis acceptance over a token range table, reference
controller.py:105) is reproduced exactly.

Contract:
  * a SearchSpace gives `init_tokens()`, `range_table()` (tokens[i] in
    [0, range_table[i])), and `eval_tokens(tokens) -> (reward, flops)`;
  * `LightNASStrategy.search()` anneals and returns the best tokens seen,
    honoring `max_flops` through the controller's constraint hook.
"""
from __future__ import annotations

import math

import numpy as np

__all__ = ["SAController", "LightNASStrategy"]


class SAController:
    """Simulated-annealing evolutionary controller (reference
    slim/searcher/controller.py SAController)."""

    def __init__(self, range_table=None, reduce_rate=0.85,
                 init_temperature=1024.0, max_iter_number=300, seed=None):
        self._range_table = range_table
        self._reduce_rate = float(reduce_rate)
        self._init_temperature = float(init_temperature)
        self._max_iter_number = int(max_iter_number)
        self._reward = -1.0
        self._tokens = None
        self._max_reward = -1.0
        self._best_tokens = None
        self._iter = 0
        self._constrain_func = None
        self._rng = np.random.default_rng(seed)

    def reset(self, range_table, init_tokens, constrain_func=None):
        self._range_table = list(range_table)
        self._tokens = list(init_tokens)
        self._constrain_func = constrain_func
        self._iter = 0

    def update(self, tokens, reward):
        """Metropolis acceptance at geometrically cooling temperature."""
        self._iter += 1
        temperature = self._init_temperature * \
            self._reduce_rate ** self._iter
        if (reward > self._reward) or (self._rng.random() <= math.exp(
                min((reward - self._reward) / max(temperature, 1e-9), 50))):
            self._reward = reward
            self._tokens = list(tokens)
        if reward > self._max_reward:
            self._max_reward = reward
            self._best_tokens = list(tokens)

    def next_tokens(self):
        """Mutate one random position; retry until the constraint admits
        the candidate (reference next_tokens loop)."""
        for _ in range(1000):
            tokens = list(self._tokens)
            pos = int(self._rng.integers(len(tokens)))
            # reference offset-mod formula: the mutation ALWAYS lands on a
            # different value, so no trial evaluates an unchanged
            # architecture (ADVICE r4; degenerate range 1 keeps the value)
            r = self._range_table[pos]
            if r > 1:
                tokens[pos] = (tokens[pos]
                               + int(self._rng.integers(r - 1)) + 1) % r
            if self._constrain_func is None or self._constrain_func(tokens):
                return tokens
        raise RuntimeError("SAController: constraint rejected 1000 "
                           "consecutive candidates")

    @property
    def best_tokens(self):
        return self._best_tokens

    @property
    def max_reward(self):
        return self._max_reward


class LightNASStrategy:
    """The search driver (reference light_nas_strategy.py, in-process).

    search_space must provide:
      init_tokens() -> list[int]
      range_table() -> list[int]
      eval_tokens(tokens) -> (reward: float, flops: float)
    """

    def __init__(self, search_space, max_flops=None, search_steps=50,
                 reduce_rate=0.85, init_temperature=1024.0, seed=None):
        self.space = search_space
        self.max_flops = max_flops
        self.search_steps = int(search_steps)
        self.controller = SAController(
            reduce_rate=reduce_rate, init_temperature=init_temperature,
            max_iter_number=search_steps, seed=seed)
        self._flops_cache: dict = {}

    def _admit(self, tokens):
        if self.max_flops is None:
            return True
        key = tuple(tokens)
        if key not in self._flops_cache:
            self._flops_cache[key] = float(self.space.flops(tokens))
        return self._flops_cache[key] <= self.max_flops

    def search(self):
        """Run the annealed search; returns (best_tokens, best_reward)."""
        init = self.space.init_tokens()
        constrain = self._admit if (self.max_flops is not None
                                    and hasattr(self.space, "flops")) \
            else None
        self.controller.reset(self.space.range_table(), init, constrain)
        reward, _ = self.space.eval_tokens(init)
        self.controller.update(init, reward)
        for _ in range(self.search_steps):
            tokens = self.controller.next_tokens()
            reward, _ = self.space.eval_tokens(tokens)
            self.controller.update(tokens, reward)
        return self.controller.best_tokens, self.controller.max_reward
