"""Model-compression toolkit (reference python/paddle/fluid/contrib/slim/):
quantization (QAT/PTQ/freeze/int8), magnitude pruning, distillation losses.
Light-NAS is out of scope (the reference's evolutionary searcher is an
experiment driver, not a framework capability)."""
from . import distillation  # noqa: F401
from . import prune  # noqa: F401
from . import quantization  # noqa: F401
from . import nas  # noqa: F401
