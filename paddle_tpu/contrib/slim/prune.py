"""Magnitude pruning: one-shot weight masking + mask re-application during
training, and a loss-sensitivity sweep to pick per-parameter ratios.

TPU-native re-design of the reference's pruning strategies
(/root/reference/python/paddle/fluid/contrib/slim/prune/:
prune_strategy.py SensitivePruneStrategy, pruner.py StructurePruner): the
reference prunes whole filters through a graph wrapper; here the same two
ingredients operate on the Program IR directly —

  * `MagnitudePruner.prune_weights` zeroes the lowest-|w| entries (or whole
    output columns/filters in structured mode) and stores a persistable
    `<p>@prune_mask` in the scope;
  * `MagnitudePruner.apply` additionally appends `p = p * mask` after the
    program's optimizer ops, so SGD steps cannot resurrect pruned weights —
    the reference's "mask backward" trick expressed as a program transform
    (XLA fuses the multiply into the update);
  * `sensitivity` measures eval-metric degradation per (param, ratio) — the
    reference's SensitivePruneStrategy probe — so callers can budget ratios.
"""
from __future__ import annotations

import numpy as np

from ...framework import default_main_program

__all__ = ["MagnitudePruner", "sensitivity"]


class MagnitudePruner:
    def __init__(self, structured: bool = False):
        # structured=True prunes whole output columns (axis -1 groups, the
        # fc/conv filter analogue) by their L2 norm; False prunes elements
        self.structured = structured

    def _mask(self, w: np.ndarray, ratio: float) -> np.ndarray:
        if ratio <= 0:
            return np.ones_like(w, dtype=np.float32)
        # rank-based selection prunes EXACTLY k entries: a magnitude
        # threshold would overshoot on ties (e.g. many exact zeros, or a
        # constant tensor pruning to nothing)
        if self.structured and w.ndim >= 2:
            norms = np.sqrt((w.astype(np.float64) ** 2).reshape(
                -1, w.shape[-1]).sum(axis=0))
            k = int(np.floor(ratio * norms.size))
            if k == 0:
                return np.ones_like(w, dtype=np.float32)
            col_mask = np.ones(norms.size, np.float32)
            col_mask[np.argpartition(norms, k - 1)[:k]] = 0.0
            return np.broadcast_to(col_mask, w.shape).astype(np.float32)
        flat = np.abs(w).reshape(-1)
        k = int(np.floor(ratio * flat.size))
        if k == 0:
            return np.ones_like(w, dtype=np.float32)
        mask = np.ones(flat.size, np.float32)
        mask[np.argpartition(flat, k - 1)[:k]] = 0.0
        return mask.reshape(w.shape)

    def prune_weights(self, scope, params, ratios) -> dict:
        """Zero the masked entries in the SCOPE; returns {param: mask}.
        `ratios` is a float (uniform) or {param: float}."""
        masks = {}
        for p in params:
            r = ratios[p] if isinstance(ratios, dict) else float(ratios)
            w = np.asarray(scope.find_var(p))
            m = self._mask(w, r)
            scope.set_var(p, (w * m).astype(w.dtype))
            scope.set_var(p + "@prune_mask", m)
            masks[p] = m
        return masks

    def apply(self, params, ratios, scope=None, program=None):
        """prune_weights + keep-pruned-through-training: appends
        `p = elementwise_mul(p, mask)` ops AFTER the existing program ops
        (i.e. after the optimizer update), so each step re-zeroes."""
        from ...executor import global_scope

        scope = scope or global_scope()
        program = program or default_main_program()
        masks = self.prune_weights(scope, params, ratios)
        block = program.global_block
        for p in params:
            mname = p + "@prune_mask"
            if not block.has_var(mname):
                v = block.var(p)
                block.create_var(name=mname, shape=v.shape, dtype="float32",
                                 persistable=True)
            block.append_op("elementwise_mul", {"X": [p], "Y": [mname]},
                            {"Out": [p]}, {"axis": -1})
        program._bump_version()
        return masks


def sensitivity(program, scope, exe, params, eval_fn, ratios=(0.1, 0.3, 0.5),
                pruner: MagnitudePruner | None = None) -> dict:
    """Per-(param, ratio) eval degradation (reference
    SensitivePruneStrategy's sensitivity probe): prunes ONE param at a time
    in a scratch copy of its value, calls `eval_fn() -> float` (higher =
    better), restores, returns {param: {ratio: metric}}."""
    pruner = pruner or MagnitudePruner()
    out: dict = {}
    for p in params:
        orig = np.asarray(scope.find_var(p)).copy()
        out[p] = {}
        for r in ratios:
            m = pruner._mask(orig, float(r))
            scope.set_var(p, (orig * m).astype(orig.dtype))
            out[p][float(r)] = float(eval_fn())
        scope.set_var(p, orig)
    return out
