"""Knowledge-distillation loss builders.

TPU-native re-design of the reference distillation strategies
(/root/reference/python/paddle/fluid/contrib/slim/distillation/:
distillation_strategy.py + distiller.py FSPDistiller, L2Distiller,
SoftLabelDistiller): the reference merges teacher/student graphs through a
GraphWrapper; here both towers are built in ONE program (freeze the teacher
with stop_gradient / excluded parameter_list) and these helpers append the
distillation losses as ordinary layers.
"""
from __future__ import annotations

from ... import layers as L

__all__ = ["soft_label_loss", "l2_distill_loss", "fsp_matrix", "fsp_loss"]


def soft_label_loss(teacher_logits, student_logits,
                    teacher_temperature=1.0, student_temperature=1.0):
    """KL-style soft-label loss (reference distiller.py SoftLabelDistiller):
    mean cross-entropy of softened student predictions against softened
    teacher probabilities."""
    t = L.softmax(L.scale(teacher_logits, scale=1.0 / teacher_temperature))
    t.stop_gradient = True  # the teacher is a fixed target
    s = L.scale(student_logits, scale=1.0 / student_temperature)
    return L.mean(L.cross_entropy(L.softmax(s), t, soft_label=True))


def l2_distill_loss(teacher_feature, student_feature):
    """Feature-map L2 matching (reference distiller.py L2Distiller)."""
    diff = L.elementwise_sub(student_feature, teacher_feature)
    return L.mean(L.elementwise_mul(diff, diff))


def fsp_matrix(a, b):
    """Flow-of-solution-procedure matrix (reference fsp op /
    distiller.py FSPDistiller): a [B, C1, H, W] x b [B, C2, H, W] ->
    [B, C1, C2] = (a_flat @ b_flat^T) / (H*W). Built from existing
    reshape/matmul ops — no bespoke kernel needed."""
    B_, C1, H, W = -1, a.shape[1], a.shape[2], a.shape[3]
    C2 = b.shape[1]
    af = L.reshape(a, [-1, C1, H * W])
    bf = L.reshape(b, [-1, C2, H * W])
    return L.scale(L.matmul(af, bf, transpose_y=True), scale=1.0 / (H * W))


def fsp_loss(teacher_pair, student_pair):
    """L2 between teacher and student FSP matrices; each pair is
    (feature_in, feature_out) of a section with equal spatial dims."""
    tm = fsp_matrix(*teacher_pair)
    tm.stop_gradient = True
    sm = fsp_matrix(*student_pair)
    return l2_distill_loss(tm, sm)
