"""Tensor-creation layers + the `data` input declaration.

Reference: /root/reference/python/paddle/fluid/layers/tensor.py and
layers/io.py (`data`:45).
"""
from __future__ import annotations

from ..core.types import DType
from ..framework import default_main_program, default_startup_program
from ..layer_helper import LayerHelper

__all__ = [
    "data",
    "fill_constant",
    "zeros",
    "ones",
    "assign",
    "create_tensor",
    "create_global_var",
    "fill_constant_batch_size_like",
    "zeros_like",
    "ones_like",
    "linspace",
    "range",
    "uniform_random",
    "gaussian_random",
]


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=True, stop_gradient=True):
    """Declare a feed input (reference layers/io.py:45). With
    append_batch_size=True a leading -1 batch dim is added; each concrete batch
    size becomes one XLA compile-cache entry."""
    if append_batch_size:
        shape = [-1] + list(shape)
    block = default_main_program().current_block()
    return block.create_var(
        name=name,
        shape=shape,
        dtype=dtype,
        is_data=True,
        stop_gradient=stop_gradient,
    )


def fill_constant(shape, dtype, value, out=None, name=None):
    helper = LayerHelper("fill_constant", name=name)
    if out is None:
        out = helper.create_variable_for_type_inference(DType.parse(dtype))
    helper.append_op(
        "fill_constant",
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": DType.parse(dtype).value, "value": float(value)},
    )
    return out


def fill_constant_batch_size_like(input, shape, dtype, value, input_dim_idx=0, output_dim_idx=0):
    helper = LayerHelper("fill_constant_batch_size_like")
    out = helper.create_variable_for_type_inference(DType.parse(dtype))
    helper.append_op(
        "fill_constant_batch_size_like",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={
            "shape": list(shape),
            "dtype": DType.parse(dtype).value,
            "value": float(value),
            "input_dim_idx": input_dim_idx,
            "output_dim_idx": output_dim_idx,
        },
    )
    return out


def zeros(shape, dtype="float32", name=None):
    return fill_constant(shape, dtype, 0.0, name=name)


def ones(shape, dtype="float32", name=None):
    return fill_constant(shape, dtype, 1.0, name=name)


def zeros_like(x, out=None):
    helper = LayerHelper("fill_zeros_like")
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("fill_zeros_like", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def ones_like(x, out=None):
    helper = LayerHelper("fill_any_like")
    if out is None:
        out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "fill_any_like", inputs={"X": [x]}, outputs={"Out": [out]}, attrs={"value": 1.0}
    )
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    from ..framework import Variable
    import numpy as np

    if isinstance(input, Variable):
        if output is None:
            output = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op("assign", inputs={"X": [input]}, outputs={"Out": [output]})
    else:
        arr = np.asarray(input)
        if output is None:
            output = helper.create_variable_for_type_inference(DType.parse(arr.dtype))
        helper.append_op(
            "assign_value",
            outputs={"Out": [output]},
            attrs={
                "shape": list(arr.shape),
                "dtype": DType.parse(arr.dtype).value,
                "values": arr.reshape(-1).tolist(),
            },
        )
    return output


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.create_global_variable(
        shape=[1], dtype=dtype, persistable=persistable, name=name
    )


def create_global_var(shape, value, dtype, persistable=False, force_cpu=False, name=None):
    from ..initializer import Constant

    helper = LayerHelper("global_var", name=name)
    return helper.create_or_get_global_variable(
        name or helper.name,
        shape,
        dtype,
        persistable=persistable,
        initializer=Constant(value),
    )


def linspace(start, stop, num, dtype="float32"):
    helper = LayerHelper("linspace")
    out = helper.create_variable_for_type_inference(DType.parse(dtype))
    helper.append_op(
        "linspace",
        outputs={"Out": [out]},
        attrs={
            "start": float(start),
            "stop": float(stop),
            "num": int(num),
            "dtype": DType.parse(dtype).value,
        },
    )
    return out


def range(start, end, step, dtype="int64"):
    helper = LayerHelper("range")
    out = helper.create_variable_for_type_inference(DType.parse(dtype))
    helper.append_op(
        "range",
        outputs={"Out": [out]},
        attrs={
            "start": float(start),
            "end": float(end),
            "step": float(step),
            "dtype": DType.parse(dtype).value,
        },
    )
    return out


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0,
                   name=None):
    """reference layers.uniform_random — counter-based PRNG under jit."""
    helper = LayerHelper("uniform_random", name=name)
    out = helper.create_variable_for_type_inference(DType.parse(dtype))
    helper.append_op(
        "uniform_random", outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": DType.parse(dtype).value,
               "min": float(min), "max": float(max), "seed": int(seed)})
    return out


def gaussian_random(shape, dtype="float32", mean=0.0, std=1.0, seed=0,
                    name=None):
    """reference layers.gaussian_random."""
    helper = LayerHelper("gaussian_random", name=name)
    out = helper.create_variable_for_type_inference(DType.parse(dtype))
    helper.append_op(
        "gaussian_random", outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": DType.parse(dtype).value,
               "mean": float(mean), "std": float(std), "seed": int(seed)})
    return out
