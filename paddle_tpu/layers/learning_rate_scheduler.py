"""Learning-rate schedules as in-program ops over a global step counter.

Reference: /root/reference/python/paddle/fluid/layers/learning_rate_scheduler.py
(noam_decay:40, exponential_decay:75, natural_exp_decay:114, inverse_time_decay
:151, polynomial_decay:190, piecewise_decay:243, cosine_decay:295,
linear_lr_warmup:324). Same contract: call before optimizer construction, pass
the returned Variable as `learning_rate`. The schedule math is ordinary ops in
the main program, computed from a persistable step counter incremented once
per executor run — so it compiles into the same XLA block as the train step.
"""
from __future__ import annotations

import math

from ..framework import default_main_program
from ..initializer import Constant
from ..layer_helper import LayerHelper
from . import nn as L
from . import tensor as T

__all__ = [
    "noam_decay", "exponential_decay", "natural_exp_decay",
    "inverse_time_decay", "polynomial_decay", "piecewise_decay",
    "cosine_decay", "linear_lr_warmup",
]

_COUNTER_NAME = "@LR_DECAY_COUNTER@"


def _unary(op_type, x):
    helper = LayerHelper(op_type)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(op_type, {"X": [x]}, {"Out": [out]}, {})
    return out


def _floor(x):
    return _unary("floor", x)


def _ceil(x):
    return _unary("ceil", x)


def _reciprocal(x):
    return _unary("reciprocal", x)


def _cos(x):
    return _unary("cos", x)


def _less_than(x, y):
    helper = LayerHelper("less_than")
    out = helper.create_variable_for_type_inference("bool")
    helper.append_op("less_than", {"X": [x], "Y": [y]}, {"Out": [out]}, {})
    return out


def _decay_step_counter(begin: int = 0):
    """Auto-incremented float32 step counter (reference
    layers/tensor.py autoincreased_step_counter)."""
    helper = LayerHelper("global_step_counter")
    program = default_main_program()
    existed = _COUNTER_NAME in program.global_block.vars
    if existed:
        prev_begin = getattr(program.global_block.vars[_COUNTER_NAME],
                             "_lr_counter_begin", begin)
        if prev_begin != begin:
            raise ValueError(
                f"schedulers with different step-counter origins (begin="
                f"{prev_begin} vs {begin}) cannot share one program: the "
                f"shared {_COUNTER_NAME} would be off by one for one of them "
                f"(noam_decay starts at 1, other schedules at 0)"
            )
    # init to begin-1: the in-graph increment runs before first use, so the
    # first executed step sees `begin` (reference autoincreased_step_counter)
    counter = helper.create_or_get_global_variable(
        _COUNTER_NAME, [1], "float32", initializer=Constant(float(begin) - 1.0)
    )
    counter._lr_counter_begin = begin
    if not existed:
        # one increment per program, however many schedulers share the counter
        # (composed schedules like linear_lr_warmup(piecewise_decay(...)) must
        # not double-step)
        helper.append_op("increment", {"X": [counter]}, {"Out": [counter]}, {"step": 1.0})
    return counter


def noam_decay(d_model, warmup_steps):
    step = _decay_step_counter(1)
    a = L.pow(step, -0.5)
    b = L.scale(step, scale=float(warmup_steps) ** -1.5)
    return L.scale(L.elementwise_min(a, b), scale=float(d_model) ** -0.5)


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _decay_step_counter()
    ratio = L.scale(step, scale=1.0 / decay_steps)
    if staircase:
        ratio = _floor(ratio)
    return L.scale(L.elementwise_pow(T.fill_constant([1], "float32", float(decay_rate)), ratio),
                   scale=float(learning_rate))


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _decay_step_counter()
    ratio = L.scale(step, scale=1.0 / decay_steps)
    if staircase:
        ratio = _floor(ratio)
    return L.scale(L.exp(L.scale(ratio, scale=-float(decay_rate))),
                   scale=float(learning_rate))


def inverse_time_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _decay_step_counter()
    ratio = L.scale(step, scale=1.0 / decay_steps)
    if staircase:
        ratio = _floor(ratio)
    denom = L.scale(ratio, scale=float(decay_rate), bias=1.0)
    return L.scale(_reciprocal(denom), scale=float(learning_rate))


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=1e-4,
                     power=1.0, cycle=False):
    step = _decay_step_counter()
    if cycle:
        div = _ceil(L.scale(step, scale=1.0 / decay_steps))
        # at step 0 ceil(0)=0 -> use 1 (reference zero_var/one_var dance)
        div = L.elementwise_max(div, T.fill_constant([1], "float32", 1.0))
        decay_var = L.scale(div, scale=float(decay_steps))
    else:
        decay_var = T.fill_constant([1], "float32", float(decay_steps))
        step = L.elementwise_min(step, decay_var)
    frac = L.elementwise_div(step, decay_var)
    base = L.pow(L.scale(frac, scale=-1.0, bias=1.0), float(power))
    return L.scale(base, scale=float(learning_rate) - float(end_learning_rate),
                   bias=float(end_learning_rate))


def piecewise_decay(boundaries, values):
    if len(values) - len(boundaries) != 1:
        raise ValueError("len(values) must be len(boundaries) + 1")
    step = _decay_step_counter()
    helper = LayerHelper("piecewise_decay")
    lr = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "piecewise_decay", {"Step": [step]}, {"Out": [lr]},
        {"boundaries": [float(b) for b in boundaries],
         "values": [float(v) for v in values]},
    )
    return lr


def cosine_decay(learning_rate, step_each_epoch, epochs):
    step = _decay_step_counter()
    epoch = _floor(L.scale(step, scale=1.0 / step_each_epoch))
    cosv = _cos(L.scale(epoch, scale=math.pi / epochs))
    return L.scale(cosv, scale=0.5 * float(learning_rate),
                   bias=0.5 * float(learning_rate))


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    """Linear warmup from start_lr to end_lr over warmup_steps, then the wrapped
    schedule (float or Variable)."""
    step = _decay_step_counter()
    if not isinstance(learning_rate, L.Variable):
        learning_rate = T.fill_constant([1], "float32", float(learning_rate))
    frac = L.elementwise_min(L.scale(step, scale=1.0 / warmup_steps),
                             T.fill_constant([1], "float32", 1.0))
    warm = L.scale(frac, scale=float(end_lr) - float(start_lr), bias=float(start_lr))
    in_warmup = L.cast(_less_than(step, T.fill_constant([1], "float32", float(warmup_steps))),
                       "float32")
    a = L.elementwise_mul(warm, in_warmup)
    b = L.elementwise_mul(learning_rate, L.scale(in_warmup, scale=-1.0, bias=1.0))
    return L.elementwise_add(a, b)
