"""Detection layers (reference python/paddle/fluid/layers/detection.py:
prior_box:1500, box_coder:704, iou_similarity:660, multiclass_nms:2127,
detection_output:160) on the padding contract — NMS output is a fixed
[N, keep_top_k, 6] tensor with label -1 padding instead of a LoD tensor.
"""
from __future__ import annotations

from ..layer_helper import LayerHelper

__all__ = ["prior_box", "box_coder", "iou_similarity", "multiclass_nms",
           "detection_output", "ssd_loss", "bipartite_match",
           "yolo_box", "yolov3_loss", "anchor_generator",
           "density_prior_box", "generate_proposals", "psroi_pool"]


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=None,
              variance=None, flip=False, clip=False, steps=None, offset=0.5,
              name=None):
    """reference detection.py:1500 -> (boxes [H,W,P,4], variances)."""
    helper = LayerHelper("prior_box", name=name)
    boxes = helper.create_variable_for_type_inference("float32")
    var = helper.create_variable_for_type_inference("float32")
    steps = steps or [0.0, 0.0]
    helper.append_op(
        "prior_box", {"Input": [input], "Image": [image]},
        {"Boxes": [boxes], "Variances": [var]},
        {"min_sizes": list(min_sizes),
         "max_sizes": list(max_sizes or []),
         "aspect_ratios": list(aspect_ratios or [1.0]),
         "variances": list(variance or [0.1, 0.1, 0.2, 0.2]),
         "flip": flip, "clip": clip,
         "step_w": float(steps[0]), "step_h": float(steps[1]),
         "offset": offset})
    return boxes, var


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              name=None, axis=0):
    """reference detection.py:704."""
    if axis != 0:
        raise NotImplementedError(
            "box_coder: only axis=0 (priors broadcast along dim 0) is "
            "supported")
    helper = LayerHelper("box_coder", name=name)
    out = helper.create_variable_for_type_inference("float32")
    ins = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    if prior_box_var is not None:
        ins["PriorBoxVar"] = [prior_box_var]
    helper.append_op("box_coder", ins, {"OutputBox": [out]},
                     {"code_type": code_type,
                      "box_normalized": box_normalized})
    return out


def iou_similarity(x, y, name=None):
    """reference detection.py:660 — pairwise IoU [N, M]."""
    helper = LayerHelper("iou_similarity", name=name)
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op("iou_similarity", {"X": [x], "Y": [y]},
                     {"Out": [out]}, {})
    return out


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                   nms_threshold=0.3, normalized=True, nms_eta=1.0,
                   background_label=0, name=None):
    """reference detection.py:2127 — output [N, keep_top_k, 6] rows of
    (label, score, x1, y1, x2, y2); label -1 marks padding."""
    if nms_eta != 1.0:
        raise NotImplementedError(
            "multiclass_nms: adaptive NMS (nms_eta != 1.0) is not supported")
    helper = LayerHelper("multiclass_nms", name=name)
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "multiclass_nms", {"BBoxes": [bboxes], "Scores": [scores]},
        {"Out": [out]},
        {"score_threshold": float(score_threshold),
         "nms_top_k": int(nms_top_k), "keep_top_k": int(keep_top_k),
         "nms_threshold": float(nms_threshold),
         "normalized": bool(normalized),
         "background_label": int(background_label)})
    return out


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0):
    """reference detection.py:160 — decode SSD locations against priors then
    multiclass NMS. loc [N, M, 4] offsets, scores [N, C, M] (softmaxed)."""
    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size")
    return multiclass_nms(decoded, scores, score_threshold, nms_top_k,
                          keep_top_k, nms_threshold, nms_eta=nms_eta,
                          background_label=background_label)


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mining_type="max_negative", normalize=True, sample_size=None,
             gt_count=None):
    """reference detection.py:1280 — SSD multibox loss on padded ground
    truth: gt_box [N, G, 4] + gt_label [N, G, 1] + optional gt_count [N]
    valid rows (the LoD walk). Returns per-image loss [N, 1]."""
    if match_type != "per_prediction" or mining_type != "max_negative":
        raise NotImplementedError(
            "ssd_loss supports match_type='per_prediction' with "
            "mining_type='max_negative' (the reference defaults)")
    if sample_size is not None:
        raise NotImplementedError("ssd_loss: sample_size is not supported")
    helper = LayerHelper("ssd_loss")
    out = helper.create_variable_for_type_inference("float32")
    ins = {"Loc": [location], "Conf": [confidence], "GTBox": [gt_box],
           "GTLabel": [gt_label], "PriorBox": [prior_box]}
    if prior_box_var is not None:
        ins["PriorBoxVar"] = [prior_box_var]
    if gt_count is not None:
        ins["GTCount"] = [gt_count]
    helper.append_op(
        "ssd_loss", ins, {"Loss": [out]},
        {"background_label": int(background_label),
         "overlap_threshold": float(overlap_threshold),
         "neg_overlap": float(neg_overlap),
         "neg_pos_ratio": float(neg_pos_ratio),
         "loc_loss_weight": float(loc_loss_weight),
         "conf_loss_weight": float(conf_loss_weight),
         "normalize": bool(normalize)})
    return out


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    """reference detection.py bipartite_match / bipartite_match_op.cc."""
    helper = LayerHelper("bipartite_match", name=name)
    idx = helper.create_variable_for_type_inference("int32")
    d = helper.create_variable_for_type_inference(dist_matrix.dtype)
    helper.append_op(
        "bipartite_match", {"DistMat": [dist_matrix]},
        {"ColToRowMatchIndices": [idx], "ColToRowMatchDist": [d]},
        {"match_type": match_type or "bipartite",
         "dist_threshold": float(dist_threshold or 0.5)})
    return idx, d


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, name=None):
    """reference detection.py yolo_box / detection/yolo_box_op.cc."""
    helper = LayerHelper("yolo_box", name=name)
    boxes = helper.create_variable_for_type_inference(x.dtype)
    scores = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "yolo_box", {"X": [x], "ImgSize": [img_size]},
        {"Boxes": [boxes], "Scores": [scores]},
        {"anchors": [int(a) for a in anchors], "class_num": int(class_num),
         "conf_thresh": float(conf_thresh),
         "downsample_ratio": int(downsample_ratio)})
    return boxes, scores


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, name=None):
    """reference detection.py yolov3_loss / detection/yolov3_loss_op.h."""
    helper = LayerHelper("yolov3_loss", name=name)
    loss = helper.create_variable_for_type_inference(x.dtype)
    obj_mask = helper.create_variable_for_type_inference(x.dtype)
    gt_match = helper.create_variable_for_type_inference("int32")
    ins = {"X": [x], "GTBox": [gt_box], "GTLabel": [gt_label]}
    if gt_score is not None:
        ins["GTScore"] = [gt_score]
    helper.append_op(
        "yolov3_loss", ins,
        {"Loss": [loss], "ObjectnessMask": [obj_mask],
         "GTMatchMask": [gt_match]},
        {"anchors": [int(a) for a in anchors],
         "anchor_mask": [int(m) for m in anchor_mask],
         "class_num": int(class_num),
         "ignore_thresh": float(ignore_thresh),
         "downsample_ratio": int(downsample_ratio),
         "use_label_smooth": bool(use_label_smooth)})
    return loss


def anchor_generator(input, anchor_sizes=None, aspect_ratios=None,
                     variance=None, stride=None, offset=0.5, name=None):
    """reference detection.py anchor_generator."""
    helper = LayerHelper("anchor_generator", name=name)
    anchors = helper.create_variable_for_type_inference(input.dtype)
    variances = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "anchor_generator", {"Input": [input]},
        {"Anchors": [anchors], "Variances": [variances]},
        {"anchor_sizes": [float(s) for s in (anchor_sizes or [64, 128, 256,
                                                              512])],
         "aspect_ratios": [float(r) for r in (aspect_ratios or [0.5, 1.0,
                                                                2.0])],
         "variances": [float(v) for v in (variance or [0.1, 0.1, 0.2, 0.2])],
         "stride": [float(s) for s in (stride or [16.0, 16.0])],
         "offset": float(offset)})
    anchors.stop_gradient = True
    variances.stop_gradient = True
    return anchors, variances


def density_prior_box(input, image, densities=None, fixed_sizes=None,
                      fixed_ratios=None, variance=None, clip=False,
                      steps=None, offset=0.5, flatten_to_2d=False,
                      name=None):
    """reference detection.py density_prior_box."""
    helper = LayerHelper("density_prior_box", name=name)
    boxes = helper.create_variable_for_type_inference(input.dtype)
    var = helper.create_variable_for_type_inference(input.dtype)
    steps = steps or [0.0, 0.0]
    helper.append_op(
        "density_prior_box", {"Input": [input], "Image": [image]},
        {"Boxes": [boxes], "Variances": [var]},
        {"densities": [int(d) for d in (densities or [])],
         "fixed_sizes": [float(s) for s in (fixed_sizes or [])],
         "fixed_ratios": [float(r) for r in (fixed_ratios or [])],
         "variances": [float(v) for v in (variance or [0.1, 0.1, 0.2,
                                                       0.2])],
         "clip": bool(clip), "step_w": float(steps[0]),
         "step_h": float(steps[1]), "offset": float(offset),
         "flatten_to_2d": bool(flatten_to_2d)})
    boxes.stop_gradient = True
    var.stop_gradient = True
    return boxes, var


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0, name=None):
    """reference detection.py generate_proposals."""
    helper = LayerHelper("generate_proposals", name=name)
    rois = helper.create_variable_for_type_inference(scores.dtype)
    roi_probs = helper.create_variable_for_type_inference(scores.dtype)
    helper.append_op(
        "generate_proposals",
        {"Scores": [scores], "BboxDeltas": [bbox_deltas],
         "ImInfo": [im_info], "Anchors": [anchors],
         "Variances": [variances]},
        {"RpnRois": [rois], "RpnRoiProbs": [roi_probs]},
        {"pre_nms_topN": int(pre_nms_top_n),
         "post_nms_topN": int(post_nms_top_n),
         "nms_thresh": float(nms_thresh), "min_size": float(min_size),
         "eta": float(eta)})
    rois.stop_gradient = True
    roi_probs.stop_gradient = True
    return rois, roi_probs


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, rois_batch=None, name=None):
    """reference nn.py psroi_pool / psroi_pool_op.h."""
    helper = LayerHelper("psroi_pool", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {"X": [input], "ROIs": [rois]}
    if rois_batch is not None:
        ins["RoisBatch"] = [rois_batch]
    helper.append_op(
        "psroi_pool", ins, {"Out": [out]},
        {"output_channels": int(output_channels),
         "spatial_scale": float(spatial_scale),
         "pooled_height": int(pooled_height),
         "pooled_width": int(pooled_width)})
    return out
