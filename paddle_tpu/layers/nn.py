"""Layers DSL — each function appends ops to the default main program.

TPU-native re-design of /root/reference/python/paddle/fluid/layers/nn.py
(fc:228, embedding, conv2d, pool2d, batch_norm, layer_norm, dropout, softmax,
cross_entropy, softmax_with_cross_entropy, reduce_*, elementwise_*, matmul,
topk, accuracy) — same public signatures, new lowering (each op is a JAX
compute traced into one XLA block; see ops/).
"""
from __future__ import annotations

import numpy as np

from ..core.types import DType
from ..framework import Variable
from ..initializer import Constant, Normal, Xavier
from ..layer_helper import LayerHelper
from ..param_attr import ParamAttr

__all__ = [
    "uniform_random_batch_size_like",
    "row_conv",
    "spectral_norm",
    "data_norm",
    "center_loss",
    "npair_loss",
    "teacher_student_sigmoid_loss",
    "cross_entropy2",
    "sampled_softmax_with_cross_entropy",
    "unique",
    "unique_with_counts",
    "hash",
    "continuous_value_model",
    "merge_selected_rows",
    "get_tensor_from_selected_rows",
    "filter_by_instag",
    "autoincreased_step_counter",
    "py_func",
    "lstm_unit",
    "lstm",
    "dynamic_lstmp",
    "edit_distance",
    "ctc_greedy_decoder",
    "chunk_eval",
    "match_matrix_tensor",
    "tree_conv",
    "affine_grid",
    "im2sequence",
    "random_crop",
    "resize_trilinear",
    "image_resize_short",
    "conv3d_transpose",
    "adaptive_pool3d",
    "deformable_conv",
    "gaussian_random_batch_size_like",
    "Print",
    "linear_chain_crf",
    "crf_decoding",
    "elu",
    "relu6",
    "hard_sigmoid",
    "hard_swish",
    "swish",
    "brelu",
    "soft_relu",
    "stanh",
    "selu",
    "sign",
    "elementwise_mod",
    "elementwise_floordiv",
    "reduce_all",
    "reduce_any",
    "gather_nd",
    "scatter_nd_add",
    "scatter_nd",
    "sum",
    "rank",
    "size",
    "huber_loss",
    "log_loss",
    "kldiv_loss",
    "rank_loss",
    "margin_rank_loss",
    "bpr_loss",
    "dice_loss",
    "mean_iou",
    "resize_bilinear",
    "resize_nearest",
    "image_resize",
    "adaptive_pool2d",
    "pool3d",
    "conv3d",
    "pixel_shuffle",
    "shuffle_channel",
    "space_to_depth",
    "temporal_shift",
    "maxout",
    "lrn",
    "affine_channel",
    "multiplex",
    "crop",
    "pad_constant_like",
    "unfold",
    "grid_sampler",
    "bilinear_tensor_product",
    "shard_index",
    "sampling_id",
    "roi_align",
    "roi_pool",
    "fsp_matrix",
    "add_position_encoding",
    "fused_attention",
    "ring_attention",
    "nce",
    "hsigmoid",
    "warpctc",
    "fc",
    "embedding",
    "conv2d",
    "conv2d_transpose",
    "pool2d",
    "batch_norm",
    "layer_norm",
    "group_norm",
    "dropout",
    "softmax",
    "log_softmax",
    "cross_entropy",
    "softmax_with_cross_entropy",
    "sigmoid_cross_entropy_with_logits",
    "square_error_cost",
    "smooth_l1",
    "mean",
    "mul",
    "matmul",
    "relu",
    "sigmoid",
    "tanh",
    "gelu",
    "leaky_relu",
    "exp",
    "log",
    "sqrt",
    "square",
    "abs",
    "elementwise_add",
    "elementwise_sub",
    "elementwise_mul",
    "elementwise_div",
    "elementwise_max",
    "elementwise_min",
    "elementwise_pow",
    "reduce_sum",
    "reduce_mean",
    "reduce_max",
    "reduce_min",
    "reduce_prod",
    "scale",
    "sums",
    "cast",
    "reshape",
    "flatten",
    "transpose",
    "concat",
    "split",
    "slice",
    "squeeze",
    "unsqueeze",
    "stack",
    "unstack",
    "expand",
    "gather",
    "scatter",
    "one_hot",
    "topk",
    "argmax",
    "argmin",
    "argsort",
    "accuracy",
    "label_smooth",
    "clip",
    "clip_by_norm",
    "pad",
    "pad2d",
    "prelu",
    "l2_normalize",
    "dot",
    "cos_sim",
    "pow",
    "where",
    "shape",
    "increment",
    "cumsum",
    "lod_reset",
]


def _elementwise_binary(op_type: str, x, y, axis=-1, act=None, name=None):
    helper = LayerHelper(op_type, name=name)
    if not isinstance(y, Variable):
        # scalar operand — lower to `scale` (fused by XLA anyway)
        if op_type == "elementwise_add":
            return scale(x, scale=1.0, bias=float(y))
        if op_type == "elementwise_sub":
            return scale(x, scale=1.0, bias=-float(y))
        if op_type == "elementwise_mul":
            return scale(x, scale=float(y))
        if op_type == "elementwise_div":
            return scale(x, scale=1.0 / float(y))
        from .tensor import fill_constant

        y = fill_constant(shape=[1], dtype=x.dtype.value, value=float(y))
    if not isinstance(x, Variable):
        # scalar on the left: lower to scale/reciprocal forms (elementwise
        # broadcast aligns Y to X, so a [1]-shaped X would mis-broadcast)
        if op_type == "elementwise_add":
            return scale(y, scale=1.0, bias=float(x))
        if op_type == "elementwise_mul":
            return scale(y, scale=float(x))
        if op_type == "elementwise_sub":
            return scale(y, scale=-1.0, bias=float(x))
        if op_type == "elementwise_div":
            return scale(_unary("reciprocal", y), scale=float(x))
        from .tensor import fill_constant

        x = fill_constant(shape=[1], dtype=y.dtype.value, value=float(x))
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        op_type,
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"axis": axis},
    )
    return helper.append_activation(out, act)


def elementwise_add(x, y, axis=-1, act=None, name=None):
    return _elementwise_binary("elementwise_add", x, y, axis, act, name)


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return _elementwise_binary("elementwise_sub", x, y, axis, act, name)


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return _elementwise_binary("elementwise_mul", x, y, axis, act, name)


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return _elementwise_binary("elementwise_div", x, y, axis, act, name)


def elementwise_max(x, y, axis=-1, act=None, name=None):
    return _elementwise_binary("elementwise_max", x, y, axis, act, name)


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return _elementwise_binary("elementwise_min", x, y, axis, act, name)


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return _elementwise_binary("elementwise_pow", x, y, axis, act, name)


def fc(
    input,
    size,
    num_flatten_dims=1,
    param_attr=None,
    bias_attr=None,
    act=None,
    name=None,
):
    """Fully-connected layer (reference nn.py:228)."""
    helper = LayerHelper("fc", name=name)
    inputs = input if isinstance(input, (list, tuple)) else [input]
    mul_results = []
    for inp in inputs:
        in_dim = int(np.prod(inp.shape[num_flatten_dims:]))
        w = helper.create_parameter(param_attr, [in_dim, size], inp.dtype)
        tmp = helper.create_variable_for_type_inference(inp.dtype)
        helper.append_op(
            "mul",
            inputs={"X": [inp], "Y": [w]},
            outputs={"Out": [tmp]},
            attrs={"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1},
        )
        mul_results.append(tmp)
    if len(mul_results) == 1:
        pre_bias = mul_results[0]
    else:
        pre_bias = helper.create_variable_for_type_inference(inputs[0].dtype)
        helper.append_op("sum", inputs={"X": mul_results}, outputs={"Out": [pre_bias]})
    pre_act = helper.append_bias_op(pre_bias, bias_attr) if bias_attr is not False else pre_bias
    return helper.append_activation(pre_act, act)


def embedding(
    input,
    size,
    is_sparse=False,
    is_distributed=False,
    padding_idx=None,
    param_attr=None,
    dtype="float32",
    name=None,
):
    """Embedding lookup (reference nn.py lookup_table). `is_sparse` keeps the
    API; on TPU the grad is a dense scatter-add fused by XLA."""
    helper = LayerHelper("embedding", name=name)
    w = helper.create_parameter(param_attr, list(size), dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "lookup_table",
        inputs={"W": [w], "Ids": [input]},
        outputs={"Out": [out]},
        attrs={
            "is_sparse": is_sparse,
            "is_distributed": is_distributed,
            "padding_idx": -1 if padding_idx is None else padding_idx,
        },
    )
    return out


def conv2d(
    input,
    num_filters,
    filter_size,
    stride=1,
    padding=0,
    dilation=1,
    groups=1,
    param_attr=None,
    bias_attr=None,
    act=None,
    name=None,
    use_cudnn=True,  # accepted for API parity; XLA owns the implementation
    data_format="NCHW",
):
    helper = LayerHelper("conv2d", name=name)
    num_channels = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    fs = filter_size if isinstance(filter_size, (list, tuple)) else (filter_size, filter_size)
    # NHWC stores weights natively in HWIO: transposing OIHW inside the
    # step measures ~6% slower per conv on TPU (PERF.md r5)
    if data_format == "NHWC":
        w_shape = [fs[0], fs[1], num_channels // groups, num_filters]
    else:
        w_shape = [num_filters, num_channels // groups, fs[0], fs[1]]
    fan_in = (num_channels // groups) * fs[0] * fs[1]
    w = helper.create_parameter(
        param_attr, w_shape, input.dtype,
        default_initializer=Normal(0.0, (2.0 / fan_in) ** 0.5),
    )
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "depthwise_conv2d" if groups == num_channels and num_filters == num_channels else "conv2d",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={
            "strides": list(stride if isinstance(stride, (list, tuple)) else (stride, stride)),
            "paddings": list(padding if isinstance(padding, (list, tuple)) else (padding, padding)),
            "dilations": list(dilation if isinstance(dilation, (list, tuple)) else (dilation, dilation)),
            "groups": groups,
            "data_format": data_format,
        },
    )
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, [num_filters], input.dtype, is_bias=True)
        tmp = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op(
            "elementwise_add",
            inputs={"X": [out], "Y": [b]},
            outputs={"Out": [tmp]},
            attrs={"axis": 1 if data_format == "NCHW" else -1},
        )
        out = tmp
    return helper.append_activation(out, act)


def conv2d_transpose(
    input,
    num_filters,
    filter_size,
    stride=1,
    padding=0,
    dilation=1,
    param_attr=None,
    bias_attr=None,
    act=None,
    name=None,
):
    helper = LayerHelper("conv2d_transpose", name=name)
    num_channels = input.shape[1]
    fs = filter_size if isinstance(filter_size, (list, tuple)) else (filter_size, filter_size)
    w = helper.create_parameter(
        param_attr, [num_channels, num_filters, fs[0], fs[1]], input.dtype
    )
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "conv2d_transpose",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={
            "strides": list(stride if isinstance(stride, (list, tuple)) else (stride, stride)),
            "paddings": list(padding if isinstance(padding, (list, tuple)) else (padding, padding)),
            "dilations": list(dilation if isinstance(dilation, (list, tuple)) else (dilation, dilation)),
        },
    )
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, [num_filters], input.dtype, is_bias=True)
        tmp = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op(
            "elementwise_add",
            inputs={"X": [out], "Y": [b]},
            outputs={"Out": [tmp]},
            attrs={"axis": 1},
        )
        out = tmp
    return helper.append_activation(out, act)


def pool2d(
    input,
    pool_size=2,
    pool_type="max",
    pool_stride=1,
    pool_padding=0,
    global_pooling=False,
    exclusive=True,
    name=None,
    use_cudnn=True,
    data_format="NCHW",
):
    helper = LayerHelper("pool2d", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "pool2d",
        inputs={"X": [input]},
        outputs={"Out": [out]},
        attrs={
            "pooling_type": pool_type,
            "ksize": list(pool_size if isinstance(pool_size, (list, tuple)) else (pool_size, pool_size)),
            "strides": list(
                pool_stride if isinstance(pool_stride, (list, tuple)) else (pool_stride, pool_stride)
            ),
            "paddings": list(
                pool_padding if isinstance(pool_padding, (list, tuple)) else (pool_padding, pool_padding)
            ),
            "global_pooling": global_pooling,
            "exclusive": exclusive,
            "data_format": data_format,
        },
    )
    return out


def batch_norm(
    input,
    act=None,
    is_test=False,
    momentum=0.9,
    epsilon=1e-5,
    param_attr=None,
    bias_attr=None,
    data_layout="NCHW",
    name=None,
    moving_mean_name=None,
    moving_variance_name=None,
    use_global_stats=False,
):
    helper = LayerHelper("batch_norm", name=name)
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    scale = helper.create_parameter(
        param_attr, [c], "float32", default_initializer=Constant(1.0)
    )
    bias = helper.create_parameter(bias_attr, [c], "float32", is_bias=True)
    mean = helper.create_or_get_global_variable(
        moving_mean_name or helper.name + ".mean", [c], "float32", initializer=Constant(0.0)
    )
    var = helper.create_or_get_global_variable(
        moving_variance_name or helper.name + ".var", [c], "float32", initializer=Constant(1.0)
    )
    y = helper.create_variable_for_type_inference(input.dtype)
    saved_mean = helper.create_variable_for_type_inference("float32", stop_gradient=True)
    saved_var = helper.create_variable_for_type_inference("float32", stop_gradient=True)
    helper.append_op(
        "batch_norm",
        inputs={"X": [input], "Scale": [scale], "Bias": [bias], "Mean": [mean], "Variance": [var]},
        outputs={
            "Y": [y],
            "MeanOut": [mean],
            "VarianceOut": [var],
            "SavedMean": [saved_mean],
            "SavedVariance": [saved_var],
        },
        attrs={
            "momentum": momentum,
            "epsilon": epsilon,
            "is_test": is_test or use_global_stats,
            "data_layout": data_layout,
        },
    )
    return helper.append_activation(y, act)


def layer_norm(
    input,
    scale=True,
    shift=True,
    begin_norm_axis=1,
    epsilon=1e-5,
    param_attr=None,
    bias_attr=None,
    act=None,
    name=None,
):
    helper = LayerHelper("layer_norm", name=name)
    norm_shape = [int(np.prod(input.shape[begin_norm_axis:]))]
    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(
            param_attr, norm_shape, "float32", default_initializer=Constant(1.0)
        )
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(bias_attr, norm_shape, "float32", is_bias=True)
        inputs["Bias"] = [b]
    y = helper.create_variable_for_type_inference(input.dtype)
    mean = helper.create_variable_for_type_inference("float32", stop_gradient=True)
    var = helper.create_variable_for_type_inference("float32", stop_gradient=True)
    helper.append_op(
        "layer_norm",
        inputs=inputs,
        outputs={"Y": [y], "Mean": [mean], "Variance": [var]},
        attrs={"begin_norm_axis": begin_norm_axis, "epsilon": epsilon},
    )
    return helper.append_activation(y, act)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("group_norm", name=name)
    c = input.shape[1]
    inputs = {"X": [input]}
    if param_attr is not False:
        inputs["Scale"] = [
            helper.create_parameter(param_attr, [c], "float32", default_initializer=Constant(1.0))
        ]
    if bias_attr is not False:
        inputs["Bias"] = [helper.create_parameter(bias_attr, [c], "float32", is_bias=True)]
    y = helper.create_variable_for_type_inference(input.dtype)
    mean = helper.create_variable_for_type_inference("float32", stop_gradient=True)
    var = helper.create_variable_for_type_inference("float32", stop_gradient=True)
    helper.append_op(
        "group_norm",
        inputs=inputs,
        outputs={"Y": [y], "Mean": [mean], "Variance": [var]},
        attrs={"groups": groups, "epsilon": epsilon},
    )
    return helper.append_activation(y, act)


def dropout(
    x,
    dropout_prob,
    is_test=False,
    seed=None,
    name=None,
    dropout_implementation="downgrade_in_infer",
):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    mask = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        "dropout",
        inputs={"X": [x]},
        outputs={"Out": [out], "Mask": [mask]},
        attrs={
            "dropout_prob": dropout_prob,
            "is_test": is_test,
            "seed": seed or 0,
            "dropout_implementation": dropout_implementation,
        },
    )
    return out


def _unary(op_type, x, name=None, **attrs):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(op_type, inputs={"X": [x]}, outputs={"Out": [out]}, attrs=attrs)
    return out


def relu(x, name=None):
    return _unary("relu", x, name)


def sigmoid(x, name=None):
    return _unary("sigmoid", x, name)


def tanh(x, name=None):
    return _unary("tanh", x, name)


def gelu(x, name=None):
    return _unary("gelu", x, name)


def leaky_relu(x, alpha=0.02, name=None):
    return _unary("leaky_relu", x, name, alpha=alpha)


def exp(x, name=None):
    return _unary("exp", x, name)


def log(x, name=None):
    return _unary("log", x, name)


def sqrt(x, name=None):
    return _unary("sqrt", x, name)


def square(x, name=None):
    return _unary("square", x, name)


def abs(x, name=None):
    return _unary("abs", x, name)


def pow(x, factor=1.0, name=None):
    return _unary("pow", x, name, factor=factor)


def softmax(input, axis=-1, name=None, use_cudnn=False):
    return _unary("softmax", input, name, axis=axis)


def log_softmax(input, axis=-1, name=None):
    return _unary("log_softmax", input, name, axis=axis)


def clip(x, min, max, name=None):
    return _unary("clip", x, name, min=min, max=max)


def clip_by_norm(x, max_norm, name=None):
    return _unary("clip_by_norm", x, name, max_norm=max_norm)


def mean(x, name=None):
    helper = LayerHelper("mean", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("mean", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "mul",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"x_num_col_dims": x_num_col_dims, "y_num_col_dims": y_num_col_dims},
    )
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "matmul",
        inputs={"X": [x], "Y": [y]},
        outputs={"Out": [out]},
        attrs={"transpose_X": transpose_x, "transpose_Y": transpose_y, "alpha": alpha},
    )
    return out


def cross_entropy(input, label, soft_label=False, ignore_index=-100, name=None):
    helper = LayerHelper("cross_entropy", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "cross_entropy",
        inputs={"X": [input], "Label": [label]},
        outputs={"Y": [out]},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index},
    )
    return out


def softmax_with_cross_entropy(
    logits,
    label,
    soft_label=False,
    ignore_index=-100,
    numeric_stable_mode=True,
    return_softmax=False,
    name=None,
):
    helper = LayerHelper("softmax_with_cross_entropy", name=name)
    softmax_out = helper.create_variable_for_type_inference(logits.dtype)
    loss = helper.create_variable_for_type_inference(logits.dtype)
    helper.append_op(
        "softmax_with_cross_entropy",
        inputs={"Logits": [logits], "Label": [label]},
        outputs={"Softmax": [softmax_out], "Loss": [loss]},
        attrs={"soft_label": soft_label, "ignore_index": ignore_index},
    )
    if return_softmax:
        return loss, softmax_out
    return loss


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100, normalize=False, name=None):
    helper = LayerHelper("sigmoid_cross_entropy_with_logits", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "sigmoid_cross_entropy_with_logits",
        inputs={"X": [x], "Label": [label]},
        outputs={"Out": [out]},
        attrs={"ignore_index": ignore_index, "normalize": normalize},
    )
    return out


def square_error_cost(input, label, name=None):
    helper = LayerHelper("square_error_cost", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "square_error_cost",
        inputs={"X": [input], "Y": [label]},
        outputs={"Out": [out]},
    )
    return out


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=1.0, name=None):
    helper = LayerHelper("smooth_l1_loss", name=name)
    loss = helper.create_variable_for_type_inference(x.dtype)
    diff = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    inputs = {"X": [x], "Y": [y]}
    if inside_weight is not None:
        inputs["InsideWeight"] = [inside_weight]
    if outside_weight is not None:
        inputs["OutsideWeight"] = [outside_weight]
    helper.append_op(
        "smooth_l1_loss",
        inputs=inputs,
        outputs={"Out": [loss], "Diff": [diff]},
        attrs={"sigma": sigma},
    )
    return loss


def _reduce(op_type, input, dim=None, keep_dim=False, name=None):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    if dim is None:
        attrs = {"dim": [0], "keep_dim": keep_dim, "reduce_all": True}
    else:
        attrs = {
            "dim": dim if isinstance(dim, (list, tuple)) else [dim],
            "keep_dim": keep_dim,
            "reduce_all": False,
        }
    helper.append_op(op_type, inputs={"X": [input]}, outputs={"Out": [out]}, attrs=attrs)
    return out


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_sum", input, dim, keep_dim, name)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_mean", input, dim, keep_dim, name)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_max", input, dim, keep_dim, name)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_min", input, dim, keep_dim, name)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_prod", input, dim, keep_dim, name)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    helper = LayerHelper("scale", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "scale",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"scale": float(scale), "bias": float(bias), "bias_after_scale": bias_after_scale},
    )
    return helper.append_activation(out, act)


def sums(input, out=None):
    helper = LayerHelper("sum")
    if out is None:
        out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op("sum", inputs={"X": input}, outputs={"Out": [out]})
    return out


def cast(x, dtype):
    helper = LayerHelper("cast")
    dtype = DType.parse(dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "cast",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"out_dtype": dtype.value, "in_dtype": x.dtype.value},
    )
    return out


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper("reshape2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "reshape2",
        inputs={"X": [x]},
        outputs={"Out": [out]},
        attrs={"shape": list(shape)},
    )
    return helper.append_activation(out, act)


def flatten(x, axis=1, name=None):
    return _unary("flatten2", x, name, axis=axis)


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose2", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "transpose2", inputs={"X": [x]}, outputs={"Out": [out]}, attrs={"axis": list(perm)}
    )
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op("concat", inputs={"X": input}, outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    dim = dim % len(input.shape)
    if isinstance(num_or_sections, int):
        num, sections = num_or_sections, []
        n_out = num_or_sections
    else:
        num, sections = 0, list(num_or_sections)
        n_out = len(sections)
    outs = [helper.create_variable_for_type_inference(input.dtype) for _ in range(n_out)]
    helper.append_op(
        "split",
        inputs={"X": [input]},
        outputs={"Out": outs},
        attrs={"axis": dim, "num": num, "sections": sections},
    )
    return outs


def slice(input, axes, starts, ends, name=None):
    helper = LayerHelper("slice", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "slice",
        inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={"axes": list(axes), "starts": list(starts), "ends": list(ends)},
    )
    return out


def squeeze(input, axes, name=None):
    return _unary("squeeze2", input, name, axes=list(axes))


def unsqueeze(input, axes, name=None):
    return _unary("unsqueeze2", input, name, axes=list(axes))


def stack(x, axis=0, name=None):
    helper = LayerHelper("stack", name=name)
    out = helper.create_variable_for_type_inference(x[0].dtype)
    helper.append_op("stack", inputs={"X": x}, outputs={"Y": [out]}, attrs={"axis": axis})
    return out


def unstack(x, axis=0, num=None, name=None):
    helper = LayerHelper("unstack", name=name)
    n = num if num is not None else x.shape[axis]
    outs = [helper.create_variable_for_type_inference(x.dtype) for _ in range(n)]
    helper.append_op("unstack", inputs={"X": [x]}, outputs={"Y": outs}, attrs={"axis": axis})
    return outs


def expand(x, expand_times, name=None):
    return _unary("expand", x, name, expand_times=list(expand_times))


def gather(input, index, name=None):
    helper = LayerHelper("gather", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("gather", inputs={"X": [input], "Index": [index]}, outputs={"Out": [out]})
    return out


def scatter(input, index, updates, overwrite=True, name=None):
    helper = LayerHelper("scatter", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op(
        "scatter",
        inputs={"X": [input], "Ids": [index], "Updates": [updates]},
        outputs={"Out": [out]},
        attrs={"overwrite": overwrite},
    )
    return out


def one_hot(input, depth, name=None):
    helper = LayerHelper("one_hot", name=name)
    out = helper.create_variable_for_type_inference("float32")
    helper.append_op(
        "one_hot", inputs={"X": [input]}, outputs={"Out": [out]}, attrs={"depth": depth}
    )
    return out


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    values = helper.create_variable_for_type_inference(input.dtype)
    indices = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        "top_k",
        inputs={"X": [input]},
        outputs={"Out": [values], "Indices": [indices]},
        attrs={"k": k},
    )
    return values, indices


def argmax(x, axis=0, name=None):
    helper = LayerHelper("arg_max", name=name)
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op("arg_max", inputs={"X": [x]}, outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def argmin(x, axis=0, name=None):
    helper = LayerHelper("arg_min", name=name)
    out = helper.create_variable_for_type_inference("int64")
    helper.append_op("arg_min", inputs={"X": [x]}, outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def argsort(input, axis=-1, name=None):
    helper = LayerHelper("argsort", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    idx = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        "argsort",
        inputs={"X": [input]},
        outputs={"Out": [out], "Indices": [idx]},
        attrs={"axis": axis},
    )
    return out, idx


def accuracy(input, label, k=1, correct=None, total=None):
    """Classification accuracy (reference layers/metric_op.py:32)."""
    helper = LayerHelper("accuracy")
    _, indices = topk(input, k)
    acc = helper.create_variable_for_type_inference("float32")
    correct = correct or helper.create_variable_for_type_inference("int32")
    total = total or helper.create_variable_for_type_inference("int32")
    helper.append_op(
        "accuracy",
        inputs={"Out": [input], "Indices": [indices], "Label": [label]},
        outputs={"Accuracy": [acc], "Correct": [correct], "Total": [total]},
    )
    return acc


def label_smooth(label, prior_dist=None, epsilon=0.1, dtype="float32", name=None):
    helper = LayerHelper("label_smooth", name=name)
    out = helper.create_variable_for_type_inference(dtype)
    inputs = {"X": [label]}
    if prior_dist is not None:
        inputs["PriorDist"] = [prior_dist]
    helper.append_op(
        "label_smooth", inputs=inputs, outputs={"Out": [out]}, attrs={"epsilon": float(epsilon)}
    )
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    return _unary("pad", x, name, paddings=list(paddings), pad_value=float(pad_value))


def pad2d(input, paddings, mode="constant", pad_value=0.0, name=None):
    return _unary("pad2d", input, name, paddings=list(paddings), mode=mode, pad_value=float(pad_value))


def prelu(x, mode="all", param_attr=None, name=None):
    helper = LayerHelper("prelu", name=name)
    if mode == "all":
        alpha_shape = [1]
    elif mode == "channel":
        alpha_shape = [x.shape[1]]
    else:
        alpha_shape = list(x.shape[1:])
    alpha = helper.create_parameter(
        param_attr, alpha_shape, x.dtype, default_initializer=Constant(0.25)
    )
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "prelu",
        inputs={"X": [x], "Alpha": [alpha]},
        outputs={"Out": [out]},
        attrs={"mode": mode},
    )
    return out


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("norm", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    norm = helper.create_variable_for_type_inference(x.dtype, stop_gradient=True)
    helper.append_op(
        "norm",
        inputs={"X": [x]},
        outputs={"Out": [out], "Norm": [norm]},
        attrs={"axis": axis, "epsilon": epsilon},
    )
    return out


def dot(x, y, name=None):
    helper = LayerHelper("dot", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("dot", inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]})
    return out


def cos_sim(X, Y, name=None):
    helper = LayerHelper("cos_sim", name=name)
    out = helper.create_variable_for_type_inference(X.dtype)
    xn = helper.create_variable_for_type_inference(X.dtype, stop_gradient=True)
    yn = helper.create_variable_for_type_inference(X.dtype, stop_gradient=True)
    helper.append_op(
        "cos_sim",
        inputs={"X": [X], "Y": [Y]},
        outputs={"Out": [out], "XNorm": [xn], "YNorm": [yn]},
    )
    return out


def where(condition, x, y, name=None):
    helper = LayerHelper("where", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "where",
        inputs={"Condition": [condition], "X": [x], "Y": [y]},
        outputs={"Out": [out]},
    )
    return out


def shape(input, name=None):
    helper = LayerHelper("shape", name=name)
    out = helper.create_variable_for_type_inference("int32")
    helper.append_op("shape", inputs={"X": [input]}, outputs={"Out": [out]})
    return out


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    out = x if in_place else helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "increment", inputs={"X": [x]}, outputs={"Out": [out]}, attrs={"step": float(value)}
    )
    return out


def cumsum(x, axis=-1, exclusive=False, reverse=False, name=None):
    return _unary("cum", x, name, axis=axis, exclusive=exclusive, reverse=reverse)


def lod_reset(x, y=None, target_lod=None):
    """LoD is replaced by padding + segment ids on TPU (SURVEY.md §5); this is
    an identity kept for API compatibility."""
    return x


def fused_attention(q, k, v, bias=None, causal=False, sm_scale=None,
                    use_pallas=False, name=None):
    """Fused scaled-dot-product attention over [B, nh, S, dh] tensors —
    one op boundary for the whole QK^T -> softmax -> PV block, dispatched by
    measurement (ops/attention_ops.py): XLA fusion at train sizes, the
    custom short-seq Pallas kernel with `use_pallas` (O(S) memory), jax's
    bundled flash kernel for long sequences. The reference builds attention
    from matmul+softmax ops (nets.py:345) — this is the TPU-native fused
    equivalent."""
    helper = LayerHelper("fused_attention", name=name)
    if sm_scale is None:
        sm_scale = float(q.shape[-1]) ** -0.5
    out = helper.create_variable_for_type_inference(q.dtype)
    inputs = {"Q": [q], "K": [k], "V": [v]}
    if bias is not None:
        inputs["Bias"] = [bias]
    helper.append_op(
        "fused_attention", inputs, {"Out": [out]},
        {"causal": causal, "sm_scale": float(sm_scale),
         "use_pallas": bool(use_pallas)},
    )
    return out


def ring_attention(q, k, v, causal=False, sm_scale=None, ring_id=0, name=None):
    """Sequence-parallel ring attention: exact attention over a sequence
    sharded across the mesh axis bound to `ring_id` (K/V blocks rotate via
    collective-permute with an online-softmax merge). Single-device: plain
    fused attention."""
    helper = LayerHelper("ring_attention", name=name)
    if sm_scale is None:
        sm_scale = float(q.shape[-1]) ** -0.5
    out = helper.create_variable_for_type_inference(q.dtype)
    helper.append_op(
        "ring_attention", {"Q": [q], "K": [k], "V": [v]}, {"Out": [out]},
        {"causal": causal, "sm_scale": float(sm_scale), "ring_id": ring_id},
    )
    return out


def nce(input, label, num_total_classes, sample_weight=None, param_attr=None,
        bias_attr=None, num_neg_samples=None, name=None, sampler="uniform",
        custom_dist=None, seed=0, is_sparse=False):
    """Noise-contrastive estimation loss (reference nn.py:5955 / nce_op.h).
    Returns per-sample cost [B, 1]; negatives drawn per step from the
    counter-based PRNG (uniform or log_uniform)."""
    if custom_dist is not None or sample_weight is not None:
        raise NotImplementedError(
            "nce: custom_dist / sample_weight are not supported; use "
            "sampler='uniform' or 'log_uniform'")
    if sampler not in ("uniform", "log_uniform"):
        raise ValueError(f"nce: unknown sampler '{sampler}'")
    helper = LayerHelper("nce", name=name)
    dim = input.shape[-1]
    w = helper.create_parameter(param_attr, [num_total_classes, dim],
                                input.dtype)
    inputs = {"Input": [input], "Label": [label], "Weight": [w]}
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, [num_total_classes],
                                    input.dtype, is_bias=True)
        inputs["Bias"] = [b]
    cost = helper.create_variable_for_type_inference(input.dtype)
    samples = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        "nce", inputs, {"Cost": [cost], "SampleLabels": [samples]},
        {"num_total_classes": int(num_total_classes),
         "num_neg_samples": int(num_neg_samples or 5),
         "sampler": {"uniform": 0, "log_uniform": 1}[sampler],
         "seed": seed})
    return cost


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None, path_table=None, path_code=None, is_custom=False,
             is_sparse=False):
    """Hierarchical sigmoid loss over a complete binary tree (reference
    nn.py:6169 / hierarchical_sigmoid_op.h SimpleCode). Returns [B, 1]."""
    if is_custom or path_table is not None or path_code is not None:
        raise NotImplementedError(
            "hsigmoid custom trees (path_table/path_code) are not supported; "
            "the complete-binary-tree SimpleCode layout is")
    helper = LayerHelper("hsigmoid", name=name)
    dim = input.shape[-1]
    w = helper.create_parameter(param_attr, [num_classes - 1, dim],
                                input.dtype)
    inputs = {"X": [input], "Label": [label], "W": [w]}
    if bias_attr is not False:
        b = helper.create_parameter(bias_attr, [num_classes - 1],
                                    input.dtype, is_bias=True)
        inputs["Bias"] = [b]
    out = helper.create_variable_for_type_inference(input.dtype)
    pre = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("hierarchical_sigmoid", inputs,
                     {"Out": [out], "PreOut": [pre]},
                     {"num_classes": int(num_classes)})
    return out


def warpctc(input, label, blank=0, norm_by_times=False, input_length=None,
            label_length=None):
    """CTC loss (reference nn.py warpctc / warpctc_op.h) on padded batches:
    input [B, T, V] raw logits, label [B, S]; lengths default to the padded
    extents."""
    helper = LayerHelper("warpctc")
    inputs = {"Logits": [input], "Label": [label]}
    if input_length is not None:
        inputs["LogitsLength"] = [input_length]
    if label_length is not None:
        inputs["LabelLength"] = [label_length]
    loss = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("warpctc", inputs, {"Loss": [loss]},
                     {"blank": int(blank), "norm_by_times": norm_by_times})
    return loss


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=False,
          print_phase="both"):
    """In-graph debug printing (reference layers/control_flow.py Print ->
    print_op.cc): logs the tensor each execution, passes it through. The
    print_tensor_* layout knobs are accepted for API parity; the host op
    prints name/shape/dtype/values unconditionally."""
    helper = LayerHelper("print", name=None)
    out = helper.create_variable_for_type_inference(input.dtype)
    # host ops skip shape inference — forward the input's shape so
    # downstream layers (fc fan-in, etc.) see the real dims
    out.shape = tuple(input.shape)
    helper.append_op(
        "print", {"In": [input]}, {"Out": [out]},
        {"first_n": first_n,
         "message": message or input.name,
         "summarize": summarize,
         "print_phase": print_phase})
    return out


# ---------------------------------------------------------------------------
# long-tail layer wrappers (reference nn.py parity; ops in
# activation_ops / math_ops / tensor_ops / vision_ops / detection_ops)
# ---------------------------------------------------------------------------


def _simple_op(op_type, inputs, attrs=None, out_slot="Out", dtype=None,
               n_out=1):
    helper = LayerHelper(op_type)
    first = next(v for vs in inputs.values() for v in vs)
    outs = [helper.create_variable_for_type_inference(dtype or first.dtype)
            for _ in range(n_out)]
    helper.append_op(op_type, inputs,
                     {out_slot: [outs[0]]} if n_out == 1 else
                     {s: [o] for s, o in zip(out_slot, outs)},
                     attrs or {})
    return outs[0] if n_out == 1 else outs


def elu(x, alpha=1.0, name=None):
    return _simple_op("elu", {"X": [x]}, {"alpha": alpha})


def relu6(x, threshold=6.0, name=None):
    return _simple_op("relu6", {"X": [x]}, {"threshold": threshold})


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    return _simple_op("hard_sigmoid", {"X": [x]},
                      {"slope": slope, "offset": offset})


def hard_swish(x, threshold=6.0, scale=6.0, offset=3.0, name=None):
    return _simple_op("hard_swish", {"X": [x]},
                      {"threshold": threshold, "scale": scale,
                       "offset": offset})


def swish(x, beta=1.0, name=None):
    return _simple_op("swish", {"X": [x]}, {"beta": beta})


def brelu(x, t_min=0.0, t_max=24.0, name=None):
    return _simple_op("brelu", {"X": [x]}, {"t_min": t_min, "t_max": t_max})


def soft_relu(x, threshold=40.0, name=None):
    return _simple_op("soft_relu", {"X": [x]}, {"threshold": threshold})


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return _simple_op("stanh", {"X": [x]},
                      {"scale_a": scale_a, "scale_b": scale_b})


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return _simple_op("selu", {"X": [x]}, {"scale": scale, "alpha": alpha})


def sign(x, name=None):
    return _simple_op("sign", {"X": [x]})


def elementwise_mod(x, y, axis=-1, name=None):
    return _simple_op("elementwise_mod", {"X": [x], "Y": [y]}, {"axis": axis})


def elementwise_floordiv(x, y, axis=-1, name=None):
    return _simple_op("elementwise_floordiv", {"X": [x], "Y": [y]},
                      {"axis": axis})


def reduce_all(x, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_all", x, dim, keep_dim, name)


def reduce_any(x, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_any", x, dim, keep_dim, name)


def gather_nd(input, index, name=None):
    return _simple_op("gather_nd", {"X": [input], "Index": [index]})


def scatter_nd_add(ref, index, updates, name=None):
    return _simple_op("scatter_nd_add",
                      {"X": [ref], "Index": [index], "Updates": [updates]})


def scatter_nd(index, updates, shape, name=None):
    return _simple_op("scatter_nd", {"Index": [index], "Updates": [updates]},
                      {"shape": list(shape)}, dtype=updates.dtype)


def sum(x, name=None):
    xs = x if isinstance(x, (list, tuple)) else [x]
    return _simple_op("sum", {"X": list(xs)})


def rank(input):
    """Static rank as a constant tensor (reference nn.py rank)."""
    from .tensor import fill_constant

    return fill_constant(shape=[1], dtype="int32", value=len(input.shape))


def size(input):
    """Element count at RUNTIME (reference nn.py size): the batch dim is -1
    at build time, so the product must come from the executed shape."""
    shp = _simple_op("shape", {"X": [input]}, dtype="int32")
    shp.shape = (len(input.shape),)
    return _reduce("reduce_prod", cast(shp, "int64"), None, False, None)


def huber_loss(input, label, delta):
    return _simple_op("huber_loss", {"X": [input], "Y": [label]},
                      {"delta": delta})


def log_loss(input, label, epsilon=1e-4, name=None):
    return _simple_op("log_loss", {"Predicted": [input], "Labels": [label]},
                      {"epsilon": epsilon}, out_slot="Loss")


def kldiv_loss(x, target, reduction="mean", name=None):
    return _simple_op("kldiv_loss", {"X": [x], "Target": [target]},
                      {"reduction": reduction}, out_slot="Loss")


def rank_loss(label, left, right, name=None):
    return _simple_op("rank_loss",
                      {"Label": [label], "Left": [left], "Right": [right]})


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    helper = LayerHelper("margin_rank_loss")
    out = helper.create_variable_for_type_inference(left.dtype)
    act = helper.create_variable_for_type_inference(left.dtype)
    helper.append_op("margin_rank_loss",
                     {"Label": [label], "X1": [left], "X2": [right]},
                     {"Out": [out], "Activated": [act]}, {"margin": margin})
    return out


def bpr_loss(input, label, name=None):
    return _simple_op("bpr_loss", {"X": [input], "Label": [label]},
                      out_slot="Y")


def dice_loss(input, label, epsilon=1e-5):
    """reference nn.py dice_loss — built from primitives (no bespoke op)."""
    label_f = cast(label, input.dtype)
    inter = reduce_sum(elementwise_mul(input, label_f))
    union = reduce_sum(input) + reduce_sum(label_f)
    from .tensor import fill_constant

    one = fill_constant(shape=[], dtype=input.dtype, value=1.0)
    eps = fill_constant(shape=[], dtype=input.dtype, value=epsilon)
    return one - elementwise_div(
        scale(inter, scale=2.0), elementwise_add(union, eps))


def mean_iou(input, label, num_classes):
    helper = LayerHelper("mean_iou")
    miou = helper.create_variable_for_type_inference("float32")
    wrong = helper.create_variable_for_type_inference("float32")
    correct = helper.create_variable_for_type_inference("float32")
    helper.append_op("mean_iou",
                     {"Predictions": [input], "Labels": [label]},
                     {"OutMeanIou": [miou], "OutWrong": [wrong],
                      "OutCorrect": [correct]}, {"num_classes": num_classes})
    return miou, wrong, correct


def resize_bilinear(input, out_shape=None, scale=None, name=None,
                    actual_shape=None, align_corners=True, align_mode=1):
    oh, ow = (out_shape or (0, 0))
    return _simple_op("bilinear_interp", {"X": [input]},
                      {"out_h": oh, "out_w": ow, "scale": scale or 0.0,
                       "align_corners": align_corners,
                       "align_mode": align_mode})


def resize_nearest(input, out_shape=None, scale=None, name=None,
                   actual_shape=None, align_corners=True):
    oh, ow = (out_shape or (0, 0))
    return _simple_op("nearest_interp", {"X": [input]},
                      {"out_h": oh, "out_w": ow, "scale": scale or 0.0,
                       "align_corners": align_corners})


def image_resize(input, out_shape=None, scale=None, name=None,
                 resample="BILINEAR", actual_shape=None,
                 align_corners=True, align_mode=1):
    if resample.upper() == "NEAREST":
        return resize_nearest(input, out_shape, scale, name,
                              align_corners=align_corners)
    return resize_bilinear(input, out_shape, scale, name,
                           align_corners=align_corners,
                           align_mode=align_mode)


def adaptive_pool2d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    return _simple_op("adaptive_pool2d", {"X": [input]},
                      {"pooled_size": list(pool_size),
                       "pooling_type": pool_type})


def pool3d(input, pool_size=2, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, name=None, **kw):
    def _trip(v):
        return list(v) if isinstance(v, (list, tuple)) else [v] * 3

    return _simple_op("pool3d", {"X": [input]},
                      {"ksize": _trip(pool_size), "pooling_type": pool_type,
                       "strides": _trip(pool_stride),
                       "paddings": _trip(pool_padding),
                       "global_pooling": global_pooling})


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, name=None, act=None,
           **kw):
    def _trip(v):
        return list(v) if isinstance(v, (list, tuple)) else [v] * 3

    helper = LayerHelper("conv3d", name=name)
    C = input.shape[1]
    fs = _trip(filter_size)
    w = helper.create_parameter(
        attr=param_attr, shape=[num_filters, C // groups] + fs,
        dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("conv3d", {"Input": [input], "Filter": [w]},
                     {"Output": [out]},
                     {"strides": _trip(stride), "paddings": _trip(padding),
                      "dilations": _trip(dilation), "groups": groups})
    if bias_attr is not False:
        b = helper.create_parameter(attr=bias_attr, shape=[num_filters],
                                    dtype=input.dtype, is_bias=True)
        out2 = helper.create_variable_for_type_inference(input.dtype)
        helper.append_op("elementwise_add", {"X": [out], "Y": [b]},
                         {"Out": [out2]}, {"axis": 1})
        out = out2
    return helper.append_activation(out, act) if hasattr(
        helper, "append_activation") else (
        _simple_op(act, {"X": [out]}) if act else out)


def pixel_shuffle(x, upscale_factor):
    return _simple_op("pixel_shuffle", {"X": [x]},
                      {"upscale_factor": upscale_factor})


def shuffle_channel(x, group, name=None):
    return _simple_op("shuffle_channel", {"X": [x]}, {"group": group})


def space_to_depth(x, blocksize, name=None):
    return _simple_op("space_to_depth", {"X": [x]}, {"blocksize": blocksize})


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    return _simple_op("temporal_shift", {"X": [x]},
                      {"seg_num": seg_num, "shift_ratio": shift_ratio})


def maxout(x, groups, name=None):
    return _simple_op("maxout", {"X": [x]}, {"groups": groups})


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None):
    helper = LayerHelper("lrn")
    out = helper.create_variable_for_type_inference(input.dtype)
    mid = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("lrn", {"X": [input]},
                     {"Out": [out], "MidOut": [mid]},
                     {"n": n, "k": k, "alpha": alpha, "beta": beta})
    return out


def affine_channel(x, scale=None, bias=None, data_layout="NCHW", name=None,
                   act=None):
    out = _simple_op("affine_channel",
                     {"X": [x], "Scale": [scale], "Bias": [bias]})
    return _simple_op(act, {"X": [out]}) if act else out


def multiplex(inputs, index):
    return _simple_op("multiplex", {"X": list(inputs), "Ids": [index]},
                      dtype=inputs[0].dtype)


def crop(x, shape=None, offsets=None, name=None):
    return _simple_op("crop", {"X": [x]},
                      {"shape": list(shape),
                       "offsets": list(offsets or [0] * len(shape))})


def pad_constant_like(x, y, pad_value=0.0, name=None):
    return _simple_op("pad_constant_like", {"X": [x], "Y": [y]},
                      {"pad_value": pad_value}, dtype=y.dtype)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    def _pair_(v):
        return list(v) if isinstance(v, (list, tuple)) else [v, v]

    return _simple_op("unfold", {"X": [x]},
                      {"kernel_sizes": _pair_(kernel_sizes),
                       "strides": _pair_(strides),
                       "paddings": _pair_(paddings),
                       "dilations": _pair_(dilations)}, out_slot="Y")


def grid_sampler(x, grid, name=None):
    return _simple_op("grid_sampler", {"X": [x], "Grid": [grid]},
                      out_slot="Output")


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    helper = LayerHelper("bilinear_tensor_product", name=name)
    w = helper.create_parameter(
        attr=param_attr, shape=[size, x.shape[-1], y.shape[-1]],
        dtype=x.dtype)
    inputs = {"X": [x], "Y": [y], "Weight": [w]}
    if bias_attr is not False:
        b = helper.create_parameter(attr=bias_attr, shape=[1, size],
                                    dtype=x.dtype, is_bias=True)
        inputs["Bias"] = [b]
    out = _simple_op("bilinear_tensor_product", inputs)
    return _simple_op(act, {"X": [out]}) if act else out


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    return _simple_op("shard_index", {"X": [input]},
                      {"index_num": index_num, "nshards": nshards,
                       "shard_id": shard_id, "ignore_value": ignore_value})


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="float32"):
    return _simple_op("sampling_id", {"X": [x]}, dtype="int64")


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, name=None,
              rois_batch_id=None):
    inputs = {"X": [input], "ROIs": [rois]}
    if rois_batch_id is not None:
        inputs["RoisBatchId"] = [rois_batch_id]
    return _simple_op("roi_align", inputs,
                      {"pooled_height": pooled_height,
                       "pooled_width": pooled_width,
                       "spatial_scale": spatial_scale,
                       "sampling_ratio": sampling_ratio})


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, rois_batch_id=None):
    inputs = {"X": [input], "ROIs": [rois]}
    if rois_batch_id is not None:
        inputs["RoisBatchId"] = [rois_batch_id]
    return _simple_op("roi_pool", inputs,
                      {"pooled_height": pooled_height,
                       "pooled_width": pooled_width,
                       "spatial_scale": spatial_scale})


def fsp_matrix(x, y):
    from ..contrib.slim.distillation import fsp_matrix as _fsp

    return _fsp(x, y)


def add_position_encoding(input, alpha=1.0, beta=1.0, name=None):
    """reference nn.py add_position_encoding: sinusoid table added to
    [B, T, D] — built from primitives."""
    import numpy as _np

    from .tensor import assign

    B_, T, D = -1, input.shape[1], input.shape[2]
    pos = _np.arange(T)[:, None]
    i = _np.arange(D // 2)[None, :]
    angle = pos / _np.power(10000.0, 2.0 * i / D)
    table = _np.zeros((T, D), _np.float32)
    table[:, 0::2] = _np.sin(angle)
    table[:, 1::2] = _np.cos(angle)
    enc = assign(table)
    return elementwise_add(scale(input, scale=alpha),
                           scale(enc, scale=beta))


def linear_chain_crf(input, label, param_attr=None, length=None):
    """Linear-chain CRF negative log-likelihood (reference nn.py
    linear_chain_crf -> linear_chain_crf_op). `input` [B, T, N] emissions;
    transition parameter shape [N+2, N] (start/stop rows + NxN)."""
    helper = LayerHelper("linear_chain_crf")
    n = input.shape[-1]
    w = helper.create_parameter(attr=param_attr, shape=[n + 2, n],
                                dtype=input.dtype)
    out = helper.create_variable_for_type_inference(input.dtype)
    inputs = {"Emission": [input], "Transition": [w], "Label": [label]}
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op("linear_chain_crf", inputs,
                     {"LogLikelihood": [out]}, {})
    return out


def crf_decoding(input, param_attr, label=None, length=None):
    """Viterbi decode with the trained CRF transition (reference nn.py
    crf_decoding). With `label`, returns the per-position mismatch
    indicator instead of the path."""
    helper = LayerHelper("crf_decoding")
    w = helper.main_program.current_block().var(
        param_attr.name if hasattr(param_attr, "name") else str(param_attr))
    out = helper.create_variable_for_type_inference("int64")
    inputs = {"Emission": [input], "Transition": [w]}
    if label is not None:
        inputs["Label"] = [label]
    if length is not None:
        inputs["Length"] = [length]
    helper.append_op("crf_decoding", inputs, {"ViterbiPath": [out]}, {})
    return out


# ---------------------------------------------------------------------------
# Round-4 layers-DSL tail (reference nn.py parity batch)
# ---------------------------------------------------------------------------


def row_conv(input, future_context_size, param_attr=None, act=None):
    """reference nn.py row_conv / row_conv_op.cc: lookahead convolution.
    input [B, T, D]; filter [future_context_size+1, D]."""
    helper = LayerHelper("row_conv")
    dtype = input.dtype
    filt = helper.create_parameter(
        param_attr, [future_context_size + 1, input.shape[-1]], dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op("row_conv", {"X": [input], "Filter": [filt]},
                     {"Out": [out]}, {})
    return helper.append_activation(out, act)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """reference nn.py spectral_norm / spectral_norm_op.*."""
    helper = LayerHelper("spectral_norm", name=name)
    dtype = weight.dtype
    h = weight.shape[dim]
    w = 1
    for i, d in enumerate(weight.shape):
        if i != dim:
            w *= d
    from ..initializer import Normal

    u = helper.create_parameter(
        ParamAttr(name=helper.name + ".u", trainable=False,
                  initializer=Normal(0.0, 1.0)), [h], dtype)
    v = helper.create_parameter(
        ParamAttr(name=helper.name + ".v", trainable=False,
                  initializer=Normal(0.0, 1.0)), [w], dtype)
    u.stop_gradient = True
    v.stop_gradient = True
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "spectral_norm", {"Weight": [weight], "U": [u], "V": [v]},
        {"Out": [out], "UOut": [u], "VOut": [v]},
        {"dim": int(dim), "power_iters": int(power_iters), "eps": float(eps)})
    return out


def data_norm(input, act=None, epsilon=1e-4, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=False):
    """reference nn.py data_norm: normalization from accumulated batch
    counters (CTR models where per-batch stats are too noisy)."""
    helper = LayerHelper("data_norm", name=name)
    dtype = input.dtype
    C = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    defaults = {"batch_size": 1e4, "batch_sum": 0.0, "batch_square": 1e4}
    if isinstance(param_attr, dict):
        defaults.update({k: param_attr.get(k, v)
                         for k, v in defaults.items()})
    bsize = helper.create_parameter(
        ParamAttr(name=helper.name + ".batch_size",
                  initializer=Constant(float(defaults["batch_size"]))),
        [C], dtype)
    bsum = helper.create_parameter(
        ParamAttr(name=helper.name + ".batch_sum",
                  initializer=Constant(float(defaults["batch_sum"]))),
        [C], dtype)
    bsq = helper.create_parameter(
        ParamAttr(name=helper.name + ".batch_square_sum",
                  initializer=Constant(float(defaults["batch_square"]))),
        [C], dtype)
    out = helper.create_variable_for_type_inference(dtype)
    means = helper.create_variable_for_type_inference(dtype)
    scales = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "data_norm",
        {"X": [input], "BatchSize": [bsize], "BatchSum": [bsum],
         "BatchSquareSum": [bsq]},
        {"Y": [out], "Means": [means], "Scales": [scales]},
        {"epsilon": float(epsilon), "data_layout": data_layout})
    return helper.append_activation(out, act)


def center_loss(input, label, num_classes, alpha, param_attr=None,
                update_center=True):
    """reference nn.py center_loss / center_loss_op.h."""
    helper = LayerHelper("center_loss")
    dtype = input.dtype
    centers = helper.create_parameter(
        param_attr, [num_classes, input.shape[-1]], dtype)
    centers.stop_gradient = True
    from .tensor import fill_constant

    if not hasattr(alpha, "name"):
        alpha = fill_constant([1], "float32", float(alpha))
    loss = helper.create_variable_for_type_inference(dtype)
    diff = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "center_loss",
        {"X": [input], "Label": [label], "Centers": [centers],
         "CenterUpdateRate": [alpha]},
        {"Loss": [loss], "SampleCenterDiff": [diff], "CentersOut": [centers]},
        {"need_update": bool(update_center)})
    return loss


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """reference nn.py npair_loss — composed from the same primitives as the
    reference (no bespoke op): soft-target CE over anchor@positive^T
    similarities (targets from label equality, row-normalized) + L2."""
    from .control_flow import equal

    B = labels.shape[0]
    lab = reshape(labels, [B, 1])
    lab = expand(lab, [1, B])
    same = cast(equal(lab, transpose(lab, [1, 0])), "float32")
    target = elementwise_div(
        same, reduce_sum(same, dim=1, keep_dim=True))
    l2 = scale(
        elementwise_add(
            reduce_mean(reduce_sum(square(anchor), dim=1)),
            reduce_mean(reduce_sum(square(positive), dim=1))),
        scale=l2_reg * 0.25)
    sim = matmul(anchor, positive, transpose_y=True)
    ce = softmax_with_cross_entropy(sim, target, soft_label=True)
    return elementwise_add(reduce_mean(ce), l2)


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    return _simple_op("teacher_student_sigmoid_loss",
                      {"X": [input], "Label": [label]},
                      {"soft_max_up_bound": float(soft_max_up_bound),
                       "soft_max_lower_bound": float(soft_max_lower_bound)},
                      out_slot="Y")


def cross_entropy2(input, label, name=None, ignore_index=-100):
    helper = LayerHelper("cross_entropy2", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    match = helper.create_variable_for_type_inference(input.dtype)
    xshape = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("cross_entropy2", {"X": [input], "Label": [label]},
                     {"Y": [out], "MatchX": [match], "XShape": [xshape]},
                     {"ignore_index": ignore_index})
    return out


def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                       num_true=1,
                                       remove_accidental_hits=True,
                                       use_customized_samples=False,
                                       customized_samples=None,
                                       customized_probabilities=None,
                                       seed=0):
    """reference nn.py sampled_softmax_with_cross_entropy: sample_logits op
    + full softmax CE over the sampled vocabulary / num_true."""
    if use_customized_samples:
        raise NotImplementedError(
            "sampled_softmax_with_cross_entropy: use_customized_samples is "
            "not supported (only the log-uniform sampler)")
    helper = LayerHelper("sample_logits")
    samples = helper.create_variable_for_type_inference("int64")
    probabilities = helper.create_variable_for_type_inference(logits.dtype)
    sampled_logits = helper.create_variable_for_type_inference(logits.dtype)
    sampled_label = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        "sample_logits", {"Logits": [logits], "Labels": [label]},
        {"Samples": [samples], "SampledLogits": [sampled_logits],
         "SampledLabel": [sampled_label], "Probabilities": [probabilities]},
        {"num_samples": int(num_samples),
         "remove_accidental_hits": bool(remove_accidental_hits),
         "seed": int(seed)})
    loss = softmax_with_cross_entropy(sampled_logits, sampled_label)
    return scale(loss, scale=1.0 / num_true)


def unique(x, dtype="int32"):
    """reference nn.py unique: host op (data-dependent output extent)."""
    helper = LayerHelper("unique")
    out = helper.create_variable_for_type_inference(x.dtype,
                                                    stop_gradient=True)
    index = helper.create_variable_for_type_inference(dtype,
                                                      stop_gradient=True)
    helper.append_op("unique", {"X": [x]}, {"Out": [out], "Index": [index]},
                     {"dtype": dtype})
    return out, index


def unique_with_counts(x, dtype="int32"):
    helper = LayerHelper("unique_with_counts")
    out = helper.create_variable_for_type_inference(x.dtype,
                                                    stop_gradient=True)
    index = helper.create_variable_for_type_inference(dtype,
                                                      stop_gradient=True)
    count = helper.create_variable_for_type_inference("int64",
                                                      stop_gradient=True)
    helper.append_op("unique_with_counts", {"X": [x]},
                     {"Out": [out], "Index": [index], "Count": [count]},
                     {"dtype": dtype})
    return out, index, count


def hash(input, hash_size, num_hash=1, name=None):
    helper = LayerHelper("hash", name=name)
    out = helper.create_variable_for_type_inference("int64",
                                                    stop_gradient=True)
    helper.append_op("hash", {"X": [input]}, {"Out": [out]},
                     {"num_hash": int(num_hash), "mod_by": int(hash_size)})
    return out


def continuous_value_model(input, cvm, use_cvm=True):
    helper = LayerHelper("cvm")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("cvm", {"X": [input], "CVM": [cvm]}, {"Y": [out]},
                     {"use_cvm": bool(use_cvm)})
    return out


def merge_selected_rows(x, name=None):
    return _simple_op("merge_selected_rows", {"X": [x]})


def get_tensor_from_selected_rows(x, name=None):
    return _simple_op("get_tensor_from_selected_rows", {"X": [x]})


def filter_by_instag(ins, ins_tag, filter_tag, is_lod=True):
    helper = LayerHelper("filter_by_instag")
    out = helper.create_variable_for_type_inference(ins.dtype)
    loss_weight = helper.create_variable_for_type_inference("float32")
    mmap = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        "filter_by_instag",
        {"Ins": [ins], "Ins_tag": [ins_tag], "Filter_tag": [filter_tag]},
        {"Out": [out], "LossWeight": [loss_weight], "IndexMap": [mmap]},
        {"is_lod": bool(is_lod)})
    return out, loss_weight


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """reference nn.py autoincreased_step_counter: persistable int64 counter
    incremented once per executor run."""
    helper = LayerHelper("global_step_counter")
    name = counter_name or "@STEP_COUNTER@"
    counter = helper.create_or_get_global_variable(
        name=name, shape=[1], dtype="int64", persistable=True,
        initializer=Constant(float(begin - step)))
    helper.append_op("increment", {"X": [counter]}, {"Out": [counter]},
                     {"step": float(step)})
    counter.stop_gradient = True
    return counter


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """reference nn.py py_func / py_func_op.cc: run a user Python callable
    as a HOST op inside the program. `out` variables must be pre-created
    (their shapes/dtypes are the user's contract, like the reference)."""
    from ..ops.tensor_ops import register_py_func

    helper = LayerHelper("py_func")
    xs = x if isinstance(x, (list, tuple)) else [x]
    outs = out if isinstance(out, (list, tuple)) else [out]
    fwd_id = register_py_func(func)
    bwd_id = register_py_func(backward_func) if backward_func else -1
    skip = skip_vars_in_backward_input or []
    skip_names = [v if isinstance(v, str) else v.name
                  for v in (skip if isinstance(skip, (list, tuple))
                            else [skip])]
    helper.append_op(
        "py_func", {"X": list(xs)}, {"Out": list(outs)},
        {"forward_callable_id": fwd_id, "backward_callable_id": bwd_id,
         "skip_names": skip_names})
    return out


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """reference nn.py lstm_unit: fc([x, h]) -> 4H gates -> lstm_unit op.
    Returns (hidden_t, cell_t)."""
    helper = LayerHelper("lstm_unit", name=name)
    H = hidden_t_prev.shape[-1]
    concat_in = concat([x_t, hidden_t_prev], axis=-1)
    fc_out = fc(concat_in, size=4 * H, param_attr=param_attr,
                bias_attr=bias_attr)
    hidden = helper.create_variable_for_type_inference(x_t.dtype)
    cell = helper.create_variable_for_type_inference(x_t.dtype)
    helper.append_op(
        "lstm_unit", {"X": [fc_out], "C_prev": [cell_t_prev]},
        {"H": [hidden], "C": [cell]}, {"forget_bias": float(forget_bias)})
    return hidden, cell


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1):
    """reference nn.py lstm (the cudnn_lstm path): stacked/bidirectional
    LSTM over [B, T, D]. Returns (rnn_out, last_h, last_c).

    Deliberate layout divergence from the reference's cudnn flat-weight
    blob: ONE 4H bias per layer/direction is packed instead of cudnn's two
    (b_ih + b_hh, 8H). The cell only ever uses their SUM, so expressiveness
    is identical, but the flat W numel differs — reference-trained
    cudnn_lstm checkpoints cannot be loaded into this layer directly
    (fold b_ih+b_hh into one bias when converting). ADVICE r4."""
    helper = LayerHelper("cudnn_lstm", name=name)
    dtype = input.dtype
    D = input.shape[-1]
    dirs = 2 if is_bidirec else 1
    n_w = 0
    for layer in range(num_layers):
        in_dim = D if layer == 0 else hidden_size * dirs
        n_w += dirs * (in_dim * 4 * hidden_size
                       + hidden_size * 4 * hidden_size + 4 * hidden_size)
    w = helper.create_parameter(
        ParamAttr(name=helper.name + ".w"), [n_w], dtype,
        default_initializer=default_initializer)
    out = helper.create_variable_for_type_inference(dtype)
    last_h = helper.create_variable_for_type_inference(dtype)
    last_c = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "cudnn_lstm",
        {"Input": [input], "W": [w], "InitH": [init_h], "InitC": [init_c]},
        {"Out": [out], "LastH": [last_h], "LastC": [last_c]},
        {"num_layers": int(num_layers), "hidden_size": int(hidden_size),
         "is_bidirec": bool(is_bidirec), "dropout_prob": float(dropout_prob),
         "is_test": bool(is_test), "seed": int(seed)})
    return out, last_h, last_c


def dynamic_lstmp(input, size, proj_size, param_attr=None, bias_attr=None,
                  use_peepholes=False, is_reverse=False,
                  gate_activation="sigmoid", cell_activation="tanh",
                  candidate_activation="tanh", proj_activation="tanh",
                  dtype="float32", name=None):
    """reference nn.py dynamic_lstmp / lstmp_op.cc: LSTM with a learned
    projection on the recurrent path. input [B, T, 4H] pre-projected; size
    is 4*H like dynamic_lstm. Returns (projection [B,T,P], cell [B,T,H])."""
    if use_peepholes:
        raise NotImplementedError(
            "dynamic_lstmp: peephole connections are not implemented "
            "(reference default use_peepholes=True differs; pass False)")
    H = size // 4
    helper = LayerHelper("dynamic_lstmp", name=name)
    weight = helper.create_parameter(param_attr, [proj_size, 4 * H], dtype)
    proj_weight = helper.create_parameter(
        ParamAttr(name=helper.name + ".proj_w"), [H, proj_size], dtype)
    bias = helper.create_parameter(bias_attr, [1, 4 * H], dtype,
                                   is_bias=True)
    proj = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    ins = {"Input": [input], "Weight": [weight],
           "ProjWeight": [proj_weight]}
    if bias is not None:
        ins["Bias"] = [bias]
    helper.append_op(
        "lstmp", ins, {"Projection": [proj], "Cell": [cell]},
        {"is_reverse": bool(is_reverse),
         "gate_activation": gate_activation,
         "cell_activation": cell_activation,
         "candidate_activation": candidate_activation,
         "proj_activation": proj_activation})
    return proj, cell


def edit_distance(input, label, normalized=True, ignored_tokens=None,
                  input_length=None, label_length=None):
    """reference nn.py edit_distance: Levenshtein distance on padded int
    sequences. Returns (distance [B,1] float32, sequence_num [1])."""
    helper = LayerHelper("edit_distance")
    if ignored_tokens:
        erased_in = helper.create_variable_for_type_inference("int64")
        erased_in_len = helper.create_variable_for_type_inference("int64")
        ins = {"X": [input]}
        if input_length is not None:
            ins["Length"] = [input_length]
        helper.append_op("sequence_erase", ins,
                         {"Out": [erased_in], "OutLength": [erased_in_len]},
                         {"tokens": list(ignored_tokens)})
        input, input_length = erased_in, erased_in_len
        erased_lab = helper.create_variable_for_type_inference("int64")
        erased_lab_len = helper.create_variable_for_type_inference("int64")
        ins = {"X": [label]}
        if label_length is not None:
            ins["Length"] = [label_length]
        helper.append_op("sequence_erase", ins,
                         {"Out": [erased_lab], "OutLength": [erased_lab_len]},
                         {"tokens": list(ignored_tokens)})
        label, label_length = erased_lab, erased_lab_len
    dist = helper.create_variable_for_type_inference("float32")
    seq_num = helper.create_variable_for_type_inference("int64")
    ins = {"Hyps": [input], "Refs": [label]}
    if input_length is not None:
        ins["HypsLength"] = [input_length]
    if label_length is not None:
        ins["RefsLength"] = [label_length]
    helper.append_op("edit_distance", ins,
                     {"Out": [dist], "SequenceNum": [seq_num]},
                     {"normalized": bool(normalized)})
    return dist, seq_num


def ctc_greedy_decoder(input, blank, input_length=None, padding_value=-1,
                       name=None):
    """reference nn.py ctc_greedy_decoder: argmax -> merge repeats -> drop
    blanks (ctc_align op). input [B, T, V] probs; returns decoded [B, T]
    padded with -1 (+ the decode lengths when input_length given)."""
    helper = LayerHelper("ctc_greedy_decoder", name=name)
    am = helper.create_variable_for_type_inference("int64",
                                                   stop_gradient=True)
    helper.append_op("arg_max", {"X": [input]}, {"Out": [am]}, {"axis": -1})
    out = helper.create_variable_for_type_inference("int64",
                                                    stop_gradient=True)
    out_len = helper.create_variable_for_type_inference("int64",
                                                        stop_gradient=True)
    ins = {"Input": [am]}
    if input_length is not None:
        ins["InputLength"] = [input_length]
    helper.append_op("ctc_align", ins,
                     {"Output": [out], "OutputLength": [out_len]},
                     {"blank": int(blank),
                      "padding_value": int(padding_value)})
    if input_length is None:
        return out
    return out, out_len


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    """reference nn.py chunk_eval / chunk_eval_op.cc."""
    helper = LayerHelper("chunk_eval")
    precision = helper.create_variable_for_type_inference("float32")
    recall = helper.create_variable_for_type_inference("float32")
    f1 = helper.create_variable_for_type_inference("float32")
    n_infer = helper.create_variable_for_type_inference("int64")
    n_label = helper.create_variable_for_type_inference("int64")
    n_correct = helper.create_variable_for_type_inference("int64")
    ins = {"Inference": [input], "Label": [label]}
    if seq_length is not None:
        ins["SeqLength"] = [seq_length]
    helper.append_op(
        "chunk_eval", ins,
        {"Precision": [precision], "Recall": [recall], "F1-Score": [f1],
         "NumInferChunks": [n_infer], "NumLabelChunks": [n_label],
         "NumCorrectChunks": [n_correct]},
        {"chunk_scheme": chunk_scheme,
         "num_chunk_types": int(num_chunk_types),
         "excluded_chunk_types": list(excluded_chunk_types or [])})
    return precision, recall, f1, n_infer, n_label, n_correct


def match_matrix_tensor(x, y, channel_num, act=None, param_attr=None,
                        dtype="float32", name=None, x_length=None,
                        y_length=None):
    """reference nn.py match_matrix_tensor: out[b,c,i,j] = x_i^T W_c y_j.
    Padded design: x [B, Tx, H], y [B, Ty, H] -> out [B, C, Tx, Ty]."""
    helper = LayerHelper("match_matrix_tensor", name=name)
    H = x.shape[-1]
    w = helper.create_parameter(param_attr, [H, channel_num, H], dtype)
    out = helper.create_variable_for_type_inference(dtype)
    ins = {"X": [x], "Y": [y], "W": [w]}
    if x_length is not None:
        ins["XLength"] = [x_length]
    if y_length is not None:
        ins["YLength"] = [y_length]
    helper.append_op("match_matrix_tensor", ins, {"Out": [out]}, {})
    return helper.append_activation(out, act), w


def tree_conv(nodes_vector, edge_set, output_size, num_filters=1,
              max_depth=2, act="tanh", param_attr=None, bias_attr=None,
              name=None):
    """reference nn.py tree_conv (TBCNN) / tree_conv_op.*."""
    helper = LayerHelper("tree_conv", name=name)
    dtype = nodes_vector.dtype
    F = nodes_vector.shape[2]
    w = helper.create_parameter(param_attr,
                                [F, 3, output_size, num_filters], dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "tree_conv",
        {"NodesVector": [nodes_vector], "EdgeSet": [edge_set],
         "Filter": [w]},
        {"Out": [out]}, {"max_depth": int(max_depth)})
    if bias_attr:
        out = helper.append_bias_op(out, bias_attr)
    return helper.append_activation(out, act)


def affine_grid(theta, out_shape, name=None):
    helper = LayerHelper("affine_grid", name=name)
    out = helper.create_variable_for_type_inference(theta.dtype)
    shape = list(out_shape) if not hasattr(out_shape, "name") else None
    if shape is None:
        raise NotImplementedError(
            "affine_grid: out_shape must be a static list under XLA")
    helper.append_op("affine_grid", {"Theta": [theta]}, {"Output": [out]},
                     {"output_shape": [int(s) for s in shape]})
    return out


def im2sequence(input, filter_size=1, stride=1, padding=0, input_image_size=None,
                out_stride=1, name=None):
    """reference nn.py im2sequence: sliding-window im2col. Padded design
    returns [B, n_windows, C*kh*kw] (the reference flattens the batch into
    the LoD)."""
    os_ = (list(out_stride) if isinstance(out_stride, (list, tuple))
           else [out_stride] * 2)
    if input_image_size is not None or os_ != [1, 1]:
        # the reference uses these for per-image real-size window counts
        # (im2sequence_op.cc batch-LoD path); silently ignoring them would
        # return wrong window counts — refuse like dynamic_lstmp peepholes
        raise NotImplementedError(
            "im2sequence: input_image_size/out_stride (per-image real-size "
            "windows) are not supported on the padded XLA design")

    def _pair(v, n=2):
        return list(v) if isinstance(v, (list, tuple)) else [v] * n

    helper = LayerHelper("im2sequence", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    pad = _pair(padding, 4)
    if len(pad) == 2:
        pad = pad * 2
    helper.append_op("im2sequence", {"X": [input]}, {"Out": [out]},
                     {"kernels": _pair(filter_size),
                      "strides": _pair(stride), "paddings": pad})
    return out


def random_crop(x, shape, seed=None):
    helper = LayerHelper("random_crop")
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("random_crop", {"X": [x]}, {"Out": [out]},
                     {"shape": [int(s) for s in shape],
                      "seed": int(seed) if seed is not None else -1})
    return out


def resize_trilinear(input, out_shape=None, scale=None, name=None,
                     actual_shape=None, align_corners=True, align_mode=1):
    helper = LayerHelper("trilinear_interp", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    od, oh, ow = (out_shape or (0, 0, 0))
    helper.append_op("trilinear_interp", {"X": [input]}, {"Out": [out]},
                     {"out_d": od, "out_h": oh, "out_w": ow,
                      "scale": scale or 0.0,
                      "align_corners": bool(align_corners),
                      "align_mode": int(align_mode)})
    return out


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """reference nn.py image_resize_short: scale so the SHORT side hits
    out_short_len (static shapes: H, W known at build time)."""
    H, W = input.shape[2], input.shape[3]
    short = min(H, W)
    out_shape = [int(round(H * out_short_len / short)),
                 int(round(W * out_short_len / short))]
    return image_resize(input, out_shape=out_shape, resample=resample)


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None):
    """reference nn.py conv3d_transpose / conv_transpose_op.cc 3-D path."""
    def trip(v):
        return list(v) if isinstance(v, (list, tuple)) else [v] * 3

    helper = LayerHelper("conv3d_transpose", name=name)
    dtype = input.dtype
    C = input.shape[1]
    if filter_size is None:
        raise ValueError("conv3d_transpose: filter_size is required "
                         "(output_size-derived filters need dynamic shapes)")
    k = trip(filter_size)
    w = helper.create_parameter(
        param_attr, [C, num_filters // groups] + k, dtype)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "conv3d_transpose", {"Input": [input], "Filter": [w]},
        {"Output": [out]},
        {"strides": trip(stride), "paddings": trip(padding),
         "dilations": trip(dilation), "groups": int(groups)})
    if bias_attr is not False:
        bias = helper.create_parameter(bias_attr, [num_filters], dtype,
                                       is_bias=True)
        tmp = helper.create_variable_for_type_inference(dtype)
        helper.append_op("elementwise_add", {"X": [out], "Y": [bias]},
                         {"Out": [tmp]}, {"axis": 1})
        out = tmp
    return helper.append_activation(out, act)


def adaptive_pool3d(input, pool_size, pool_type="max", require_index=False,
                    name=None):
    return _simple_op("adaptive_pool3d", {"X": [input]},
                      {"pooled_size": list(pool_size),
                       "pooling_type": pool_type})


def deformable_conv(input, offset, mask, num_filters, filter_size, stride=1,
                    padding=0, dilation=1, groups=1, deformable_groups=1,
                    im2col_step=1, param_attr=None, bias_attr=None,
                    modulated=True, name=None):
    """reference nn.py deformable_conv / deformable_conv_op.* (v2)."""
    def _pair(v):
        return list(v) if isinstance(v, (list, tuple)) else [v] * 2

    helper = LayerHelper("deformable_conv", name=name)
    dtype = input.dtype
    C = input.shape[1]
    k = _pair(filter_size)
    w = helper.create_parameter(
        param_attr, [num_filters, C // groups] + k, dtype)
    out = helper.create_variable_for_type_inference(dtype)
    ins = {"Input": [input], "Offset": [offset], "Filter": [w]}
    if mask is not None:
        ins["Mask"] = [mask]
    helper.append_op(
        "deformable_conv", ins, {"Output": [out]},
        {"strides": _pair(stride), "paddings": _pair(padding),
         "dilations": _pair(dilation), "groups": int(groups),
         "deformable_groups": int(deformable_groups),
         "im2col_step": int(im2col_step)})
    if bias_attr:
        bias = helper.create_parameter(bias_attr, [num_filters], dtype,
                                       is_bias=True)
        tmp = helper.create_variable_for_type_inference(dtype)
        helper.append_op("elementwise_add", {"X": [out], "Y": [bias]},
                         {"Out": [tmp]}, {"axis": 1})
        out = tmp
    return out


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype="float32"):
    helper = LayerHelper("gaussian_random_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "gaussian_random_batch_size_like", {"Input": [input]},
        {"Out": [out]},
        {"shape": list(shape), "input_dim_idx": int(input_dim_idx),
         "output_dim_idx": int(output_dim_idx), "mean": float(mean),
         "std": float(std), "seed": int(seed), "dtype": dtype})
    return out


def uniform_random_batch_size_like(input, shape, dtype="float32",
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):
    helper = LayerHelper("uniform_random_batch_size_like")
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "uniform_random_batch_size_like", {"Input": [input]}, {"Out": [out]},
        {"shape": list(shape), "input_dim_idx": int(input_dim_idx),
         "output_dim_idx": int(output_dim_idx), "min": float(min),
         "max": float(max), "seed": int(seed), "dtype": dtype})
    return out
