"""Sequence + beam-search layers (LoD->padding contract).

Python front for ops/sequence_ops.py — re-design of the reference layer fns
(/root/reference/python/paddle/fluid/layers/nn.py sequence_* family,
layers/control_flow.py beam-search usage in the machine-translation book
test). Ragged LoD inputs become [B, T, ...] plus an explicit `length`
tensor; every wrapper documents the mapping.
"""
from __future__ import annotations

from ..layer_helper import LayerHelper

__all__ = [
    "sequence_conv",
    "sequence_slice",
    "sequence_scatter",
    "sequence_expand_as",
    "sequence_enumerate",
    "sequence_reshape",
    "sequence_topk_avg_pooling",
    "sequence_mask",
    "sequence_pad",
    "sequence_unpad",
    "sequence_pool",
    "sequence_reverse",
    "sequence_expand",
    "sequence_softmax",
    "sequence_concat",
    "sequence_first_step",
    "sequence_last_step",
    "beam_search",
    "beam_search_decode",
    "gru_unit",
    "dynamic_gru",
    "dynamic_lstm",
]


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """lengths [B] -> mask [B, maxlen] (reference nn.py sequence_mask).
    maxlen must be static (XLA shapes)."""
    if maxlen is None or (hasattr(maxlen, "shape")):
        raise ValueError(
            "sequence_mask needs a static int maxlen under XLA — pass the "
            "padded time extent")
    helper = LayerHelper("sequence_mask", name=name)
    out = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "sequence_mask", {"X": [x]}, {"Y": [out]},
        {"maxlen": int(maxlen), "out_dtype": dtype})
    return out


def sequence_pad(x, pad_value, maxlen=None, length=None, name=None):
    """Zero the tail beyond `length` with pad_value; returns (Out, Length)
    (reference sequence_pad's (Out, Length) contract; input is already the
    padded dense layout)."""
    helper = LayerHelper("sequence_pad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    ln = helper.create_variable_for_type_inference("int64")
    ins = {"X": [x], "PadValue": [pad_value]}
    if length is not None:
        ins["Length"] = [length]
    helper.append_op("sequence_pad", ins, {"Out": [out], "Length": [ln]},
                     {"padded_length": -1 if maxlen is None else int(maxlen)})
    return out, ln


def sequence_unpad(x, length, name=None):
    """Canonicalize: zero everything beyond `length` (reference
    sequence_unpad returns the ragged LoD tensor; the padded layout stays
    dense here)."""
    helper = LayerHelper("sequence_unpad", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op(
        "sequence_unpad", {"X": [x], "Length": [length]}, {"Out": [out]}, {})
    return out


def sequence_pool(input, pool_type, length=None, name=None):
    """reference nn.py sequence_pool: SUM/AVERAGE/SQRT/MAX/LAST/FIRST over
    the valid region of [B, T, D] given `length` [B] (None = full T)."""
    helper = LayerHelper("sequence_pool", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {"X": [input]}
    if length is not None:
        ins["Length"] = [length]
    helper.append_op("sequence_pool", ins, {"Out": [out]},
                     {"pooltype": str(pool_type).upper()})
    return out


def sequence_first_step(input, length=None):
    return sequence_pool(input, "FIRST", length)


def sequence_last_step(input, length=None):
    return sequence_pool(input, "LAST", length)


def sequence_reverse(x, length=None, name=None):
    helper = LayerHelper("sequence_reverse", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    ins = {"X": [x]}
    if length is not None:
        ins["Length"] = [length]
    helper.append_op("sequence_reverse", ins, {"Y": [out]}, {})
    return out


def sequence_expand(x, times, name=None):
    """Repeat each row `times` times along axis 0 — the beam layout
    (reference sequence_expand with a uniform ref LoD)."""
    helper = LayerHelper("sequence_expand", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("sequence_expand", {"X": [x]}, {"Out": [out]},
                     {"times": int(times)})
    return out


def sequence_softmax(input, length=None, name=None):
    helper = LayerHelper("sequence_softmax", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {"X": [input]}
    if length is not None:
        ins["Length"] = [length]
    helper.append_op("sequence_softmax", ins, {"Out": [out]}, {})
    return out


def sequence_concat(input, name=None):
    helper = LayerHelper("sequence_concat", name=name)
    out = helper.create_variable_for_type_inference(input[0].dtype)
    helper.append_op("sequence_concat", {"X": list(input)}, {"Out": [out]}, {})
    return out


def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                is_first_step=False, name=None):
    """One beam step (reference layers.beam_search / beam_search_op.cc).
    Returns (selected_ids [BW,1], selected_scores [BW,1], parent_idx [BW])."""
    helper = LayerHelper("beam_search", name=name)
    sel_ids = helper.create_variable_for_type_inference("int64")
    sel_scores = helper.create_variable_for_type_inference(scores.dtype)
    parent = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        "beam_search",
        {"pre_ids": [pre_ids], "pre_scores": [pre_scores],
         "ids": [ids], "scores": [scores]},
        {"selected_ids": [sel_ids], "selected_scores": [sel_scores],
         "parent_idx": [parent]},
        {"beam_size": int(beam_size), "end_id": int(end_id),
         "is_first_step": bool(is_first_step)})
    return sel_ids, sel_scores, parent


def beam_search_decode(ids, parent_idx, scores, end_id, name=None):
    """Backtrack stacked per-step (ids, parents) -> full hypotheses
    (reference layers.beam_search_decode). ids/parent_idx/scores: [T, BW]."""
    helper = LayerHelper("beam_search_decode", name=name)
    sent = helper.create_variable_for_type_inference("int64")
    sscores = helper.create_variable_for_type_inference(scores.dtype)
    helper.append_op(
        "beam_search_decode",
        {"Ids": [ids], "ParentIdx": [parent_idx], "Scores": [scores]},
        {"SentenceIds": [sent], "SentenceScores": [sscores]},
        {"end_id": int(end_id)})
    return sent, sscores


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", h_0=None, origin_mode=False,
                name=None):
    """Whole-sequence GRU (reference layers.dynamic_gru / gru_op.cc).
    input: [B, T, 3*size] pre-projected; returns hidden [B, T, size]."""
    helper = LayerHelper("dynamic_gru", name=name)
    dtype = input.dtype
    weight = helper.create_parameter(
        attr=param_attr, shape=[size, 3 * size], dtype=dtype)
    bias = helper.create_parameter(
        attr=bias_attr, shape=[1, 3 * size], dtype=dtype, is_bias=True)
    ins = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        ins["H0"] = [h_0]
    hidden = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "gru", ins, {"Hidden": [hidden]},
        {"is_reverse": bool(is_reverse),
         "gate_activation": gate_activation,
         "activation": candidate_activation,
         "origin_mode": bool(origin_mode)})
    return hidden


def dynamic_lstm(input, size, param_attr=None, bias_attr=None,
                 is_reverse=False, gate_activation="sigmoid",
                 cell_activation="tanh", candidate_activation="tanh",
                 h_0=None, c_0=None, name=None):
    """Whole-sequence LSTM (reference layers.dynamic_lstm / lstm_op.cc).
    input: [B, T, 4*(size//4)] pre-projected; size is 4*hidden like the
    reference. Returns (hidden [B,T,H], cell [B,T,H])."""
    H = size // 4
    helper = LayerHelper("dynamic_lstm", name=name)
    dtype = input.dtype
    weight = helper.create_parameter(
        attr=param_attr, shape=[H, 4 * H], dtype=dtype)
    bias = helper.create_parameter(
        attr=bias_attr, shape=[1, 4 * H], dtype=dtype, is_bias=True)
    ins = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        ins["H0"] = [h_0]
    if c_0 is not None:
        ins["C0"] = [c_0]
    hidden = helper.create_variable_for_type_inference(dtype)
    cell = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "lstm", ins, {"Hidden": [hidden], "Cell": [cell]},
        {"is_reverse": bool(is_reverse),
         "gate_activation": gate_activation,
         "cell_activation": cell_activation,
         "candidate_activation": candidate_activation})
    return hidden, cell


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid", name=None):
    """One GRU step (reference layers.gru_unit / gru_unit_op.cc).

    input: [B, 3*H] (pre-projected x @ W_x), hidden: [B, H]. Returns
    (new_hidden, reset_hidden_pre, gate) like the reference.
    """
    helper = LayerHelper("gru_unit", name=name)
    H = size // 3
    dtype = input.dtype
    weight = helper.create_parameter(
        attr=param_attr, shape=[H, 3 * H], dtype=dtype)
    inputs = {"Input": [input], "HiddenPrev": [hidden], "Weight": [weight]}
    if bias_attr is not False:
        bias = helper.create_parameter(
            attr=bias_attr, shape=[1, 3 * H], dtype=dtype, is_bias=True)
        inputs["Bias"] = [bias]
    new_hidden = helper.create_variable_for_type_inference(dtype)
    reset_pre = helper.create_variable_for_type_inference(dtype)
    gate = helper.create_variable_for_type_inference(dtype)
    helper.append_op(
        "gru_unit", inputs,
        {"Hidden": [new_hidden], "ResetHiddenPrev": [reset_pre],
         "Gate": [gate]},
        {"activation": activation, "gate_activation": gate_activation})
    return new_hidden, reset_pre, gate


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, length=None, name=None):
    """reference nn.py sequence_conv: context-window convolution over time.
    input [B, T, D]; filter [filter_size*D, num_filters]."""
    helper = LayerHelper("sequence_conv", name=name)
    dtype = input.dtype
    filt = helper.create_parameter(
        param_attr, [filter_size * input.shape[-1], num_filters], dtype)
    if padding_start is None:
        padding_start = -int(filter_size // 2)
    out = helper.create_variable_for_type_inference(dtype)
    ins = {"X": [input], "Filter": [filt]}
    if length is not None:
        ins["Length"] = [length]
    helper.append_op(
        "sequence_conv", ins, {"Out": [out]},
        {"contextStride": int(filter_stride),
         "contextStart": int(padding_start),
         "contextLength": int(filter_size)})
    out = helper.append_bias_op(out, bias_attr)
    return helper.append_activation(out, act)


def sequence_slice(input, offset, length, name=None):
    """reference nn.py sequence_slice: per-row sub-sequence, left-aligned
    zero-padded (padding design). Returns the sliced [B, T, ...]."""
    helper = LayerHelper("sequence_slice", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    out_len = helper.create_variable_for_type_inference("int64")
    helper.append_op(
        "sequence_slice",
        {"X": [input], "Offset": [offset], "Length": [length]},
        {"Out": [out], "OutLength": [out_len]}, {})
    return out


def sequence_scatter(input, index, updates, index_length=None, name=None):
    """reference nn.py sequence_scatter: X [B, D] add-scattered at per-row
    positions Ids [B, S] with Updates [B, S]."""
    helper = LayerHelper("sequence_scatter", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {"X": [input], "Ids": [index], "Updates": [updates]}
    if index_length is not None:
        ins["IdsLength"] = [index_length]
    helper.append_op("sequence_scatter", ins, {"Out": [out]}, {})
    return out


def sequence_expand_as(x, y, name=None):
    """reference nn.py sequence_expand_as on the padding contract: each X
    row repeats B_y/B_x times."""
    helper = LayerHelper("sequence_expand_as", name=name)
    out = helper.create_variable_for_type_inference(x.dtype)
    helper.append_op("sequence_expand_as", {"X": [x], "Y": [y]},
                     {"Out": [out]}, {})
    return out


def sequence_enumerate(input, win_size, pad_value=0, length=None, name=None):
    """reference nn.py sequence_enumerate: sliding id windows [B, T, win]."""
    helper = LayerHelper("sequence_enumerate", name=name)
    out = helper.create_variable_for_type_inference(input.dtype,
                                                    stop_gradient=True)
    ins = {"X": [input]}
    if length is not None:
        ins["Length"] = [length]
    helper.append_op("sequence_enumerate", ins, {"Out": [out]},
                     {"win_size": int(win_size), "pad_value": int(pad_value)})
    return out


def sequence_reshape(input, new_dim):
    """reference nn.py sequence_reshape: re-chunk rows to width new_dim."""
    helper = LayerHelper("sequence_reshape")
    out = helper.create_variable_for_type_inference(input.dtype)
    helper.append_op("sequence_reshape", {"X": [input]}, {"Out": [out]},
                     {"new_dim": int(new_dim)})
    return out


def sequence_topk_avg_pooling(input, topks, channel_num, row_length=None,
                              col_length=None, name=None):
    """reference nn.py sequence_topk_avg_pooling on the padding contract:
    input [B, C, R, W] -> [B, R, C*len(topks)] of top-k column averages."""
    helper = LayerHelper("sequence_topk_avg_pooling", name=name)
    out = helper.create_variable_for_type_inference(input.dtype)
    ins = {"X": [input]}
    if row_length is not None:
        ins["RowLength"] = [row_length]
    if col_length is not None:
        ins["ColLength"] = [col_length]
    helper.append_op("sequence_topk_avg_pooling", ins, {"Out": [out]},
                     {"topks": [int(k) for k in topks],
                      "channel_num": int(channel_num)})
    return out
