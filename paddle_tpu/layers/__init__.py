from .nn import *  # noqa: F401,F403
from .tensor import *  # noqa: F401,F403
from .learning_rate_scheduler import *  # noqa: F401,F403
from .control_flow import *  # noqa: F401,F403
from . import control_flow  # noqa: F401
from . import nn  # noqa: F401
from . import tensor  # noqa: F401
from . import learning_rate_scheduler  # noqa: F401
