"""Probability distributions (reference
/root/reference/python/paddle/fluid/layers/distributions.py: Distribution:28,
Uniform:113, Normal:246, Categorical:401, MultivariateNormalDiag:494).

Same API — sample/entropy/log_prob/kl_divergence building graph ops — with
sampling routed through the framework's counter-based PRNG ops
(uniform_random/gaussian_random) so runs stay reproducible under jit.
"""
from __future__ import annotations

import math

import numpy as np

from . import nn as L
from . import tensor as T

__all__ = ["Distribution", "Uniform", "Normal", "Categorical",
           "MultivariateNormalDiag"]


def _to_var(v, dtype="float32"):
    from ..framework import Variable

    if isinstance(v, Variable):
        return v
    arr = np.asarray(v, dtype=np.float32)
    return T.assign(arr)


class Distribution:
    """reference distributions.py:28."""

    def sample(self, shape, seed=0):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError


class Uniform(Distribution):
    """U(low, high) elementwise (reference :113)."""

    def __init__(self, low, high):
        self.low = _to_var(low)
        self.high = _to_var(high)

    def sample(self, shape, seed=0):
        u = T.uniform_random(shape, min=0.0, max=1.0, seed=seed)
        return L.elementwise_add(
            L.elementwise_mul(u, L.elementwise_sub(self.high, self.low)),
            self.low)

    def log_prob(self, value):
        width = L.elementwise_sub(self.high, self.low)
        lb = L.cast(L.greater_than(value, self.low), "float32")
        ub = L.cast(L.less_than(value, self.high), "float32")
        return L.log(L.elementwise_div(L.elementwise_mul(lb, ub), width))

    def entropy(self):
        return L.log(L.elementwise_sub(self.high, self.low))


class Normal(Distribution):
    """N(loc, scale) elementwise (reference :246)."""

    def __init__(self, loc, scale):
        self.loc = _to_var(loc)
        self.scale = _to_var(scale)

    def sample(self, shape, seed=0):
        z = T.gaussian_random(shape, mean=0.0, std=1.0, seed=seed)
        return L.elementwise_add(L.elementwise_mul(z, self.scale), self.loc)

    def entropy(self):
        # 0.5 + 0.5 log(2 pi) + log(scale)
        c = 0.5 + 0.5 * math.log(2 * math.pi)
        return L.scale(L.log(self.scale), scale=1.0, bias=c)

    def log_prob(self, value):
        var = L.elementwise_mul(self.scale, self.scale)
        diff = L.elementwise_sub(value, self.loc)
        return L.scale(
            L.elementwise_add(
                L.elementwise_div(L.elementwise_mul(diff, diff), var),
                L.scale(L.log(var), bias=math.log(2 * math.pi))),
            scale=-0.5)

    def kl_divergence(self, other: "Normal"):
        # KL(p||q) = log(sq/sp) + (sp^2 + (mp-mq)^2)/(2 sq^2) - 1/2
        var_p = L.elementwise_mul(self.scale, self.scale)
        var_q = L.elementwise_mul(other.scale, other.scale)
        diff = L.elementwise_sub(self.loc, other.loc)
        t1 = L.log(L.elementwise_div(other.scale, self.scale))
        t2 = L.elementwise_div(
            L.elementwise_add(var_p, L.elementwise_mul(diff, diff)),
            L.scale(var_q, scale=2.0))
        return L.scale(L.elementwise_add(t1, t2), bias=-0.5)


class Categorical(Distribution):
    """Categorical over the last dim of `logits` (reference :401)."""

    def __init__(self, logits):
        self.logits = logits

    def _probs(self):
        return L.softmax(self.logits)

    def entropy(self):
        p = self._probs()
        logp = L.log(L.scale(p, bias=1e-12))
        return L.scale(L.reduce_sum(L.elementwise_mul(p, logp), dim=-1),
                       scale=-1.0)

    def kl_divergence(self, other: "Categorical"):
        p = self._probs()
        logp = L.log(L.scale(p, bias=1e-12))
        logq = L.log(L.scale(other._probs(), bias=1e-12))
        return L.reduce_sum(
            L.elementwise_mul(p, L.elementwise_sub(logp, logq)), dim=-1)

    def log_prob(self, value):
        """value: int64 indices into the last dim; accepts [B], [B,1], or
        any batched [..., 1]/[...] layout matching logits[..., :-1]."""
        p = self._probs()
        # one_hot itself strips a trailing size-1 dim, so [B,1]->[B,V] and
        # [B]->[B,V] both line up with probs [B,V] (and [B,T] with [B,T,V])
        onehot = L.one_hot(L.cast(value, "int64"),
                           depth=self.logits.shape[-1])
        return L.log(L.scale(
            L.reduce_sum(L.elementwise_mul(p, onehot), dim=-1), bias=1e-12))

    def sample(self, shape=None, seed=0):
        """Gumbel-max sampling: argmax(logits + G), one draw per logits row
        — jit-friendly. (The reference Categorical has no sample(); a
        multi-draw `shape` is not supported.)"""
        if shape:
            raise NotImplementedError(
                "Categorical.sample draws one sample per logits row; "
                "tile the logits for multiple draws")
        from ..layer_helper import LayerHelper

        helper = LayerHelper("categorical_sample")
        u = helper.create_variable_for_type_inference("float32")
        helper.append_op(
            "uniform_random_batch_size_like",
            {"Input": [self.logits]}, {"Out": [u]},
            {"shape": list(self.logits.shape), "min": 1e-6, "max": 1.0,
             "seed": seed})
        g = L.scale(L.log(L.scale(L.log(u), scale=-1.0)), scale=-1.0)
        return L.argmax(L.elementwise_add(self.logits, g), axis=-1)


class MultivariateNormalDiag(Distribution):
    """Diagonal-covariance multivariate normal (reference :494); `scale` is
    the diagonal covariance matrix like the reference (det/inverse read the
    diagonal)."""

    def __init__(self, loc, scale):
        self.loc = _to_var(loc)
        self.scale = _to_var(scale)  # [k, k] diagonal matrix

    def _diag(self):
        k = self.scale.shape[-1]
        eye = T.assign(np.eye(k, dtype=np.float32))
        return L.reduce_sum(L.elementwise_mul(self.scale, eye), dim=-1)

    def entropy(self):
        k = self.scale.shape[-1]
        logdet = L.reduce_sum(L.log(self._diag()))
        return L.scale(logdet, scale=0.5,
                       bias=0.5 * k * (1 + math.log(2 * math.pi)))

    def kl_divergence(self, other: "MultivariateNormalDiag"):
        dp, dq = self._diag(), other._diag()
        diff = L.elementwise_sub(other.loc, self.loc)
        tr = L.reduce_sum(L.elementwise_div(dp, dq))
        quad = L.reduce_sum(
            L.elementwise_div(L.elementwise_mul(diff, diff), dq))
        k = float(self.scale.shape[-1])
        logdet = L.elementwise_sub(L.reduce_sum(L.log(dq)),
                                   L.reduce_sum(L.log(dp)))
        return L.scale(
            L.elementwise_add(L.elementwise_add(tr, quad), logdet),
            scale=0.5, bias=-0.5 * k)
