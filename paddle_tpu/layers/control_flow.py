"""Control-flow layers: While, cond, Switch, StaticRNN, DynamicRNN.

Reference: /root/reference/python/paddle/fluid/layers/control_flow.py
(While:698, Switch:1622, StaticRNN:318, DynamicRNN:1769,
ConditionalBlock:1471). DynamicRNN here is the padding-based equivalent of
the reference's LoD walker: full padded extent through one lax.scan, state
frozen per row once t >= length (see the class docstring)."""
from __future__ import annotations

from ..framework import Variable, default_main_program
from ..layer_helper import LayerHelper

__all__ = ["While", "cond", "Switch", "IfElse", "StaticRNN", "DynamicRNN",
           "less_than", "less_equal",
           "greater_than", "greater_equal", "equal", "not_equal",
           "logical_and", "logical_or", "logical_not", "logical_xor"]


def _compare(op_type, x, y, cond=None):
    """Comparison layer with the reference's optional in-place `cond` output
    (control_flow.py less_than:1007 etc.) — While loops re-assign their
    condition var through it."""
    helper = LayerHelper(op_type)
    if cond is None:
        cond = helper.create_variable_for_type_inference("bool")
    helper.append_op(op_type, {"X": [x], "Y": [y]}, {"Out": [cond]}, {})
    return cond


def less_than(x, y, cond=None, force_cpu=None):
    return _compare("less_than", x, y, cond)


def less_equal(x, y, cond=None):
    return _compare("less_equal", x, y, cond)


def greater_than(x, y, cond=None):
    return _compare("greater_than", x, y, cond)


def greater_equal(x, y, cond=None):
    return _compare("greater_equal", x, y, cond)


def equal(x, y, cond=None):
    return _compare("equal", x, y, cond)


def not_equal(x, y, cond=None):
    return _compare("not_equal", x, y, cond)


def _logical(op_type, x, y, out=None):
    helper = LayerHelper(op_type)
    if out is None:
        out = helper.create_variable_for_type_inference("bool")
    ins = {"X": [x]} if y is None else {"X": [x], "Y": [y]}
    helper.append_op(op_type, ins, {"Out": [out]}, {})
    return out


def logical_and(x, y, out=None, name=None):
    return _logical("logical_and", x, y, out)


def logical_or(x, y, out=None, name=None):
    return _logical("logical_or", x, y, out)


def logical_xor(x, y, out=None, name=None):
    return _logical("logical_xor", x, y, out)


def logical_not(x, out=None, name=None):
    return _logical("logical_not", x, None, out)


class BlockGuard:
    """Enter a fresh sub-block of the main program (reference
    control_flow.py:BlockGuard:24)."""

    def __init__(self, program=None):
        self.program = program or default_main_program()

    def __enter__(self):
        self.block = self.program._create_block()
        return self.block

    def __exit__(self, exc_type, *a):
        self.program._rollback()
        return False


def _block_io(sub_block, parent_block):
    """(reads-from-parent, writes-visible-in-parent) name sets."""
    defined_inside = set()
    reads, writes = [], []
    for op in sub_block.ops:
        for n in op.input_names:
            if n and n not in defined_inside and n not in reads:
                if parent_block.has_var(n) and n not in sub_block.vars:
                    reads.append(n)
        for n in op.output_names:
            if n:
                defined_inside.add(n)
                if (parent_block.has_var(n) and n not in sub_block.vars
                        and n not in writes):
                    writes.append(n)
    return reads, writes


class While:
    """fluid.layers.While (control_flow.py:698):

        cond = L.less_than(i, n)
        w = While(cond)
        with w.block():
            ... body ops, must re-assign `cond` ...
    """

    def __init__(self, cond: Variable, is_test=False, name=None):
        if cond.dtype.value != "bool":
            raise TypeError("While condition must be a bool Variable")
        self.cond_var = cond
        self.helper = LayerHelper("while", name=name)

    def block(self):
        return _WhileGuard(self)


class _WhileGuard(BlockGuard):
    def __init__(self, while_op: While):
        super().__init__()
        self.while_op = while_op

    def __exit__(self, exc_type, *a):
        if exc_type is not None:
            return super().__exit__(exc_type, *a)
        sub_block = self.block
        super().__exit__(exc_type, *a)
        parent = default_main_program().current_block()
        reads, writes = _block_io(sub_block, parent)
        cond_name = self.while_op.cond_var.name
        carried = [n for n in writes]
        if cond_name not in carried:
            carried.append(cond_name)
        # Deps: names READ by the body from the outer scope — listed as inputs
        # so the executor's def-use analysis pulls them into the traced env
        # (the body closes over them; they are not loop-carried)
        deps = [n for n in reads if n not in carried]
        parent.append_op(
            "while",
            {"X": carried, "Condition": [cond_name], "Deps": deps},
            {"Out": carried},
            {"sub_block": sub_block.idx, "dep_names": deps},
        )
        return False


def cond(pred: Variable, true_fn, false_fn=None, name=None):
    """Functional conditional (XLA-native): trace both branches into
    sub-blocks, select with lax.cond. Branch fns take no args and return a
    Variable or tuple of Variables of matching shapes/dtypes."""
    if false_fn is None:
        raise ValueError(
            "cond() requires both branches (XLA traces both); for the "
            "run-only-if-true pattern use conditional_block with outputs "
            "assigned before the block")
    helper = LayerHelper("cond", name=name)
    program = default_main_program()

    with BlockGuard(program) as tb:
        t_out = true_fn()
        t_outs = list(t_out) if isinstance(t_out, (list, tuple)) else [t_out]
    with BlockGuard(program) as fb:
        f_out = false_fn()
        f_outs = list(f_out) if isinstance(f_out, (list, tuple)) else [f_out]
    if len(f_outs) != len(t_outs):
        raise ValueError("true_fn and false_fn must return the same arity")

    parent = program.current_block()
    # A branch that assigns to an outer-scope var (reference ConditionalBlock
    # mutates the scope in place) cannot take conditional effect under
    # lax.cond's functional tracing — only declared return values propagate.
    # Fail loudly instead of silently discarding the write. Checked BEFORE the
    # bridge assigns below (which legitimately write parent-scope out vars).
    _, t_writes = _block_io(tb, parent)
    _, f_writes = _block_io(fb, parent)
    outer_writes = sorted(set(t_writes) | set(f_writes))
    if outer_writes:
        raise ValueError(
            f"cond() branch assigns to outer-scope variable(s) {outer_writes}; "
            "such writes are not propagated (both branches are traced "
            "functionally). Return the value from the branch fn instead.")
    outs = [
        parent.create_var(
            name=helper.name + f".out{i}", shape=v.shape, dtype=v.dtype
        )
        for i, v in enumerate(t_outs)
    ]
    # bridge: sub-block results assigned to the op's Out names inside blocks
    for blk, branch_outs in ((tb, t_outs), (fb, f_outs)):
        for o, src in zip(outs, branch_outs):
            blk.append_op("assign", {"X": [src.name]}, {"Out": [o.name]}, {})
    # Deps AFTER the bridge: a branch fn may return an outer-scope var
    # directly (its only read is the bridge assign itself), and it still must
    # reach the sub-block env via Deps/dep_names.
    deps, _ = _block_io(tb, parent)
    f_deps, _ = _block_io(fb, parent)
    deps = deps + [n for n in f_deps if n not in deps]
    parent.append_op(
        "conditional_block",
        {"Cond": [pred.name], "Deps": deps},
        {"Out": [o.name for o in outs]},
        {"sub_block": tb.idx, "sub_block_false": fb.idx, "dep_names": deps},
    )
    return outs[0] if len(outs) == 1 else outs


class Switch:
    """Reference Switch (control_flow.py:1622): a first-true case ladder,
    used mainly by LR warmup schedules.

        with Switch() as switch:
            with switch.case(cond1):
                tensor.assign(a, lr)
            with switch.default():
                tensor.assign(b, lr)

    Each case body is traced into a sub-block; the switch_case op computes
    every body and merges each written outer var with a nested first-true
    select — the functional XLA equivalent of "execute the first matching
    case". Case bodies must be side-effect-free beyond outer-var writes
    (true for every reference LR schedule)."""

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self._cases = []  # (cond var or None, sub_block)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *a):
        if exc_type is not None:
            return False
        self._build()
        return False

    def case(self, condition):
        if self._cases and self._cases[-1][0] is None:
            raise ValueError("Switch: case() after default()")
        return _SwitchCase(self, condition)

    def default(self):
        return _SwitchCase(self, None)

    def _build(self):
        if not self._cases:
            raise ValueError("Switch: no cases")
        parent = default_main_program().current_block()
        conds = [c for c, _ in self._cases if c is not None]
        has_default = self._cases[-1][0] is None
        blocks = [b for _, b in self._cases]
        # union of outer vars written by any case: those are the outputs
        writes: list[str] = []
        deps: list[str] = []
        for _, blk in self._cases:
            r, w = _block_io(blk, parent)
            for n in w:
                if n not in writes:
                    writes.append(n)
            for n in r:
                if n not in deps:
                    deps.append(n)
        if not writes:
            raise ValueError(
                "Switch: no case assigns to an outer-scope variable")
        deps = [n for n in deps if n not in writes]
        parent.append_op(
            "switch_case",
            {"Conds": [c.name for c in conds], "Deps": deps},
            {"Out": writes},
            {"sub_blocks": [b.idx for b in blocks],
             "has_default": has_default,
             "dep_names": deps},
        )


class _SwitchCase:
    def __init__(self, switch, condition):
        self.switch = switch
        self.condition = condition

    def __enter__(self):
        self._guard = BlockGuard()
        self._block = self._guard.__enter__()
        return self

    def __exit__(self, exc_type, *a):
        self._guard.__exit__(exc_type, *a)
        if exc_type is None:
            self.switch._cases.append((self.condition, self._block))
        return False


class IfElse:
    """Reference IfElse (control_flow.py:1897): per-row conditional.

    The reference physically splits the batch by the [B, 1] bool condition,
    runs each block on its row subset, and merges. Ragged splits defeat XLA,
    so both blocks compute on the FULL batch and the merge selects per row —
    identical results whenever the blocks are row-wise (the documented
    contract; a cross-row reduction inside a block would see all rows).

        ie = IfElse(cond)                 # cond: [B, 1] bool
        with ie.true_block():
            ie.output(f(ie.input(x)))
        with ie.false_block():
            ie.output(g(ie.input(x)))
        out = ie()                         # [B, ...] row-merged
    """

    def __init__(self, cond: Variable, name=None):
        self._cond = cond
        self._outs = {True: [], False: []}
        self._in_branch: bool | None = None

    def _branch(self, flag: bool):
        import contextlib

        @contextlib.contextmanager
        def guard():
            self._in_branch = flag
            try:
                yield
            finally:
                self._in_branch = None

        return guard()

    def true_block(self):
        return self._branch(True)

    def false_block(self):
        return self._branch(False)

    def input(self, x: Variable) -> Variable:
        """The reference returns the rows where cond matches; here the full
        batch flows through (selection happens at the merge)."""
        if self._in_branch is None:
            raise RuntimeError("IfElse.input outside a block")
        return x

    def output(self, *outs):
        if self._in_branch is None:
            raise RuntimeError("IfElse.output outside a block")
        self._outs[self._in_branch].extend(outs)

    def __call__(self):
        from . import nn as _nn

        t, f = self._outs[True], self._outs[False]
        if len(t) != len(f):
            raise ValueError(
                f"IfElse: true block produced {len(t)} outputs, false block "
                f"{len(f)} — they must match")
        res = []
        for tv, fv in zip(t, f):
            cond = self._cond
            # align cond rank to the output ([B,1] vs [B,...]); where()
            # selects, so a NaN/inf in the dead branch cannot leak through
            while len(cond.shape) < len(tv.shape):
                cond = _nn.unsqueeze(cond, axes=[-1])
            res.append(_nn.where(cond, tv, fv))
        return res[0] if len(res) == 1 else res


class StaticRNN:
    """Reference StaticRNN (control_flow.py:318) lowered to lax.scan.

        rnn = StaticRNN()
        with rnn.step():
            word = rnn.step_input(x_seq)   # x_seq: time-major [T, B, D]
            prev = rnn.memory(init=h0)
            h = L.fc([word, prev], size=H, act="tanh")
            rnn.update_memory(prev, h)
            rnn.step_output(h)
        out = rnn()   # [T, B, H]
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self._guard = None
        self._step_inputs = []   # (outer var, inner var)
        self._memories = []      # (init var, pre var, post var or None@idx)
        self._outputs = []       # inner per-step vars
        self._built = False
        self._out_vars = None

    def step(self):
        self._guard = BlockGuard()
        return _StaticRNNGuard(self)

    # -- inside-step API ----------------------------------------------------
    def step_input(self, x: Variable) -> Variable:
        blk = default_main_program().current_block()
        inner = blk.create_var(
            name=self.helper.name + f".in{len(self._step_inputs)}",
            shape=x.shape[1:], dtype=x.dtype)
        self._step_inputs.append((x, inner))
        return inner

    def memory(self, init: Variable) -> Variable:
        blk = default_main_program().current_block()
        if init.name in blk.vars:
            raise ValueError(
                f"StaticRNN memory init '{init.name}' was created inside the "
                f"step block; create it before rnn.step() so it has a value "
                f"at loop entry")
        pre = blk.create_var(
            name=self.helper.name + f".mem{len(self._memories)}",
            shape=init.shape, dtype=init.dtype)
        self._memories.append([init, pre, None])
        return pre

    def update_memory(self, mem: Variable, new: Variable):
        for m in self._memories:
            if m[1].name == mem.name:
                m[2] = new
                return
        raise ValueError(f"{mem.name} is not a StaticRNN memory")

    def step_output(self, out: Variable):
        self._outputs.append(out)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    # -- finalize -----------------------------------------------------------
    def _build(self, sub_block):
        parent = default_main_program().current_block()
        for m in self._memories:
            if m[2] is None:
                raise ValueError(
                    f"memory {m[1].name} never update_memory()'d")
        if not self._step_inputs:
            raise ValueError("StaticRNN needs at least one step_input")
        T = self._step_inputs[0][0].shape[0]
        outs = []
        for i, o in enumerate(self._outputs):
            outs.append(parent.create_var(
                name=self.helper.name + f".out{i}",
                shape=(T,) + tuple(o.shape), dtype=o.dtype))
        finals = [
            parent.create_var(name=self.helper.name + f".final{i}",
                              shape=m[0].shape, dtype=m[0].dtype)
            for i, m in enumerate(self._memories)
        ]
        deps, _ = _block_io(sub_block, parent)
        inner = {i.name for _, i in self._step_inputs} | {m[1].name for m in self._memories}
        deps = [n for n in deps if n not in inner]
        parent.append_op(
            "static_rnn",
            {"StepInputs": [x.name for x, _ in self._step_inputs],
             "InitMemories": [m[0].name for m in self._memories],
             "Deps": deps},
            {"Outputs": [o.name for o in outs],
             "FinalMemories": [f.name for f in finals]},
            {"sub_block": sub_block.idx,
             "dep_names": deps,
             "step_input_names": [i.name for _, i in self._step_inputs],
             "pre_names": [m[1].name for m in self._memories],
             "post_names": [m[2].name for m in self._memories],
             "output_names": [o.name for o in self._outputs]},
        )
        self._out_vars = outs
        self._built = True

    def __call__(self):
        if not self._built:
            raise RuntimeError("call after the step() block closes")
        if len(self._out_vars) == 1:
            return self._out_vars[0]
        return self._out_vars


class _StaticRNNGuard:
    def __init__(self, rnn: StaticRNN):
        self.rnn = rnn

    def __enter__(self):
        self.block = self.rnn._guard.__enter__()
        return self.rnn

    def __exit__(self, exc_type, *a):
        self.rnn._guard.__exit__(exc_type, *a)
        if exc_type is None:
            self.rnn._build(self.block)
        return False


class DynamicRNN:
    """Padding-based equivalent of the reference DynamicRNN
    (control_flow.py:1769).

    The reference walks LoD offsets, shrinking the batch as short sequences
    finish. Ragged iteration defeats XLA, so this runs the full padded
    [B, T, ...] extent through one lax.scan (StaticRNN) and freezes each
    row's state once `t >= length`:
      * memories stop updating (update_memory masks with t < length),
      * step outputs beyond a row's length are zeroed.
    Same observable semantics on the valid region, fixed shapes throughout.

        drnn = DynamicRNN()
        with drnn.block():
            w = drnn.step_input(x, length=lens)   # x: [B, T, D] batch-major
            h = drnn.memory(init=h0)
            h2 = L.fc([w, h], size=H, act="tanh")
            drnn.update_memory(h, h2)
            drnn.output(h2)
        out = drnn()   # [B, T, H], padded tail zeroed
    """

    def __init__(self, name=None):
        from . import nn as _nn  # local import; avoids a cycle at module load
        from . import tensor as _tensor

        self._nn = _nn
        self._tensor = _tensor
        self._rnn = StaticRNN(name=name)
        self._length = None
        self._t = None
        self._in_step = False
        self._output_ranks = []

    def block(self):
        return _DynamicRNNGuard(self)

    # -- inside-block API ----------------------------------------------------
    def _parent_block(self):
        prog = default_main_program()
        sub = prog.current_block()
        return prog.blocks[sub.parent_idx]

    def _parent_transpose(self, x: Variable):
        """Append a batch-major -> time-major transpose to the PARENT block
        (step_input is called inside the step sub-block, but the scan's
        sequence operand must exist outside it)."""
        parent = self._parent_block()
        perm = [1, 0] + list(range(2, len(x.shape)))
        shape = tuple(x.shape[i] for i in perm)
        out = parent.create_var(
            name=self._rnn.helper.name + f".tm{len(self._rnn._step_inputs)}",
            shape=shape, dtype=x.dtype)
        parent.append_op("transpose2", {"X": [x.name]}, {"Out": [out.name]},
                         {"axis": perm})
        return out

    def step_input(self, x: Variable, length: Variable | None = None):
        """x: batch-major [B, T, ...]; optional per-row valid length [B]."""
        if not self._in_step:
            raise RuntimeError("step_input must be called inside block()")
        if x.shape[0] is None or x.shape[0] < 0:
            raise ValueError(
                "DynamicRNN.step_input needs a static batch size (got "
                f"shape {x.shape}): set var.shape = (B, T, ...) before the "
                "block — per-step layers infer parameter shapes from it")
        if length is not None:
            if self._length is not None:
                raise ValueError("DynamicRNN already has a length input")
            self._length = length
            # per-step scalar time index, scanned alongside the data
            parent = self._parent_block()
            T = x.shape[1]
            t_seq = parent.create_var(
                name=self._rnn.helper.name + ".tseq", shape=(T,),
                dtype="int64")
            parent.append_op(
                "range", {}, {"Out": [t_seq.name]},
                {"start": 0.0, "end": float(T), "step": 1.0,
                 "dtype": "int64"})
            self._t_inner = self._rnn.step_input(t_seq)   # scalar per step
            self._len_inner = length
        return self._rnn.step_input(self._parent_transpose(x))

    def memory(self, init: Variable):
        return self._rnn.memory(init)

    def update_memory(self, mem: Variable, new: Variable):
        if self._length is not None:
            live = self._nn.cast(
                less_than(self._t_inner, self._len_inner), new.dtype)
            for _ in range(len(mem.shape) - 1):
                live = self._nn.unsqueeze(live, axes=[-1])
            new = self._nn.elementwise_add(
                self._nn.elementwise_mul(new, live),
                self._nn.elementwise_mul(mem, 1.0 - live))
        self._rnn.update_memory(mem, new)

    def output(self, *outs):
        for o in outs:
            rank = len(o.shape)  # recorded pre-mask: the mask ops' build
            # shapes can be unknown inside the sub-block
            if self._length is not None:
                live = self._nn.cast(
                    less_than(self._t_inner, self._len_inner), o.dtype)
                for _ in range(rank - 1):
                    live = self._nn.unsqueeze(live, axes=[-1])
                o = self._nn.elementwise_mul(o, live)
            self._rnn.step_output(o)
            self._output_ranks.append(rank)

    def __call__(self):
        outs = self._rnn()
        outs = outs if isinstance(outs, list) else [outs]
        # back to batch-major [B, T, ...]; rank from the recorded inner
        # step outputs (outer build shapes may be unknown when inference
        # failed inside the sub-block)
        res = []
        for o, inner_rank in zip(outs, self._output_ranks):
            rank = inner_rank + 1
            res.append(self._nn.transpose(
                o, perm=[1, 0] + list(range(2, rank))))
        return res[0] if len(res) == 1 else res


class _DynamicRNNGuard:
    def __init__(self, drnn: DynamicRNN):
        self.d = drnn

    def __enter__(self):
        d = self.d
        d._in_step = True
        d._guard = d._rnn.step()
        # pre-step plumbing happens lazily on first step_input
        d._entered = d._guard.__enter__()
        return d

    def __exit__(self, exc_type, *a):
        self.d._in_step = False
        return self.d._guard.__exit__(exc_type, *a)
