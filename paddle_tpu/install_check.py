"""Post-install smoke check.

Parity with /root/reference/python/paddle/fluid/install_check.py
(run_check:43): build a tiny linear-regression program, run a few real train
steps on the default device, and — when more than one device is visible —
repeat the run through CompiledProgram data parallelism, so the check
exercises the same executor/compiler stack a real job uses. Prints the
reference's success message; raises with a pointed hint on failure.
"""
from __future__ import annotations

import numpy as np

__all__ = ["run_check"]


def _train_tiny(parallel: bool) -> float:
    import paddle_tpu as pt
    from paddle_tpu import layers

    main, startup = pt.Program(), pt.Program()
    with pt.program_guard(main, startup), pt.unique_name.guard():
        x = layers.data(name="inp", shape=[2], dtype="float32")
        hidden = layers.fc(x, size=4)
        out = layers.fc(hidden, size=1)
        loss = layers.mean(layers.square(out))
        pt.optimizer.SGD(learning_rate=0.01).minimize(loss)
    prog = main
    if parallel:
        prog = pt.CompiledProgram(main).with_data_parallel(
            loss_name=loss.name)
    exe = pt.Executor()
    rng = np.random.default_rng(0)
    with pt.scope_guard(pt.Scope()):
        exe.run(startup)
        for _ in range(2):
            (lv,) = exe.run(
                prog,
                feed={"inp": rng.standard_normal((8, 2)).astype(np.float32)},
                fetch_list=[loss])
    return float(np.asarray(lv).reshape(-1)[0])


def run_check():
    """reference install_check.py:43 — 'to check whether fluid is installed
    correctly'."""
    import jax

    print("Running verify paddle_tpu program ... ")
    lv = _train_tiny(parallel=False)
    if not np.isfinite(lv):
        raise RuntimeError(
            "single-device check produced a non-finite loss — the XLA "
            "backend is misconfigured (check JAX_PLATFORMS and the device "
            "runtime)")
    n_dev = len(jax.devices())
    if n_dev > 1:
        lv = _train_tiny(parallel=True)
        if not np.isfinite(lv):
            raise RuntimeError(
                f"data-parallel check failed across {n_dev} devices — "
                f"single-device training works, so suspect the mesh/GSPMD "
                f"configuration (XLA_FLAGS, process count)")
        print(f"Your paddle_tpu works well on MUTIPLE {n_dev} devices.")
    else:
        print("Your paddle_tpu works well on SINGLE device.")
    print("Your paddle_tpu is installed successfully!")


if __name__ == "__main__":
    run_check()
