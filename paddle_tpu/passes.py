"""Whole-program graph rewrites that change the HLO XLA sees.

Most reference IR passes (~45 of them) are subsumed by XLA fusion and need no
analogue here (SURVEY §7). The passes in this module exist because they alter
the *structure* XLA optimizes — value provenance and op adjacency — which
fusion alone cannot recover:

  * fuse_conv_bn_stats: conv2d -> batch_norm(training) pairs become one
    conv2d_bn op whose batch statistics are computed in the conv's epilogue
    (on the implicit-GEMM path: from the fp32 GEMM accumulator before the
    low-precision down-cast). The standalone batch_norm reads the conv
    output back from HBM for its E[x]/E[x^2] reductions — measured at
    17-35% of ResNet-50 stage time (PERF.md r5, tools/_rn_diag.py).
  * fuse_epilogue_act (ISSUE 9): norm -> relu and norm -> residual-add ->
    relu chains collapse into the norm op (attr `act`, input `Residual`),
    whose lowering then dispatches the WHOLE apply chain through the
    fused-epilogue tuner lever (ops/nn_ops._bn_epilogue) — one Pallas
    kernel visit where a swept verdict keeps it, the bit-identical XLA
    composition everywhere else. This is the structural half of the
    ResNet BN/elementwise-tail attack: without the rewrite, the residual
    add and the relu live in other ops and the kernel has nothing to fuse.

Runs at minimize() time, before append_backward (the fused op's gradient
derives via vjp over the fused lowering) and after any AMP rewrite (so the
pattern sees final dtypes; AMP's casts between the pair target BN's
Scale/Bias side inputs, never the conv->BN activation edge).
"""
from __future__ import annotations

import warnings
import zlib

from . import flags

__all__ = ["fuse_conv_bn_stats", "fuse_epilogue_act",
           "rewrite_tiered_embeddings", "apply_minimize_passes"]


def _writes(op, name: str) -> bool:
    return any(name in ns for ns in op.outputs.values())


def _reads(op, name: str) -> bool:
    return any(name in ns for ns in op.inputs.values())


def _match_bn_consumer(block, conv_idx: int, out_name: str):
    """Index of the single batch_norm(training) consuming `out_name`, or None.

    Requirements for a semantics-preserving merge:
      * out_name has exactly one reader in the block and none elsewhere in
        the program (it disappears from the graph);
      * that reader is a training-mode batch_norm whose layout matches the
        conv's data_format;
      * no op between producer and consumer redefines the conv's inputs or
        touches out_name (the conv's computation is moved to the BN's
        position).
    """
    conv = block.ops[conv_idx]
    readers = []
    for b in block.program.blocks:
        for i, op in enumerate(b.ops):
            if op is not conv and _reads(op, out_name):
                readers.append((b, i, op))
            if op is not conv and _writes(op, out_name):
                return None
    if len(readers) != 1:
        return None
    b, bn_idx, bn = readers[0]
    if b is not block or bn_idx <= conv_idx or bn.type != "batch_norm":
        return None
    if bn.input("X") != [out_name]:
        return None
    if bn.attr("is_test", False):
        return None  # inference BN has no statistics pass to fuse
    if bn.attr("data_layout", "NCHW") != conv.attr("data_format", "NCHW"):
        return None
    moved = set(conv.input("Input") + conv.input("Filter"))
    for mid in block.ops[conv_idx + 1:bn_idx]:
        if any(_writes(mid, n) for n in moved):
            return None
    return bn_idx


def _fusion_wanted(block, conv, out_name: str) -> bool:
    """Per-pair tuner consult (FLAGS_tuning_mode != off): a swept-DB entry
    can retire the epilogue fusion for a specific conv shape where the
    measured A/B showed XLA declining the multi-output fusion (the PERF.md
    r6 open question), while every other shape keeps it. The analytic prior
    is the flag default — fuse — so with no DB entry behavior is unchanged.
    FLAGS_bn_fuse_stats stays the master switch: the tuner refines per
    shape, it does not resurrect a globally-retired lever."""
    from . import tuning

    if tuning.mode() == "off":
        return True
    in_shape = list(block.var(conv.input("Input")[0]).shape or [])
    w_shape = list(block.var(conv.input("Filter")[0]).shape or [])
    fmt = conv.attr("data_format", "NCHW")
    if len(in_shape) == 4 and len(w_shape) == 4:
        if fmt == "NCHW":
            n, cin = in_shape[0], in_shape[1]
            cout, kh, kw = w_shape[0], w_shape[2], w_shape[3]
        else:
            n, cin = in_shape[0], in_shape[3]
            kh, kw, cout = w_shape[0], w_shape[1], w_shape[3]
    else:  # malformed declaration: leave the decision to the default
        n = cin = cout = kh = kw = -1
    strides = conv.attr("strides", [1, 1])
    dil = conv.attr("dilations", [1, 1])
    out_var = block.var(out_name)
    out_shape = list(out_var.shape or [])
    hout, wout = (out_shape[2], out_shape[3]) if fmt == "NCHW" and \
        len(out_shape) == 4 else (out_shape[1], out_shape[2]) if \
        len(out_shape) == 4 else (-1, -1)
    key = tuning.canonical_key(
        "conv2d_bn_fusion",
        tuning.conv_key(n, hout, wout, cin, cout, kh, kw, strides, dil, fmt),
        str(out_var.dtype.value), tuning.device_kind())
    decision, _tier = tuning.decide(
        "conv2d_bn_fusion", key,
        prior=lambda: {"fuse": True},
        default={"fuse": True},
        validate=lambda dd: isinstance(dd.get("fuse"), bool))
    return bool(decision.get("fuse", True))


def fuse_conv_bn_stats(program) -> int:
    """Rewrite every eligible conv2d -> batch_norm(training) pair into one
    conv2d_bn op (ops/nn_ops.py). Returns the number of pairs fused. The
    orphaned conv-output var stays declared in the block (harmless; it no
    longer has a producer, like any pruned intermediate)."""
    n_fused = 0
    for block in program.blocks:
        i = 0
        while i < len(block.ops):
            conv = block.ops[i]
            if conv.type != "conv2d":
                i += 1
                continue
            out_name = conv.output("Output")[0]
            bn_idx = _match_bn_consumer(block, i, out_name)
            if bn_idx is None:
                i += 1
                continue
            if not _fusion_wanted(block, conv, out_name):
                i += 1
                continue
            bn = block.ops[bn_idx]
            inputs = {
                "Input": conv.input("Input"),
                "Filter": conv.input("Filter"),
                "Scale": bn.input("Scale"),
                "Bias": bn.input("Bias"),
                "Mean": bn.input("Mean"),
                "Variance": bn.input("Variance"),
            }
            outputs = {
                "Y": bn.output("Y"),
                "MeanOut": bn.output("MeanOut"),
                "VarianceOut": bn.output("VarianceOut"),
                "SavedMean": bn.output("SavedMean"),
                "SavedVariance": bn.output("SavedVariance"),
            }
            attrs = {
                "strides": conv.attr("strides", [1, 1]),
                "paddings": conv.attr("paddings", [0, 0]),
                "dilations": conv.attr("dilations", [1, 1]),
                "groups": conv.attr("groups", 1),
                "data_format": conv.attr("data_format", "NCHW"),
                "epsilon": bn.attr("epsilon", 1e-5),
                "momentum": bn.attr("momentum", 0.9),
            }
            # replace the BN in place (every fused input's producer precedes
            # it), then drop the conv
            del block.ops[bn_idx]
            block._insert_op(bn_idx, "conv2d_bn", inputs, outputs, attrs)
            del block.ops[i]
            n_fused += 1
            # stay at i: the next op shifted into this slot
    if n_fused:
        program._bump_version()
    return n_fused


# norm ops the epilogue rewrite folds a trailing activation into, and the
# activations the fused lowering (ops/nn_ops._EPILOGUE_ACTS) can carry
_EPILOGUE_NORM_OPS = ("batch_norm", "conv2d_bn", "layer_norm")
_EPILOGUE_ACT_OPS = ("relu",)


def _sole_reader(block, producer, out_name: str):
    """(block_idx, op) of the single op reading `out_name`, or None — and
    None as well if anything else WRITES it (the var must disappear
    cleanly when the chain collapses)."""
    readers = []
    for b in block.program.blocks:
        for i, op in enumerate(b.ops):
            if op is not producer and _reads(op, out_name):
                readers.append((b, i, op))
            if op is not producer and _writes(op, out_name):
                return None
    if len(readers) != 1 or readers[0][0] is not block:
        return None
    return readers[0][1], readers[0][2]


def _inputs_stable(block, names, lo: int, hi: int) -> bool:
    """No op in block.ops(lo, hi] redefines any of `names` (the fused op is
    moved to position hi, so every input must still hold its value there)."""
    for mid in block.ops[lo + 1:hi + 1]:
        if any(_writes(mid, n) for n in names):
            return False
    return True


def fuse_epilogue_act(program) -> int:
    """Collapse norm -> [same-shape residual add ->] relu chains into the
    norm op. Returns the number of chains fused.

    Two patterns, both requiring every intermediate var to have exactly one
    reader (it vanishes from the graph):
      * norm -> relu:          norm gains attr act, adopts relu's output.
      * norm -> add -> relu:   norm additionally gains input Residual (the
        add's other operand) and MOVES to the relu's position — the
        residual branch (e.g. a ResNet shortcut conv) is built after the
        main branch, so its value does not exist at the norm's old index.
    """
    n_fused = 0
    for block in program.blocks:
        i = 0
        while i < len(block.ops):
            norm = block.ops[i]
            if norm.type not in _EPILOGUE_NORM_OPS or norm.attr("act", ""):
                i += 1
                continue
            y_name = norm.output("Y")[0]
            hit = _sole_reader(block, norm, y_name)
            if hit is None:
                i += 1
                continue
            j, consumer = hit
            if j <= i:
                i += 1
                continue
            norm_inputs = [n for ns in norm.inputs.values() for n in ns]
            if consumer.type in _EPILOGUE_ACT_OPS:
                if not _inputs_stable(block, norm_inputs, i, j - 1):
                    i += 1
                    continue
                norm.attrs["act"] = consumer.type
                norm.outputs["Y"] = list(consumer.output("Out"))
                del block.ops[j]
                n_fused += 1
                continue  # re-examine i: the fused op could chain further
            if consumer.type != "elementwise_add" or norm.type == "layer_norm":
                # the residual-add fold exists for the BN apply kernels;
                # layer_norm's lowering carries no Residual slot
                i += 1
                continue
            # residual pattern: the add must be same-shape (axis -1/0) and
            # feed exactly one relu
            xs, ys = consumer.input("X"), consumer.input("Y")
            if len(xs) != 1 or len(ys) != 1:
                i += 1
                continue
            other = ys[0] if xs[0] == y_name else xs[0]
            if other == y_name:
                i += 1
                continue
            try:
                if (tuple(block.var(other).shape)
                        != tuple(block.var(y_name).shape)):
                    i += 1
                    continue
            except KeyError:
                i += 1
                continue
            if consumer.attr("axis", -1) not in (-1, 0):
                i += 1
                continue
            add_out = consumer.output("Out")[0]
            hit2 = _sole_reader(block, consumer, add_out)
            if hit2 is None:
                i += 1
                continue
            k, act_op = hit2
            if act_op.type not in _EPILOGUE_ACT_OPS or k <= j:
                i += 1
                continue
            if not _inputs_stable(block, norm_inputs, i, k) or \
                    not _inputs_stable(block, [other], j, k):
                i += 1
                continue
            norm.attrs["act"] = act_op.type
            norm.inputs["Residual"] = [other]
            norm.outputs["Y"] = list(act_op.output("Out"))
            # move the fused op to the relu's slot (the residual operand is
            # defined by then); drop relu, add, and the original position
            inputs = {s: list(ns) for s, ns in norm.inputs.items()}
            outputs = {s: list(ns) for s, ns in norm.outputs.items()}
            attrs = dict(norm.attrs)
            del block.ops[k]
            block._insert_op(k, norm.type, inputs, outputs, attrs)
            del block.ops[j]
            del block.ops[i]
            n_fused += 1
            # stay at i: the next op shifted into this slot
    if n_fused:
        program._bump_version()
    return n_fused


# -- tiered giant embeddings (ISSUE 10) --------------------------------------

_LOOKUP_OPS = ("lookup_table", "lookup_table_v2")


def _host_init_spec(startup_program, wname: str):
    """The numpy rendering of `wname`'s startup init op — which this pass
    REMOVES (the host tier owns the giant table; materializing it on the
    device first would be exactly the HBM blow-up tiering exists to avoid).
    Returns (spec tuple, values-or-None) — values for assign_value inits."""
    import numpy as np

    if startup_program is None:
        warnings.warn(
            f"tiered embedding '{wname}': no startup program in scope — "
            f"host tier initializes to zeros", stacklevel=3)
        return ("constant", 0.0), None
    sblock = startup_program.global_block
    for idx, op in enumerate(sblock.ops):
        if wname not in op.output_names:
            continue
        spec, values = None, None
        if op.type == "uniform_random":
            spec = ("uniform", float(op.attr("min", -1.0)),
                    float(op.attr("max", 1.0)))
        elif op.type in ("gaussian_random", "truncated_gaussian_random"):
            spec = ("gaussian", float(op.attr("mean", 0.0)),
                    float(op.attr("std", 1.0)))
        elif op.type == "fill_constant":
            spec = ("constant", float(op.attr("value", 0.0)))
        elif op.type == "assign_value":
            spec = ("constant", 0.0)
            values = np.asarray(op.attr("values"), np.float32).reshape(
                op.attr("shape"))
        if spec is None:
            warnings.warn(
                f"tiered embedding '{wname}': unrecognized init op "
                f"'{op.type}' — host tier initializes to zeros",
                stacklevel=3)
            spec = ("constant", 0.0)
        del sblock.ops[idx]
        startup_program._bump_version()
        return spec, values
    return ("constant", 0.0), None


def _tiered_geometry(wname: str, vocab: int, dim: int, itemsize: int,
                     dtype_str: str, budget_mb: float):
    """(slots, prefetch_rows) for one table: FLAGS_emb_cache_slots is a hard
    force; otherwise the budget-derived count is the analytic prior and a
    swept 'embedding|table=..' DB verdict refines it (the PR 6 contract)."""
    from . import tuning

    row_bytes = max(1, dim * itemsize)
    analytic = max(1, min(int(budget_mb * 2**20 // row_bytes), vocab))
    prefetch = int(flags.get_flag("emb_prefetch_rows"))
    forced = int(flags.get_flag("emb_cache_slots"))
    if forced > 0:
        return forced, prefetch
    if tuning.mode() == "off":
        return analytic, prefetch
    key = tuning.canonical_key(
        "embedding", tuning.embedding_key(wname, vocab, dim), dtype_str,
        tuning.device_kind())
    decision, _tier = tuning.decide(
        "embedding", key,
        prior=lambda: {"slots": analytic, "prefetch_rows": prefetch},
        default={"slots": analytic, "prefetch_rows": prefetch},
        validate=lambda d: isinstance(d.get("slots"), int)
        and d["slots"] > 0)
    return (int(decision.get("slots", analytic)),
            int(decision.get("prefetch_rows", prefetch) or prefetch))


def rewrite_tiered_embeddings(program, startup_program=None) -> int:
    """Rewrite every lookup_table over a table above FLAGS_emb_hbm_budget_mb
    onto the two-tier path (ISSUE 10). Per oversized table, the program
    gains:

      * a `[slots+1, dim]` trainable cache Parameter `<W>@CACHE` (row
        `slots` is the masked scratch row), zero-filled by the startup
        program — whose original `<W>` init op is REMOVED and its
        distribution re-drawn into the host tier (numpy, deterministic);
      * one `emb_cache_install` op landing the per-batch prefetch feeds
        (`<W>@PREFETCH_ROWS` / `<W>@PREFETCH_SLOTS`) in the cache and
        emitting the evicted rows (`<W>@EVICTED`, persistable so the engine
        can write them back to the host tier);
      * each lookup rewritten to `tiered_lookup` over a per-ids-feed slot
        feed (`<W>@SLOTS@<ids>`), resolved off the step by the engine.

    Tables at or under the budget are untouched — with no oversized table
    the program is bitwise-identical to the no-tiering build (the opt-in
    contract). Returns the number of lookups rewritten."""
    budget_mb = float(flags.get_flag("emb_hbm_budget_mb"))
    if budget_mb <= 0:
        return 0
    import numpy as np

    from .core.types import np_dtype
    from .embedding import HostShardedTable, TieredEmbeddingEngine

    if startup_program is None:
        from .framework import default_startup_program

        startup_program = default_startup_program()
    block = program.global_block
    engine = getattr(program, "_tiered_engine", None)
    n = 0
    i = 0
    while i < len(block.ops):
        op = block.ops[i]
        if op.type not in _LOOKUP_OPS or op.attr("is_distributed", False):
            i += 1
            continue
        wname = op.input("W")[0]
        try:
            w = block.var(wname)
        except KeyError:
            i += 1
            continue
        shape = list(w.shape or [])
        if len(shape) != 2 or any(d is None or d <= 0 for d in shape):
            i += 1
            continue
        vocab, dim = int(shape[0]), int(shape[1])
        itemsize = np.dtype(np_dtype(w.dtype)).itemsize
        if vocab * dim * itemsize <= budget_mb * 2**20:
            i += 1
            continue
        ids_name = op.input("Ids")[0]
        try:
            ids_var = block.var(ids_name)
        except KeyError:
            ids_var = None
        if ids_var is None or not getattr(ids_var, "is_data", False):
            warnings.warn(
                f"tiered embedding: table '{wname}' exceeds the HBM budget "
                f"but its ids ('{ids_name}') are computed in-graph, not "
                f"fed — the host-side resolver cannot see them; leaving "
                f"this lookup dense", stacklevel=3)
            i += 1
            continue

        if engine is None:
            engine = TieredEmbeddingEngine(program)
            program._tiered_engine = engine
        first = wname not in engine.tables
        if first:
            slots, prefetch = _tiered_geometry(
                wname, vocab, dim, itemsize, str(w.dtype.value), budget_mb)
            slots = max(1, min(int(slots), vocab))
            cache_name = wname + "@CACHE"
            rows_name = wname + "@PREFETCH_ROWS"
            slots_name = wname + "@PREFETCH_SLOTS"
            evict_name = wname + "@EVICTED"
            block.create_parameter(
                shape=[slots + 1, dim], dtype=w.dtype, name=cache_name,
                trainable=True)
            block.create_var(name=rows_name, shape=[-1, dim],
                             dtype=w.dtype, stop_gradient=True)
            block.create_var(name=slots_name, shape=[-1], dtype="int32",
                             stop_gradient=True)
            block.create_var(name=evict_name, shape=[-1, dim],
                             dtype=w.dtype, persistable=True,
                             stop_gradient=True)
            if startup_program is not None:
                sblock = startup_program.global_block
                sblock.create_var(name=cache_name, shape=[slots + 1, dim],
                                  dtype=w.dtype, persistable=True)
                sblock.append_op(
                    "fill_constant", outputs={"Out": [cache_name]},
                    attrs={"shape": [slots + 1, dim],
                           "dtype": w.dtype.value, "value": 0.0})
            init_spec, init_values = _host_init_spec(startup_program, wname)
            host = HostShardedTable(
                wname, vocab, dim, dtype=np_dtype(w.dtype),
                num_shards=int(flags.get_flag("emb_host_shards")),
                init=init_spec,
                seed=(program.random_seed or 0)
                ^ zlib.crc32(wname.encode()))
            if init_values is not None:
                host.load_rows(np.arange(vocab), init_values)
                host.clear_dirty()
            engine.add_table(wname, host, slots, cache_name, rows_name,
                             slots_name, evict_name, prefetch)
            if getattr(w, "trainable", None):
                w.trainable = False  # the cache is the trained Parameter
        ts = engine.tables[wname]
        slot_feed = f"{wname}@SLOTS@{ids_name}"
        block.create_var(name=slot_feed, shape=list(ids_var.shape),
                         dtype="int32", stop_gradient=True)
        engine.add_lookup(wname, ids_name, slot_feed,
                          op.attr("padding_idx", -1))
        out_names = list(op.output("Out"))
        del block.ops[i]
        block._insert_op(
            i, "tiered_lookup",
            {"Cache": [ts.cache_var], "SlotIds": [slot_feed]},
            {"Out": out_names},
            {"scratch_slot": ts.scratch, "table": wname})
        if first:
            # the install lands BEFORE the table's first gather; feeds and
            # the cache param are defined from step entry, so position i is
            # always safe
            block._insert_op(
                i, "emb_cache_install",
                {"Cache": [ts.cache_var], "Rows": [ts.rows_var],
                 "Slots": [ts.slots_var]},
                {"Out": [ts.cache_var], "Evicted": [ts.evict_var]},
                {"table": wname})
            i += 1
        n += 1
        i += 1
    if n:
        program._bump_version()
    return n


def _epilogue_pass_wanted() -> bool:
    """The rewrite runs when the fused lowering could ever pick the kernel:
    FLAGS_pallas_epilogue 'on' (forced A/B arms), or 'auto' with the tuner
    consulting/sweeping (a swept DB verdict is the only thing that turns
    the kernel on — the r5 ships-off-by-default rule). With tuning off the
    program keeps its exact pre-workbench structure."""
    mode = str(flags.get_flag("pallas_epilogue")).strip().lower()
    if mode == "off":
        return False
    if mode == "on":
        return True
    from . import tuning

    return tuning.mode() != "off"


def apply_minimize_passes(program) -> None:
    """Flag-gated pass pipeline run once per minimize()/backward() on the
    main program (optimizer.Optimizer.backward — the single choke point both
    the plain and the AMP-decorated paths flow through)."""
    if float(flags.get_flag("emb_hbm_budget_mb")) > 0 and not getattr(
            program, "_emb_tiered", False):
        program._emb_tiered = True  # idempotent across re-entry
        rewrite_tiered_embeddings(program)
    if flags.get_flag("bn_fuse_stats") and not getattr(
            program, "_bn_stats_fused", False):
        program._bn_stats_fused = True  # idempotent across re-entry
        fuse_conv_bn_stats(program)
    if _epilogue_pass_wanted() and not getattr(
            program, "_epilogue_fused", False):
        program._epilogue_fused = True  # idempotent across re-entry
        fuse_epilogue_act(program)
