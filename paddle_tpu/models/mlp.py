"""MNIST MLP + convnet — BASELINE config 1 (reference
python/paddle/fluid/tests/book/test_recognize_digits.py)."""
from __future__ import annotations

from .. import layers as L

__all__ = ["mnist_mlp", "mnist_conv"]


def mnist_mlp(img=None, label=None, hidden_sizes=(128, 64), num_classes=10):
    """Softmax-regression MLP; returns (avg_loss, accuracy, logits)."""
    if img is None:
        img = L.data(name="img", shape=[784], dtype="float32")
    if label is None:
        label = L.data(name="label", shape=[1], dtype="int64")
    h = img
    for size in hidden_sizes:
        h = L.fc(h, size=size, act="relu")
    logits = L.fc(h, size=num_classes)
    loss = L.softmax_with_cross_entropy(logits, label)
    avg_loss = L.mean(loss)
    acc = L.accuracy(logits, label)
    return avg_loss, acc, logits


def mnist_conv(img=None, label=None, num_classes=10):
    """LeNet-ish conv net (reference book test `conv` variant)."""
    from ..nets import simple_img_conv_pool

    if img is None:
        img = L.data(name="img", shape=[1, 28, 28], dtype="float32")
    if label is None:
        label = L.data(name="label", shape=[1], dtype="int64")
    c1 = simple_img_conv_pool(img, filter_size=5, num_filters=20, pool_size=2,
                              pool_stride=2, act="relu")
    c2 = simple_img_conv_pool(c1, filter_size=5, num_filters=50, pool_size=2,
                              pool_stride=2, act="relu")
    logits = L.fc(c2, size=num_classes)
    loss = L.softmax_with_cross_entropy(logits, label)
    avg_loss = L.mean(loss)
    acc = L.accuracy(logits, label)
    return avg_loss, acc, logits
