"""ResNet image classification — BASELINE config 2 (reference: fluid
image_classification book test and the SE-ResNeXt ParallelExecutor tests,
python/paddle/fluid/tests/unittests/test_parallel_executor_seresnext*.py).

NCHW at the API (reference layers contract); XLA picks the TPU-native layout.
Data parallelism = batch-dim GSPMD sharding via CompiledProgram — no per-GPU
graph replication.
"""
from __future__ import annotations

from .. import layers as L

__all__ = ["resnet", "resnet50", "resnet18", "resnet_cifar10",
           "fold_stem_to_s2d"]

_DEPTH_CFG = {
    18: ("basic", [2, 2, 2, 2]),
    34: ("basic", [3, 4, 6, 3]),
    50: ("bottleneck", [3, 4, 6, 3]),
    101: ("bottleneck", [3, 4, 23, 3]),
    152: ("bottleneck", [3, 8, 36, 3]),
}


def _conv_bn(x, ch, k, stride=1, act=None, name=None, fmt="NCHW"):
    y = L.conv2d(x, num_filters=ch, filter_size=k, stride=stride,
                 padding=(k - 1) // 2, bias_attr=False, name=name,
                 data_format=fmt)
    return L.batch_norm(y, act=act, name=(name + ".bn") if name else None,
                        data_layout=fmt)


def _shortcut(x, ch_out, stride, name, fmt):
    cax = 1 if fmt == "NCHW" else -1
    if x.shape[cax] != ch_out or stride != 1:
        return _conv_bn(x, ch_out, 1, stride, name=name + ".sc", fmt=fmt)
    return x


def _basic_block(x, ch, stride, name, fmt):
    y = _conv_bn(x, ch, 3, stride, act="relu", name=name + ".c1", fmt=fmt)
    y = _conv_bn(y, ch, 3, 1, name=name + ".c2", fmt=fmt)
    s = _shortcut(x, ch, stride, name, fmt)
    return L.relu(L.elementwise_add(y, s))


def _bottleneck_block(x, ch, stride, name, fmt):
    y = _conv_bn(x, ch, 1, 1, act="relu", name=name + ".c1", fmt=fmt)
    y = _conv_bn(y, ch, 3, stride, act="relu", name=name + ".c2", fmt=fmt)
    y = _conv_bn(y, ch * 4, 1, 1, name=name + ".c3", fmt=fmt)
    s = _shortcut(x, ch * 4, stride, name, fmt)
    return L.relu(L.elementwise_add(y, s))


def fold_stem_to_s2d(w7, data_format="NCHW"):
    """Convert a trained 7x7-s2 stem weight [64, 3, 7, 7] (OIHW) into the
    exactly equivalent 4x4-s1 kernel for the space-to-depth stem
    (s2d_stem=True): pad the 7-tap kernel to 8 at the FRONT of each
    spatial dim, then repack taps into (phase_h, phase_w, c) input channels
    to match the space_to_depth op's channel order (vision_ops.py:177).
    Derivation: y[o] = sum_u w[u] x[2o-3+u]; n = 2(o+j)+p gives 2j+p = u-3,
    j in [-2,1] -> 4 taps with spatial padding (2, 1). Measured on TPU v5e:
    widening the stem contraction 3->12 is +1.3 MFU points end-to-end
    (tools/_rn_s2d.py, PERF.md r5).

    data_format: layout of the TARGET model's stem parameter — "NCHW"
    returns OIHW [64, 12, 4, 4]; "NHWC" returns HWIO [4, 4, 12, 64] (NHWC
    conv2d layers allocate weights HWIO, layers/nn.py)."""
    import numpy as np
    w7 = np.asarray(w7)
    o, ci, _, _ = w7.shape
    w8 = np.zeros((o, ci, 8, 8), w7.dtype)
    w8[:, :, 1:, 1:] = w7
    w8 = w8.reshape(o, ci, 4, 2, 4, 2)          # (O, c, th, ph, tw, pw)
    w8 = w8.transpose(0, 3, 5, 1, 2, 4)         # (O, ph, pw, c, th, tw)
    w4 = w8.reshape(o, 4 * ci, 4, 4)
    if data_format == "NHWC":
        return np.ascontiguousarray(w4.transpose(2, 3, 1, 0))  # -> HWIO
    return w4


def resnet(img, depth=50, num_classes=1000, s2d_stem=False,
           data_format="NCHW"):
    """Build the trunk + logits head. img: [N,3,H,W] (NCHW) or [N,H,W,3]
    (NHWC).

    s2d_stem: repack the input 2x2 space-to-depth (3->12 channels, HW/2)
    and run the stem as a 4x4-s1 conv — the standard TPU counter-move to
    the 3-channel-contraction MXU fill of the 7x7-s2 stem. Same function
    class (fold_stem_to_s2d maps 7x7 weights onto it exactly).

    data_format: "NHWC" keeps the whole activation chain channels-last —
    on TPU v5e the s2d stem win measures 2.3 ms in NHWC vs 0.6 ms in NCHW
    (tools/_rn_s2d.py vs /tmp probes, PERF.md r5)."""
    kind, layers_per_stage = _DEPTH_CFG[depth]
    fmt = data_format
    block = _basic_block if kind == "basic" else _bottleneck_block
    if s2d_stem:
        if fmt == "NCHW":
            x = L.space_to_depth(img, blocksize=2)
        else:
            # NHWC space-to-depth via reshape+transpose; channel order
            # (ph, pw, c) matches fold_stem_to_s2d and the NCHW op.
            n, h, w, c = img.shape
            x = L.reshape(img, [n, h // 2, 2, w // 2, 2, c])
            x = L.transpose(x, [0, 1, 3, 2, 4, 5])
            x = L.reshape(x, [n, h // 2, w // 2, 4 * c])
        # asymmetric (2,1) padding folded INTO the conv: a separate pad op
        # measures 2.4x slower on TPU (XLA does not fold it, tools/_rn_s2d.py)
        x = L.conv2d(x, num_filters=64, filter_size=4, stride=1,
                     padding=[2, 1, 2, 1], bias_attr=False, name="stem",
                     data_format=fmt)
        x = L.batch_norm(x, act="relu", name="stem.bn", data_layout=fmt)
    else:
        x = _conv_bn(img, 64, 7, stride=2, act="relu", name="stem", fmt=fmt)
    x = L.pool2d(x, pool_size=3, pool_type="max", pool_stride=2,
                 pool_padding=1, data_format=fmt)
    for stage, n in enumerate(layers_per_stage):
        ch = 64 * (2 ** stage)
        for i in range(n):
            stride = 2 if (i == 0 and stage > 0) else 1
            x = block(x, ch, stride, f"res{stage}.{i}", fmt)
    x = L.pool2d(x, pool_type="avg", global_pooling=True, data_format=fmt)
    return L.fc(x, size=num_classes)


def resnet50(img=None, label=None, num_classes=1000, class_dim=None,
             s2d_stem=False, data_format="NCHW"):
    if class_dim is not None:
        num_classes = class_dim
    if img is None:
        shape = [3, 224, 224] if data_format == "NCHW" else [224, 224, 3]
        img = L.data(name="img", shape=shape, dtype="float32")
    if label is None:
        label = L.data(name="label", shape=[1], dtype="int64")
    logits = resnet(img, depth=50, num_classes=num_classes,
                    s2d_stem=s2d_stem, data_format=data_format)
    loss = L.mean(L.softmax_with_cross_entropy(logits, label))
    acc = L.accuracy(logits, label)
    return loss, acc, logits


def resnet18(img=None, label=None, num_classes=1000):
    if img is None:
        img = L.data(name="img", shape=[3, 224, 224], dtype="float32")
    if label is None:
        label = L.data(name="label", shape=[1], dtype="int64")
    logits = resnet(img, depth=18, num_classes=num_classes)
    loss = L.mean(L.softmax_with_cross_entropy(logits, label))
    acc = L.accuracy(logits, label)
    return loss, acc, logits


def resnet_cifar10(img=None, label=None, num_classes=10):
    """Small 3-stage ResNet for 32x32 inputs (book image_classification)."""
    if img is None:
        img = L.data(name="img", shape=[3, 32, 32], dtype="float32")
    if label is None:
        label = L.data(name="label", shape=[1], dtype="int64")
    x = _conv_bn(img, 16, 3, act="relu", name="stem")
    for stage in range(3):
        ch = 16 * (2 ** stage)
        for i in range(3):
            stride = 2 if (i == 0 and stage > 0) else 1
            x = _basic_block(x, ch, stride, f"res{stage}.{i}", "NCHW")
    x = L.pool2d(x, pool_type="avg", global_pooling=True)
    logits = L.fc(x, size=num_classes)
    loss = L.mean(L.softmax_with_cross_entropy(logits, label))
    acc = L.accuracy(logits, label)
    return loss, acc, logits
