"""ResNet image classification — BASELINE config 2 (reference: fluid
image_classification book test and the SE-ResNeXt ParallelExecutor tests,
python/paddle/fluid/tests/unittests/test_parallel_executor_seresnext*.py).

NCHW at the API (reference layers contract); XLA picks the TPU-native layout.
Data parallelism = batch-dim GSPMD sharding via CompiledProgram — no per-GPU
graph replication.
"""
from __future__ import annotations

from .. import layers as L

__all__ = ["resnet", "resnet50", "resnet18", "resnet_cifar10"]

_DEPTH_CFG = {
    18: ("basic", [2, 2, 2, 2]),
    34: ("basic", [3, 4, 6, 3]),
    50: ("bottleneck", [3, 4, 6, 3]),
    101: ("bottleneck", [3, 4, 23, 3]),
    152: ("bottleneck", [3, 8, 36, 3]),
}


def _conv_bn(x, ch, k, stride=1, act=None, name=None):
    y = L.conv2d(x, num_filters=ch, filter_size=k, stride=stride,
                 padding=(k - 1) // 2, bias_attr=False, name=name)
    return L.batch_norm(y, act=act, name=(name + ".bn") if name else None)


def _shortcut(x, ch_out, stride, name):
    if x.shape[1] != ch_out or stride != 1:
        return _conv_bn(x, ch_out, 1, stride, name=name + ".sc")
    return x


def _basic_block(x, ch, stride, name):
    y = _conv_bn(x, ch, 3, stride, act="relu", name=name + ".c1")
    y = _conv_bn(y, ch, 3, 1, name=name + ".c2")
    s = _shortcut(x, ch, stride, name)
    return L.relu(L.elementwise_add(y, s))


def _bottleneck_block(x, ch, stride, name):
    y = _conv_bn(x, ch, 1, 1, act="relu", name=name + ".c1")
    y = _conv_bn(y, ch, 3, stride, act="relu", name=name + ".c2")
    y = _conv_bn(y, ch * 4, 1, 1, name=name + ".c3")
    s = _shortcut(x, ch * 4, stride, name)
    return L.relu(L.elementwise_add(y, s))


def resnet(img, depth=50, num_classes=1000):
    """Build the trunk + logits head. img: [N,3,H,W]."""
    kind, layers_per_stage = _DEPTH_CFG[depth]
    block = _basic_block if kind == "basic" else _bottleneck_block
    x = _conv_bn(img, 64, 7, stride=2, act="relu", name="stem")
    x = L.pool2d(x, pool_size=3, pool_type="max", pool_stride=2, pool_padding=1)
    for stage, n in enumerate(layers_per_stage):
        ch = 64 * (2 ** stage)
        for i in range(n):
            stride = 2 if (i == 0 and stage > 0) else 1
            x = block(x, ch, stride, name=f"res{stage}.{i}")
    x = L.pool2d(x, pool_type="avg", global_pooling=True)
    return L.fc(x, size=num_classes)


def resnet50(img=None, label=None, num_classes=1000, class_dim=None):
    if class_dim is not None:
        num_classes = class_dim
    if img is None:
        img = L.data(name="img", shape=[3, 224, 224], dtype="float32")
    if label is None:
        label = L.data(name="label", shape=[1], dtype="int64")
    logits = resnet(img, depth=50, num_classes=num_classes)
    loss = L.mean(L.softmax_with_cross_entropy(logits, label))
    acc = L.accuracy(logits, label)
    return loss, acc, logits


def resnet18(img=None, label=None, num_classes=1000):
    if img is None:
        img = L.data(name="img", shape=[3, 224, 224], dtype="float32")
    if label is None:
        label = L.data(name="label", shape=[1], dtype="int64")
    logits = resnet(img, depth=18, num_classes=num_classes)
    loss = L.mean(L.softmax_with_cross_entropy(logits, label))
    acc = L.accuracy(logits, label)
    return loss, acc, logits


def resnet_cifar10(img=None, label=None, num_classes=10):
    """Small 3-stage ResNet for 32x32 inputs (book image_classification)."""
    if img is None:
        img = L.data(name="img", shape=[3, 32, 32], dtype="float32")
    if label is None:
        label = L.data(name="label", shape=[1], dtype="int64")
    x = _conv_bn(img, 16, 3, act="relu", name="stem")
    for stage in range(3):
        ch = 16 * (2 ** stage)
        for i in range(3):
            stride = 2 if (i == 0 and stage > 0) else 1
            x = _basic_block(x, ch, stride, name=f"res{stage}.{i}")
    x = L.pool2d(x, pool_type="avg", global_pooling=True)
    logits = L.fc(x, size=num_classes)
    loss = L.mean(L.softmax_with_cross_entropy(logits, label))
    acc = L.accuracy(logits, label)
    return loss, acc, logits
