"""Transformer encoder / BERT-style pretraining — BASELINE configs 3 & 4
(reference: fluid book machine-translation transformer and ERNIE/BERT built on
fluid layers; attention primitive at reference python/paddle/fluid/nets.py:345
scaled_dot_product_attention).

TPU-first design notes:
  * Megatron-style tensor parallelism comes from GSPMD annotations on the
    projection weights (SURVEY.md §2.3): QKV/FFN-in shard the output dim over
    the `tp` mesh axis, attention-out/FFN-out shard the input dim — XLA's
    sharding propagator inserts the all-reduces the reference would have
    needed hand-written DistFC logic for.
  * Sequence parallelism = sharding the sequence dim of the token stream over
    the `sp` axis; the attention score matmul forces an all-gather that XLA
    places on ICI.
  * Everything is static-shaped (padded seq_len); bf16-friendly.
"""
from __future__ import annotations

from dataclasses import dataclass

from .. import layers as L
from ..framework import default_main_program
from ..param_attr import ParamAttr
from ..parallel.mesh import DATA_AXIS, MODEL_AXIS, SEQ_AXIS
from ..parallel.sharding import annotate_sharding

__all__ = ["TransformerConfig", "bert_base", "bert_tiny", "transformer_encoder",
           "bert_pretrain", "multi_head_attention", "positionwise_ffn",
           "wmt_base", "transformer_wmt", "cross_attention"]


@dataclass
class TransformerConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    ffn_size: int = 3072
    max_position: int = 512
    dropout: float = 0.1
    # parallelism intent: annotate weights/feeds with these mesh axes; harmless
    # when the program runs on a mesh lacking the axis (annotations filtered)
    use_tp: bool = True
    use_sp: bool = False
    # fused (Pallas flash) attention — used when there is no attention-prob
    # dropout and no additive mask (those paths keep the unfused ops).
    # Default OFF: measured on v5e, XLA's own attention fusion beats the
    # bundled Pallas kernel at train sizes (seq<=2048: 16ms vs 36ms fwd+bwd
    # for B8/h12/S2048/d64); flash pays off when the [B,nh,S,S] score tensor
    # no longer fits HBM (long-context), where it is the only option.
    use_flash_attention: bool = False
    causal: bool = False
    dtype: str = "float32"


def bert_base() -> TransformerConfig:
    return TransformerConfig()


def bert_tiny(use_tp: bool = True, use_sp: bool = False) -> TransformerConfig:
    return TransformerConfig(vocab_size=1024, hidden_size=64, num_layers=2,
                             num_heads=4, ffn_size=128, max_position=64,
                             dropout=0.0, use_tp=use_tp, use_sp=use_sp)


def _annot(spec):
    """Return a hook that annotates the named main-program var after creation."""
    def apply(name):
        block = default_main_program().global_block
        annotate_sharding(block.var(name), spec)
    return apply


def _fc(x, size, prefix, w_spec=None, b_spec=None, act=None, cfg=None):
    num_flatten = len(x.shape) - 1
    w_name, b_name = prefix + ".w", prefix + ".b"
    out = L.fc(
        x, size=size, num_flatten_dims=num_flatten,
        param_attr=ParamAttr(name=w_name), bias_attr=ParamAttr(name=b_name),
        act=act,
    )
    if cfg is not None and cfg.use_tp:
        if w_spec is not None:
            _annot(w_spec)(w_name)
        if b_spec is not None:
            _annot(b_spec)(b_name)
    return out


def _attn_core(q, k, v, attn_bias, cfg: TransformerConfig, causal, dh):
    """The attention block proper, [B,nh,Sq,dh] x [B,nh,Sk,dh] -> [B,nh,Sq,dh].

    One fused-attention op boundary whenever semantics allow (no additive
    bias, no attention-prob dropout): the op dispatches to the measured
    winner per shape — XLA fusion at train sizes, Pallas for long context.
    cfg.use_flash_attention forces an O(S)-memory kernel. Shared by self-
    and cross-attention so the dispatch policy lives in exactly one place.
    """
    if attn_bias is None and not cfg.dropout:
        return L.fused_attention(q, k, v, causal=causal, sm_scale=dh ** -0.5,
                                 use_pallas=cfg.use_flash_attention)
    scores = L.matmul(q, k, transpose_y=True, alpha=dh ** -0.5)
    if attn_bias is not None:
        scores = L.elementwise_add(scores, attn_bias)
    if causal:
        # fused causal-mask+softmax op (probs directly)
        helper = L.nn.LayerHelper("causal_softmax")
        probs = helper.create_variable_for_type_inference(scores.dtype)
        helper.append_op("softmax_mask_fuse_upper_triangle",
                         {"X": [scores.name]}, {"Out": [probs.name]}, {})
    else:
        probs = L.softmax(scores)
    if cfg.dropout:
        probs = L.dropout(probs, dropout_prob=cfg.dropout,
                          dropout_implementation="upscale_in_train")
    return L.matmul(probs, v)


def multi_head_attention(x, cfg: TransformerConfig, attn_bias=None, name="attn"):
    """Self-attention: fused QKV projection, [B,S,H] -> [B,S,H].

    TP: QKV weight [H, 3H] shards dim 1; out-proj [H, H] shards dim 0 — the
    classic Megatron column/row-parallel pair, expressed as annotations.
    """
    B_, S, H = -1, x.shape[-2], cfg.hidden_size
    nh, dh = cfg.num_heads, cfg.hidden_size // cfg.num_heads

    qkv = _fc(x, 3 * H, name + ".qkv", w_spec=(None, MODEL_AXIS),
              b_spec=(MODEL_AXIS,), cfg=cfg)
    qkv = L.reshape(qkv, shape=[0, S, 3, nh, dh])
    qkv = L.transpose(qkv, perm=[2, 0, 3, 1, 4])  # [3, B, nh, S, dh]
    q = L.squeeze(L.slice(qkv, axes=[0], starts=[0], ends=[1]), axes=[0])
    k = L.squeeze(L.slice(qkv, axes=[0], starts=[1], ends=[2]), axes=[0])
    v = L.squeeze(L.slice(qkv, axes=[0], starts=[2], ends=[3]), axes=[0])

    ctxv = _attn_core(q, k, v, attn_bias, cfg, causal=cfg.causal, dh=dh)
    ctxv = L.transpose(ctxv, perm=[0, 2, 1, 3])
    ctxv = L.reshape(ctxv, shape=[0, S, H])
    out = _fc(ctxv, H, name + ".out", w_spec=(MODEL_AXIS, None), cfg=cfg)
    return out


def positionwise_ffn(x, cfg: TransformerConfig, name="ffn"):
    h = _fc(x, cfg.ffn_size, name + ".in", w_spec=(None, MODEL_AXIS),
            b_spec=(MODEL_AXIS,), act="gelu", cfg=cfg)
    if cfg.dropout:
        h = L.dropout(h, dropout_prob=cfg.dropout,
                      dropout_implementation="upscale_in_train")
    return _fc(h, cfg.hidden_size, name + ".out", w_spec=(MODEL_AXIS, None), cfg=cfg)


def _encoder_layer(x, cfg: TransformerConfig, attn_bias, name):
    # post-LN as in BERT/original transformer
    a = multi_head_attention(x, cfg, attn_bias, name=name + ".mha")
    if cfg.dropout:
        a = L.dropout(a, dropout_prob=cfg.dropout,
                      dropout_implementation="upscale_in_train")
    x = L.layer_norm(L.elementwise_add(x, a), begin_norm_axis=2,
                     name=name + ".ln1")
    f = positionwise_ffn(x, cfg, name=name + ".ffn")
    if cfg.dropout:
        f = L.dropout(f, dropout_prob=cfg.dropout,
                      dropout_implementation="upscale_in_train")
    return L.layer_norm(L.elementwise_add(x, f), begin_norm_axis=2,
                        name=name + ".ln2")


# per-layer outputs of the MOST RECENT transformer_encoder build — the
# natural checkpoint set for RecomputeOptimizer._set_checkpoints. Snapshot
# it (list(...)) right after the build: a second encoder build (eval tower,
# second program) overwrites it, and _set_checkpoints with stale vars from a
# different program fails loudly at minimize().
last_layer_outputs: list = []


def transformer_encoder(src_ids, pos_ids, cfg: TransformerConfig,
                        input_mask=None, name="encoder"):
    """Token+position embedding -> N encoder layers. Returns [B,S,H]."""
    emb = L.embedding(src_ids, size=[cfg.vocab_size, cfg.hidden_size],
                      param_attr=ParamAttr(name=name + ".word_emb"),
                      dtype=cfg.dtype)
    pos = L.embedding(pos_ids, size=[cfg.max_position, cfg.hidden_size],
                      param_attr=ParamAttr(name=name + ".pos_emb"),
                      dtype=cfg.dtype)
    x = L.elementwise_add(emb, pos)
    x = L.layer_norm(x, begin_norm_axis=2, name=name + ".emb_ln")
    if cfg.dropout:
        x = L.dropout(x, dropout_prob=cfg.dropout,
                      dropout_implementation="upscale_in_train")

    attn_bias = None
    if input_mask is not None:
        # input_mask [B,S] 1/0 -> additive bias [B,1,1,S]
        neg = L.scale(input_mask, scale=-1.0, bias=1.0)
        neg = L.scale(neg, scale=-1e9)
        attn_bias = L.unsqueeze(L.unsqueeze(neg, axes=[1]), axes=[1])

    last_layer_outputs.clear()
    for i in range(cfg.num_layers):
        x = _encoder_layer(x, cfg, attn_bias, name=f"{name}.layer{i}")
        last_layer_outputs.append(x)
    return x


def bert_pretrain(cfg: TransformerConfig, seq_len: int = 128):
    """Masked-LM pretraining program: returns (avg_loss, feeds dict).

    Feeds: src_ids, pos_ids [B,S] int64; lm_label [B,S] int64 (ids at masked
    positions, -ignored elsewhere via mask weighting); lm_weight [B,S] float32.
    """
    src_ids = L.data(name="src_ids", shape=[seq_len], dtype="int64")
    pos_ids = L.data(name="pos_ids", shape=[seq_len], dtype="int64")
    lm_label = L.data(name="lm_label", shape=[seq_len], dtype="int64")
    lm_weight = L.data(name="lm_weight", shape=[seq_len], dtype="float32")

    if cfg.use_sp:
        block = default_main_program().global_block
        for n in ("src_ids", "pos_ids", "lm_label", "lm_weight"):
            annotate_sharding(block.var(n), (DATA_AXIS, SEQ_AXIS))

    enc = transformer_encoder(src_ids, pos_ids, cfg)  # [B,S,H]
    logits = _fc(enc, cfg.vocab_size, "lm_head", w_spec=(None, MODEL_AXIS),
                 b_spec=(MODEL_AXIS,), cfg=cfg)       # [B,S,V]
    label = L.unsqueeze(lm_label, axes=[2])
    loss = L.softmax_with_cross_entropy(logits, label)  # [B,S,1]
    loss = L.squeeze(loss, axes=[2])
    weighted = L.elementwise_mul(loss, lm_weight)
    denom = L.elementwise_add(L.reduce_sum(lm_weight), _const_eps())
    avg_loss = L.elementwise_div(L.reduce_sum(weighted), denom)
    feeds = {"src_ids": src_ids, "pos_ids": pos_ids,
             "lm_label": lm_label, "lm_weight": lm_weight}
    return avg_loss, feeds


def _const_eps():
    from ..layers.tensor import fill_constant
    return fill_constant(shape=[], dtype="float32", value=1e-6)


# ---------------------------------------------------------------------------
# Encoder-decoder Transformer (WMT en-de, BASELINE config 3; reference: the
# fluid book machine-translation transformer model family)
# ---------------------------------------------------------------------------


def wmt_base() -> TransformerConfig:
    """Transformer-base: 6+6 layers, d_model 512, 8 heads, ffn 2048, joint
    37k BPE vocab (Vaswani et al. table 3 'base')."""
    return TransformerConfig(vocab_size=37000, hidden_size=512, num_layers=6,
                             num_heads=8, ffn_size=2048, max_position=256,
                             dropout=0.1, use_tp=False)


def cross_attention(x, mem, cfg: TransformerConfig, attn_bias=None,
                    name="xattn"):
    """Encoder-decoder attention: queries from the decoder stream `x`
    [B,St,H], keys/values from encoder memory `mem` [B,Ss,H]."""
    St, Ss, H = x.shape[-2], mem.shape[-2], cfg.hidden_size
    nh, dh = cfg.num_heads, cfg.hidden_size // cfg.num_heads

    q = _fc(x, H, name + ".q", w_spec=(None, MODEL_AXIS),
            b_spec=(MODEL_AXIS,), cfg=cfg)
    kv = _fc(mem, 2 * H, name + ".kv", w_spec=(None, MODEL_AXIS),
             b_spec=(MODEL_AXIS,), cfg=cfg)
    q = L.transpose(L.reshape(q, shape=[0, St, nh, dh]), perm=[0, 2, 1, 3])
    kv = L.transpose(L.reshape(kv, shape=[0, Ss, 2, nh, dh]),
                     perm=[2, 0, 3, 1, 4])
    k = L.squeeze(L.slice(kv, axes=[0], starts=[0], ends=[1]), axes=[0])
    v = L.squeeze(L.slice(kv, axes=[0], starts=[1], ends=[2]), axes=[0])
    ctxv = _attn_core(q, k, v, attn_bias, cfg, causal=False, dh=dh)
    ctxv = L.reshape(L.transpose(ctxv, perm=[0, 2, 1, 3]), shape=[0, St, H])
    return _fc(ctxv, H, name + ".out", w_spec=(MODEL_AXIS, None), cfg=cfg)


def _decoder_layer(x, mem, cfg: TransformerConfig, self_bias, cross_bias,
                   name):
    import dataclasses

    causal_cfg = dataclasses.replace(cfg, causal=True)
    a = multi_head_attention(x, causal_cfg, self_bias, name=name + ".self")
    if cfg.dropout:
        a = L.dropout(a, dropout_prob=cfg.dropout,
                      dropout_implementation="upscale_in_train")
    x = L.layer_norm(L.elementwise_add(x, a), begin_norm_axis=2,
                     name=name + ".ln1")
    c = cross_attention(x, mem, cfg, cross_bias, name=name + ".cross")
    if cfg.dropout:
        c = L.dropout(c, dropout_prob=cfg.dropout,
                      dropout_implementation="upscale_in_train")
    x = L.layer_norm(L.elementwise_add(x, c), begin_norm_axis=2,
                     name=name + ".ln2")
    f = positionwise_ffn(x, cfg, name=name + ".ffn")
    if cfg.dropout:
        f = L.dropout(f, dropout_prob=cfg.dropout,
                      dropout_implementation="upscale_in_train")
    return L.layer_norm(L.elementwise_add(x, f), begin_norm_axis=2,
                        name=name + ".ln3")


def _embed_stream(ids, pos_ids, cfg, name, word_emb_name=None):
    emb = L.embedding(ids, size=[cfg.vocab_size, cfg.hidden_size],
                      param_attr=ParamAttr(name=word_emb_name or
                                           name + ".word_emb"),
                      dtype=cfg.dtype)
    pos = L.embedding(pos_ids, size=[cfg.max_position, cfg.hidden_size],
                      param_attr=ParamAttr(name=name + ".pos_emb"),
                      dtype=cfg.dtype)
    x = L.scale(emb, scale=cfg.hidden_size ** 0.5)
    x = L.elementwise_add(x, pos)
    if cfg.dropout:
        x = L.dropout(x, dropout_prob=cfg.dropout,
                      dropout_implementation="upscale_in_train")
    return x


def transformer_wmt(cfg: TransformerConfig, src_len: int = 128,
                    tgt_len: int = 128, label_smooth_eps: float = 0.1,
                    use_src_mask: bool = False):
    """Training program for WMT translation: returns (avg_loss, feeds dict).

    Feeds (all [B, len]): src_ids/src_pos int64, tgt_ids/tgt_pos int64 (the
    shifted-right decoder input), tgt_label int64, tgt_weight float32 (0 on
    padding). With `use_src_mask` an extra src_mask [B, src_len] float32
    (1=token, 0=pad) feed masks encoder self-attention AND decoder
    cross-attention, so padded source positions cannot contaminate the
    memory (tgt_weight only masks the loss). Label-smoothed cross entropy
    averaged over non-pad tokens — the reference transformer book model's
    loss. Source and target share the joint-BPE word embedding table.
    """
    src_ids = L.data(name="src_ids", shape=[src_len], dtype="int64")
    src_pos = L.data(name="src_pos", shape=[src_len], dtype="int64")
    tgt_ids = L.data(name="tgt_ids", shape=[tgt_len], dtype="int64")
    tgt_pos = L.data(name="tgt_pos", shape=[tgt_len], dtype="int64")
    tgt_label = L.data(name="tgt_label", shape=[tgt_len], dtype="int64")
    tgt_weight = L.data(name="tgt_weight", shape=[tgt_len], dtype="float32")

    src_bias = None
    extra_feeds = []
    if use_src_mask:
        src_mask = L.data(name="src_mask", shape=[src_len], dtype="float32")
        extra_feeds.append(src_mask)
        # [B,S] 1/0 -> additive bias [B,1,1,S] (broadcasts over heads + query)
        neg = L.scale(src_mask, scale=-1.0, bias=1.0)
        neg = L.scale(neg, scale=-1e9)
        src_bias = L.unsqueeze(L.unsqueeze(neg, axes=[1]), axes=[1])

    mem = _embed_stream(src_ids, src_pos, cfg, "enc", word_emb_name="word_emb")
    for i in range(cfg.num_layers):
        mem = _encoder_layer(mem, cfg, src_bias, name=f"enc.layer{i}")

    x = _embed_stream(tgt_ids, tgt_pos, cfg, "dec", word_emb_name="word_emb")
    for i in range(cfg.num_layers):
        x = _decoder_layer(x, mem, cfg, None, src_bias, name=f"dec.layer{i}")

    logits = _fc(x, cfg.vocab_size, "proj", w_spec=(None, MODEL_AXIS),
                 b_spec=(MODEL_AXIS,), cfg=cfg)        # [B,St,V]
    if label_smooth_eps:
        # dense one_hot -> label_smooth -> soft-label CE. The algebraic
        # fusion smoothCE = (1-eps)*hardCE + eps*(lse - mean_v(x)) was
        # built and MEASURED SLOWER (446.4k vs 465.3k tok/s, r5): XLA
        # already generates the one-hot as an iota-compare inside the CE
        # fusion (nothing dense materializes), while the "fused" form's
        # separate max/sum-exp reductions do not CSE against the CE's
        # internal statistics. Equivalence test kept in test_models.py.
        onehot = L.one_hot(tgt_label, cfg.vocab_size)  # [B,St,V]
        soft = L.label_smooth(onehot, epsilon=label_smooth_eps)
        loss = L.softmax_with_cross_entropy(logits, soft, soft_label=True)
    else:
        loss = L.softmax_with_cross_entropy(
            logits, L.unsqueeze(tgt_label, axes=[2]))
    loss = L.squeeze(loss, axes=[2])                   # [B,St]
    weighted = L.elementwise_mul(loss, tgt_weight)
    denom = L.elementwise_add(L.reduce_sum(tgt_weight), _const_eps())
    avg_loss = L.elementwise_div(L.reduce_sum(weighted), denom)
    feeds = {v.name: v for v in (src_ids, src_pos, tgt_ids, tgt_pos,
                                 tgt_label, tgt_weight, *extra_feeds)}
    return avg_loss, feeds
