"""Attention seq2seq for machine translation — the book test model.

TPU-native re-design of the reference's machine-translation book test
(/root/reference/python/paddle/fluid/tests/book/test_machine_translation.py:
encoder:64, train_decoder:94 DynamicRNN+attention, decode:148 While loop with
beam_search) on the padding contract:

  * encoder: embedding -> pre-projection -> `gru` scan op (dynamic_gru);
  * train decoder: StaticRNN (lax.scan) stepping the target sequence with
    Bahdanau-style dot attention over the padded source states (masked by
    src length — the LoD walk becomes a sequence_softmax);
  * infer decoder: While loop (lax.while_loop) over decode steps; each step
    scores beam continuations with the fixed-shape `beam_search` op, gathers
    decoder state by parent_idx, and scatters the step's choices into
    preallocated [T, B*beam] buffers that `beam_search_decode` backtracks.

All shapes are static: batch, beam, and max decode length are build-time
constants, which is what lets the whole decode loop jit as one XLA while.
"""
from __future__ import annotations

from .. import layers as L
from ..param_attr import ParamAttr

__all__ = ["encoder", "train_model", "infer_model"]


def encoder(src_ids, src_len, dict_size, word_dim=128, hidden_dim=256,
            name="enc"):
    """[B, S] ids + [B] lengths -> [B, S, H] states (book test encoder:64)."""
    emb = L.embedding(src_ids, size=[dict_size, word_dim],
                      param_attr=ParamAttr(name=name + ".emb"))
    proj = L.fc(emb, size=hidden_dim * 3, num_flatten_dims=2,
                param_attr=ParamAttr(name=name + ".proj.w"),
                bias_attr=ParamAttr(name=name + ".proj.b"))
    states = L.dynamic_gru(proj, size=hidden_dim,
                           param_attr=ParamAttr(name=name + ".gru.w"),
                           bias_attr=ParamAttr(name=name + ".gru.b"))
    # zero padded tail so attention sums stay clean
    states = L.sequence_unpad(states, src_len)
    return states


def _attention(h, enc_states, src_len, hidden_dim, name):
    """Dot attention with source-length masking (book test attention fn)."""
    # h: [B, H]; enc_states: [B, S, H]
    scores = L.reduce_sum(
        L.elementwise_mul(enc_states, L.unsqueeze(h, axes=[1])), dim=-1
    )  # [B, S]
    weights = L.sequence_softmax(scores, length=src_len)
    ctx = L.reduce_sum(
        L.elementwise_mul(enc_states, L.unsqueeze(weights, axes=[2])), dim=1
    )  # [B, H]
    return ctx


def train_model(src_ids, src_len, tgt_in, tgt_out, tgt_len, dict_size,
                word_dim=128, hidden_dim=256, name="s2s"):
    """Teacher-forced training loss (book test train_decoder:94).

    tgt_in: [B, T] decoder inputs (<s> w1 ... w_{T-1});
    tgt_out: [B, T] shifted targets; tgt_len: [B] valid lengths.
    """
    enc_states = encoder(src_ids, src_len, dict_size, word_dim, hidden_dim,
                         name=name + ".enc")
    dec_init = L.sequence_last_step(enc_states, length=src_len)  # [B, H]

    emb = L.embedding(tgt_in, size=[dict_size, word_dim],
                      param_attr=ParamAttr(name=name + ".dec.emb"))
    emb_t = L.transpose(emb, perm=[1, 0, 2])  # time-major [T, B, D]

    rnn = L.StaticRNN()
    with rnn.step():
        word = rnn.step_input(emb_t)           # [B, D]
        prev = rnn.memory(init=dec_init)       # [B, H]
        ctx = _attention(prev, enc_states, src_len, hidden_dim,
                         name + ".attn")
        inp = L.concat([word, ctx], axis=1)
        gates = L.fc(inp, size=hidden_dim * 3,
                     param_attr=ParamAttr(name=name + ".dec.in.w"),
                     bias_attr=ParamAttr(name=name + ".dec.in.b"))
        h, _, _ = L.gru_unit(gates, prev, size=hidden_dim * 3,
                             param_attr=ParamAttr(name=name + ".dec.gru.w"),
                             bias_attr=ParamAttr(name=name + ".dec.gru.b"))
        rnn.update_memory(prev, h)
        logits = L.fc(h, size=dict_size,
                      param_attr=ParamAttr(name=name + ".dec.out.w"),
                      bias_attr=ParamAttr(name=name + ".dec.out.b"))
        rnn.step_output(logits)
    logits_t = rnn()                            # [T, B, V]
    logits_bt = L.transpose(logits_t, perm=[1, 0, 2])  # [B, T, V]

    labels = L.unsqueeze(tgt_out, axes=[2])
    loss_bt = L.softmax_with_cross_entropy(logits_bt, labels)  # [B, T, 1]
    loss_bt = L.squeeze(loss_bt, axes=[2])
    mask = L.cast(L.sequence_mask(tgt_len, maxlen=tgt_in.shape[1],
                                  dtype="int64"), "float32")
    denom = L.reduce_sum(mask)
    avg_loss = L.reduce_sum(L.elementwise_mul(loss_bt, mask)) / denom
    return avg_loss


def infer_model(src_ids, src_len, dict_size, word_dim=128, hidden_dim=256,
                beam_size=4, max_len=16, bos_id=0, eos_id=1, name="s2s"):
    """Beam-search decode (book test decode:148). Returns
    (sentence_ids [B*beam, max_len], sentence_scores [B*beam])."""
    enc_states = encoder(src_ids, src_len, dict_size, word_dim, hidden_dim,
                         name=name + ".enc")
    dec_init = L.sequence_last_step(enc_states, length=src_len)

    B = src_ids.shape[0]
    if B is None or B < 0:
        raise ValueError("infer_model needs a static batch size")
    BW = B * beam_size

    # beam-expand encoder outputs and state (reference sequence_expand)
    enc_beam = L.sequence_expand(enc_states, beam_size)        # [BW, S, H]
    src_len_beam = L.sequence_expand(src_len, beam_size)       # [BW]
    hidden = L.sequence_expand(dec_init, beam_size)            # [BW, H]

    pre_ids = L.fill_constant([BW, 1], "int64", bos_id)
    # first-step trick: every beam of a batch starts identical, so kill all
    # but beam 0 with a -inf initial score — the standard fixed-shape
    # equivalent of the reference's "start with one hypothesis per source"
    live0 = L.fill_constant([B, 1], "float32", 0.0)
    dead = L.fill_constant([B, beam_size - 1], "float32", -1e9)
    pre_scores = L.reshape(L.concat([live0, dead], axis=1), [BW, 1])
    step = L.fill_constant([], "int64", 0)
    ids_buf = L.fill_constant([max_len, BW], "int64", eos_id)
    parent_buf = L.fill_constant([max_len, BW], "int32", 0)
    score_buf = L.fill_constant([max_len, BW], "float32", 0.0)
    max_len_c = L.fill_constant([], "int64", max_len)

    cond = L.less_than(step, max_len_c)
    w = L.While(cond)
    with w.block():
        # lookup_table on [BW, 1] ids yields [BW, D] (fluid's trailing-1
        # LoD convention)
        word = L.embedding(pre_ids, size=[dict_size, word_dim],
                           param_attr=ParamAttr(name=name + ".dec.emb"))
        ctx = _attention(hidden, enc_beam, src_len_beam, hidden_dim,
                         name + ".attn")
        gates = L.fc(L.concat([word, ctx], axis=1), size=hidden_dim * 3,
                     param_attr=ParamAttr(name=name + ".dec.in.w"),
                     bias_attr=ParamAttr(name=name + ".dec.in.b"))
        h, _, _ = L.gru_unit(gates, hidden, size=hidden_dim * 3,
                             param_attr=ParamAttr(name=name + ".dec.gru.w"),
                             bias_attr=ParamAttr(name=name + ".dec.gru.b"))
        logits = L.fc(h, size=dict_size,
                      param_attr=ParamAttr(name=name + ".dec.out.w"),
                      bias_attr=ParamAttr(name=name + ".dec.out.b"))
        logp = L.log(L.softmax(logits))
        top_scores, top_ids = L.topk(logp, k=beam_size)        # [BW, K]

        sel_ids, sel_scores, parent = L.beam_search(
            pre_ids, pre_scores, top_ids, top_scores,
            beam_size=beam_size, end_id=eos_id)
        new_hidden = L.gather(h, parent)                       # [BW, H]

        step_i = L.unsqueeze(L.cast(step, "int32"), axes=[0])  # [1]
        ids_row = L.unsqueeze(L.squeeze(sel_ids, axes=[1]), axes=[0])
        parent_row = L.unsqueeze(parent, axes=[0])
        score_row = L.unsqueeze(L.squeeze(sel_scores, axes=[1]), axes=[0])
        L.assign(L.scatter(ids_buf, step_i, ids_row), ids_buf)
        L.assign(L.scatter(parent_buf, step_i, parent_row), parent_buf)
        L.assign(L.scatter(score_buf, step_i, score_row), score_buf)

        L.assign(sel_ids, pre_ids)
        L.assign(sel_scores, pre_scores)
        L.assign(new_hidden, hidden)
        L.increment(step, value=1)
        L.assign(L.less_than(step, max_len_c), cond)

    sent_ids, sent_scores = L.beam_search_decode(
        ids_buf, parent_buf, score_buf, end_id=eos_id)
    return sent_ids, sent_scores
