"""N-gram word embedding model (reference book test:
python/paddle/fluid/tests/book/test_word2vec.py — 4-gram context -> next word
via shared embeddings, hidden layer, softmax cross-entropy)."""
from __future__ import annotations

from ..param_attr import ParamAttr
from ..layers import nn as L
from ..layers import tensor as T


def word2vec(dict_size: int = 2000, embed_dim: int = 32,
             hidden_size: int = 256, context: int = 4,
             is_sparse: bool = False):
    """Returns (avg_loss, predict, feed_names). Feeds: context word id slots
    `w0..w{context-1}` [B,1] int64 + `next_word` [B,1] int64."""
    embeds = []
    feeds = []
    for i in range(context):
        w = T.data(name=f"w{i}", shape=[1], dtype="int64")
        feeds.append(w.name)
        embeds.append(L.embedding(
            w, size=[dict_size, embed_dim], is_sparse=is_sparse,
            param_attr=ParamAttr(name="shared_w")))  # shared table
    concat = L.concat([L.reshape(e, [-1, embed_dim]) for e in embeds], axis=1)
    hidden = L.fc(concat, size=hidden_size, act="sigmoid")
    predict = L.fc(hidden, size=dict_size, act="softmax")
    next_word = T.data(name="next_word", shape=[1], dtype="int64")
    feeds.append(next_word.name)
    cost = L.cross_entropy(predict, next_word)
    avg_loss = L.mean(cost)
    return avg_loss, predict, feeds
