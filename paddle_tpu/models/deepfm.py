"""DeepFM CTR model (BASELINE.json config 5).

Reference analogue: the CTR workloads the pserver path serves
(/root/reference/python/paddle/fluid/tests/unittests/dist_ctr.py, ctr_dataset
reader) — factorization machine + deep tower over sparse slot features.

Inputs are the classic slot layout: `sparse_ids` [B, n_fields] int64 feature
ids hashed into one shared vocabulary, `dense_x` [B, n_dense] float features,
`label` [B, 1]. Sparse embeddings use is_sparse=True so gradients travel as
SelectedRows to the parameter server (or a dense fused scatter-add when
trained single-process).
"""
from __future__ import annotations

from ..param_attr import ParamAttr
from ..layers import nn as L
from ..layers import tensor as T


def deepfm(
    n_fields: int = 26,
    n_dense: int = 13,
    vocab_size: int = 100_000,
    embed_dim: int = 16,
    hidden_sizes=(400, 400, 400),
    is_sparse: bool = True,
):
    """Build DeepFM; returns (avg_loss, predict, feed_names)."""
    sparse_ids = T.data(name="sparse_ids", shape=[n_fields], dtype="int64")
    dense_x = T.data(name="dense_x", shape=[n_dense], dtype="float32")
    label = T.data(name="label", shape=[1], dtype="float32")

    # -- FM first order: per-feature scalar weights --------------------------
    w1 = L.embedding(
        sparse_ids, size=[vocab_size, 1], is_sparse=is_sparse,
        param_attr=ParamAttr(name="fm_w1"))           # [B, F, 1]
    first_sparse = L.reduce_sum(w1, dim=1)             # [B, 1]
    first_dense = L.fc(dense_x, size=1, bias_attr=False,
                       param_attr=ParamAttr(name="fm_dense_w"))
    first_order = first_sparse + first_dense

    # -- FM second order: 0.5 * ((sum v)^2 - sum v^2) ------------------------
    emb = L.embedding(
        sparse_ids, size=[vocab_size, embed_dim], is_sparse=is_sparse,
        param_attr=ParamAttr(name="fm_emb"))           # [B, F, D]
    sum_v = L.reduce_sum(emb, dim=1)                   # [B, D]
    sum_sq = L.elementwise_mul(sum_v, sum_v)
    sq = L.elementwise_mul(emb, emb)
    sq_sum = L.reduce_sum(sq, dim=1)
    second_order = L.scale(
        L.reduce_sum(sum_sq - sq_sum, dim=1, keep_dim=True), 0.5)  # [B, 1]

    # -- deep tower over flattened embeddings + dense ------------------------
    deep = L.concat(
        [L.reshape(emb, [-1, n_fields * embed_dim]), dense_x], axis=1)
    for i, h in enumerate(hidden_sizes):
        deep = L.fc(deep, size=h, act="relu",
                    param_attr=ParamAttr(name=f"deep_w{i}"),
                    bias_attr=ParamAttr(name=f"deep_b{i}"))
    deep_out = L.fc(deep, size=1, param_attr=ParamAttr(name="deep_out_w"))

    logit = first_order + second_order + deep_out
    predict = L.sigmoid(logit)
    loss = L.sigmoid_cross_entropy_with_logits(logit, label)
    avg_loss = L.mean(loss)
    return avg_loss, predict, ["sparse_ids", "dense_x", "label"]
