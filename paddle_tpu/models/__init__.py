"""Model zoo built on the layers DSL — the book/models configs of the
reference (python/paddle/fluid/tests/book/, BASELINE.json configs):
MNIST MLP, ResNet image classification, Transformer/BERT, word2vec, DeepFM.

Each builder appends to the current default main/startup programs (use
`program_guard` for isolation) and returns the named output Variables.
"""
from . import deepfm  # noqa: F401
from . import mlp  # noqa: F401
from . import resnet  # noqa: F401
from . import seq2seq  # noqa: F401
from . import transformer  # noqa: F401
from . import word2vec  # noqa: F401
