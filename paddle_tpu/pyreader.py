"""PyReader / DataLoader: background-thread prefetch feeding the executor.

Reference: /root/reference/python/paddle/fluid/reader.py (PyReader:47) +
operators/reader/buffered_reader.cc (host->device double buffering) +
lod_tensor_blocking_queue.h. TPU re-design: one python background thread
fills a bounded queue with ready feed dicts (the LoDTensorBlockingQueue
equivalent); with use_double_buffer=True (the default, and the reference's
buffered_reader) a second background thread — pipeline.DeviceLoader — stages
the next FLAGS_device_prefetch_depth batches into device memory with
jax.device_put, so the host->HBM transfer overlaps the running step.
use_double_buffer=False keeps the plain host-queue prefetch (batches reach
the consumer as numpy and Executor.run places them synchronously).
`iterable=True` mode only (the start/reset in-program reader-op protocol has
no XLA analogue; the reference itself deprecated it)."""
from __future__ import annotations

from .data_feeder import DataFeeder
from .reader import _prefetch_iter

__all__ = ["PyReader", "DataLoader"]


class PyReader:
    def __init__(self, feed_list=None, capacity=64, use_double_buffer=True,
                 iterable=True, return_list=False):
        if not iterable:
            raise NotImplementedError(
                "non-iterable PyReader (start/reset protocol) is not part of "
                "the TPU build; iterate the reader object instead")
        self.feed_list = feed_list
        self.capacity = capacity
        self.use_double_buffer = use_double_buffer
        self.return_list = return_list
        self._feeder = DataFeeder(feed_list) if feed_list else None
        self._source = None  # callable -> generator of feed dicts

    # -- decoration (reference reader.py:214-372) ---------------------------
    def decorate_sample_generator(self, sample_generator, batch_size,
                                  drop_last=True, places=None):
        from . import reader as _reader

        self.decorate_sample_list_generator(
            _reader.batch(sample_generator, batch_size, drop_last), places)

    def decorate_sample_list_generator(self, reader, places=None):
        """reader: generator of SAMPLE LISTS (paddle.batch output)."""
        if self._feeder is None:
            raise ValueError("feed_list is required for sample-list mode")

        def gen():
            for samples in reader():
                yield self._feeder.feed(samples)

        self._source = gen

    def decorate_batch_generator(self, reader, places=None):
        """reader: generator of ready feed dicts (or tuples matching
        feed_list order)."""

        def gen():
            for item in reader():
                if isinstance(item, dict):
                    yield item
                else:
                    yield {v.name: a for v, a in zip(self.feed_list, item)}

        self._source = gen

    # -- iteration ----------------------------------------------------------
    def __call__(self):
        return self.__iter__()

    def __iter__(self):
        if self._source is None:
            raise RuntimeError("decorate_* must be called before iterating")
        if self.use_double_buffer:
            from .pipeline import DeviceLoader

            # two stages, mirroring the reference's queue + buffered_reader:
            # the host queue (capacity) absorbs reader jitter cheaply in
            # numpy; the DeviceLoader holds only a few batches in HBM
            source, capacity = self._source, self.capacity
            it = iter(DeviceLoader(
                lambda: _prefetch_iter(source, capacity),
                feed_vars=self.feed_list))
        else:
            it = _prefetch_iter(self._source, self.capacity)
        for d in it:
            if self.return_list:
                yield [d[v.name] for v in self.feed_list]
            else:
                yield d


class DataLoader:
    """fluid.io.DataLoader facade (2.x-style entry the reference was growing
    toward); from_generator mirrors PyReader."""

    @staticmethod
    def from_generator(feed_list=None, capacity=64, use_double_buffer=True,
                       iterable=True, return_list=False):
        return PyReader(feed_list, capacity, use_double_buffer, iterable,
                        return_list)
