"""LayerHelper: shared plumbing for layers — parameter creation wired to the
startup program, op appending, activation sugar.

Reference: /root/reference/python/paddle/fluid/layer_helper.py. Same contract:
`create_parameter` creates the Parameter in the main program AND appends its
init op to the default startup program; `append_op` builds ops in the current
default main program block.
"""
from __future__ import annotations

from . import unique_name
from .core.types import is_floating
from .framework import default_main_program, default_startup_program
from .initializer import Constant, Xavier
from .param_attr import ParamAttr


class LayerHelper:
    def __init__(self, layer_type: str, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = kwargs.get("name")
        self.name = name if name is not None else unique_name.generate(layer_type)

    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    def append_op(self, type, inputs=None, outputs=None, attrs=None):
        def _names(d):
            if d is None:
                return {}
            out = {}
            for slot, vs in d.items():
                if not isinstance(vs, (list, tuple)):
                    vs = [vs]
                out[slot] = [v if isinstance(v, str) else v.name for v in vs]
            return out

        return self.main_program.current_block().append_op(
            type, _names(inputs), _names(outputs), attrs
        )

    def create_parameter(
        self, attr, shape, dtype, is_bias=False, default_initializer=None, **kw
    ):
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        if attr.name is None:
            attr.name = unique_name.generate(".".join([self.name, "b" if is_bias else "w"]))
        init = attr.initializer or default_initializer
        if init is None:
            init = Constant(0.0) if is_bias else Xavier()
        block = self.main_program.current_block()
        param = block.create_parameter(
            shape=shape,
            dtype=dtype,
            name=attr.name,
            trainable=attr.trainable,
            regularizer=attr.regularizer,
            gradient_clip_attr=attr.gradient_clip,
            optimize_attr={"learning_rate": attr.learning_rate},
            **kw,
        )
        # mirror into the startup program + append the init op there
        sblock = self.startup_program.global_block
        sparam = sblock.create_parameter(
            shape=shape, dtype=dtype, name=attr.name, trainable=attr.trainable
        )
        init(sparam, sblock)
        return param

    def create_variable_for_type_inference(self, dtype, stop_gradient=False):
        return self.main_program.current_block().create_var(
            name=unique_name.generate(".".join([self.name, "tmp"])),
            dtype=dtype,
            stop_gradient=stop_gradient,
        )

    def create_global_variable(self, shape, dtype, persistable=False, name=None, stop_gradient=True):
        return self.main_program.global_block.create_var(
            name=name or unique_name.generate(".".join([self.name, "gvar"])),
            shape=shape,
            dtype=dtype,
            persistable=persistable,
            stop_gradient=stop_gradient,
        )

    def create_or_get_global_variable(self, name, shape, dtype, persistable=True, initializer=None):
        """Create a persistable var in both main and startup programs (e.g.
        batch-norm running stats, optimizer accumulators, global step)."""
        block = self.main_program.global_block
        if name in block.vars:
            return block.vars[name]
        v = block.create_var(
            name=name, shape=shape, dtype=dtype, persistable=persistable, stop_gradient=True
        )
        sblock = self.startup_program.global_block
        sv = sblock.create_var(name=name, shape=shape, dtype=dtype, persistable=persistable)
        (initializer or Constant(0.0))(sv, sblock)
        return v

    def input_dtype(self, x):
        return x.dtype

    def append_activation(self, out_var, act: str | None):
        if act is None:
            return out_var
        act_out = self.create_variable_for_type_inference(out_var.dtype)
        self.append_op(act, inputs={"X": [out_var]}, outputs={"Out": [act_out]})
        return act_out

    def append_bias_op(self, input_var, bias_attr, dim_start=1, num_flatten_dims=None):
        size = input_var.shape[-1]
        b = self.create_parameter(bias_attr, [size], input_var.dtype, is_bias=True)
        if b is None:
            return input_var
        out = self.create_variable_for_type_inference(input_var.dtype)
        # axis=-1 (trailing alignment): a [size] bias always lands on the
        # last dim regardless of the input's build-time rank, which can
        # differ from runtime rank inside control-flow sub-blocks
        self.append_op(
            "elementwise_add",
            inputs={"X": [input_var], "Y": [b]},
            outputs={"Out": [out]},
            attrs={"axis": -1},
        )
        return out
