"""CheckpointedRunner: a fault-tolerant Executor.run training loop.

Wraps the plain `for step: exe.run(...)` loop with the full recovery ladder:

  1. periodic atomic checkpoints (CheckpointManager) every `save_every`
     steps, plus one at the end of the run;
  2. auto-resume — `resume()` restores the newest good checkpoint and the
     loop continues from the following step (the kill-and-resume contract:
     a SIGKILL'd trainer restarts within one checkpoint of the crash);
  3. on a step failure: restore the last good checkpoint and *replay*
     deterministically from it (feeds and RNG are keyed by step index, so
     the replayed trajectory is bit-identical to an undisturbed run);
  4. graceful degradation before surfacing: the second failure of the same
     step also invalidates the executor's compile cache (a poisoned cached
     executable recompiles), the third runs that one step under
     `jax.disable_jit()` (an XLA-compile-path failure still makes forward
     progress); further failures re-raise.

Determinism contract: `feed_fn(step)` must be a pure function of the step
index, and the runner passes `rng_counter=step + 1` to Executor.run so
counter-derived randomness (dropout keys) depends only on the step — never
on how many crashes and replays it took to get there.
"""
from __future__ import annotations

from typing import Callable, Sequence

from .checkpoint import CheckpointManager

__all__ = ["CheckpointedRunner"]


class StepFailure(RuntimeError):
    """A step kept failing after the whole recovery ladder."""

    def __init__(self, step: int, attempts: int, last: Exception):
        super().__init__(
            f"training step {step} failed after {attempts} attempts "
            f"(restore+retry, cache invalidation, disable_jit all "
            f"exhausted): {last}")
        self.step = step
        self.attempts = attempts


class CheckpointedRunner:
    def __init__(self, executor, manager: "CheckpointManager | str",
                 main_program=None, scope=None, save_every: int | None = None,
                 max_retries: int | None = None):
        """manager: a CheckpointManager or a checkpoint root directory.
        save_every/max_retries default from FLAGS_ckpt_save_every /
        FLAGS_runner_max_retries."""
        from .. import flags
        from ..executor import global_scope
        from ..framework import default_main_program

        self.exe = executor
        self.manager = (manager if isinstance(manager, CheckpointManager)
                        else CheckpointManager(manager))
        self.program = main_program or default_main_program()
        self.scope = scope or global_scope()
        self.save_every = (flags.get_flag("ckpt_save_every")
                           if save_every is None else int(save_every))
        self.max_retries = (flags.get_flag("runner_max_retries")
                            if max_retries is None else int(max_retries))
        self.retries_used = 0  # across the whole run, for observability

    # -- resume --------------------------------------------------------------
    def resume(self, executor=None) -> int:
        """Restore the newest good checkpoint into the scope; returns the
        next step index to run (0 on a fresh root)."""
        restored = self.manager.restore(executor=executor or self.exe,
                                        main_program=self.program,
                                        scope=self.scope)
        return 0 if restored is None else restored + 1

    # -- the guarded step ----------------------------------------------------
    def _run_step(self, step: int, feed: dict, fetch_list):
        return self.exe.run(self.program, feed=feed, fetch_list=fetch_list,
                            scope=self.scope, rng_counter=step + 1)

    def _recover(self, attempt: int, step: int, exc: Exception) -> int:
        """Roll state back to the last good checkpoint; returns the step the
        loop must resume from (replay). Escalates with the attempt count."""
        if attempt >= 2:
            # a cached executable (or its donated-buffer bookkeeping) may be
            # the thing that is broken — recompile from scratch
            invalidate = getattr(self.exe, "invalidate_cache", None)
            if invalidate is not None:
                invalidate(self.program)
        restored = self.manager.restore(executor=self.exe,
                                        main_program=self.program,
                                        scope=self.scope)
        if restored is None:
            return step  # nothing to roll back to: plain retry
        return restored + 1

    def run(self, feed_fn: Callable[[int], dict], num_steps: int,
            fetch_list: Sequence | None = None,
            on_step: Callable[[int, list], None] | None = None,
            start_step: int | None = None) -> dict:
        """Train steps [start, num_steps) with recovery and checkpoints.

        feed_fn(step) -> feed dict, pure in step; on_step(step, outs) fires
        after every *successful* step (replays re-fire it — consumers keyed
        by step stay consistent). Returns {"start_step", "results": {step:
        outs}, "retries"}.
        """
        import jax

        start = self.resume() if start_step is None else int(start_step)
        results: dict[int, list] = {}
        step = start
        # per-step failure counts must survive replays: a rollback re-runs
        # earlier (healthy) steps, and the failing step has to resume its
        # escalation ladder where it left off, not restart it
        fails: dict[int, int] = {}
        while step < num_steps:
            use_eager = fails.get(step, 0) >= 3  # last rung: step without XLA
            try:
                if use_eager:
                    with jax.disable_jit():
                        outs = self._run_step(step, feed_fn(step), fetch_list)
                else:
                    outs = self._run_step(step, feed_fn(step), fetch_list)
            except Exception as e:  # noqa: BLE001 — ladder decides
                nfails = fails.get(step, 0) + 1
                fails[step] = nfails
                self.retries_used += 1
                if nfails > self.max_retries:
                    raise StepFailure(step, nfails, e) from e
                step = self._recover(nfails, step, e)
                continue
            results[step] = outs
            if on_step is not None:
                on_step(step, outs)
            if self.save_every and (step + 1) % self.save_every == 0:
                self.manager.save(step, executor=self.exe,
                                  main_program=self.program, scope=self.scope)
            step += 1
        if num_steps > start and (
                not self.save_every or num_steps % self.save_every != 0):
            # final state is always durable, whatever the cadence
            self.manager.save(num_steps - 1, executor=self.exe,
                              main_program=self.program, scope=self.scope)
        return {"start_step": start, "results": results,
                "retries": self.retries_used}
