"""RetryPolicy: exponential backoff + deterministic jitter + deadline.

The reference RPC stack retried inside gRPC (rpc_client retry loops,
listen_and_serv re-accept); here retry is a first-class policy object shared
by the pserver RPC client (distributed/ps_rpc.py), the async Communicator's
final flush, and orbax checkpoint I/O (io.py save_sharded/load_sharded).

Only *transient* errors retry: transport failures (ConnectionError — which
InjectedFault subclasses — EOFError, TimeoutError, OSError) by default.
Server-side application errors (RuntimeError from an "err" reply) are not
transient and surface immediately.

Jitter is deterministic — seeded from the attempt index — so a replayed
fault plan sees identical sleep sequences and the chaos tests stay
reproducible down to timing-dependent interleavings.
"""
from __future__ import annotations

import hashlib
import time
from typing import Callable, Iterable

__all__ = ["RetryPolicy", "rpc_policy", "io_policy", "serving_policy",
           "fleet_policy", "connect_policy"]

_TRANSIENT = (ConnectionError, EOFError, TimeoutError, OSError)


class RetryPolicy:
    def __init__(self, max_attempts: int = 4, base_delay: float = 0.05,
                 max_delay: float = 2.0, multiplier: float = 2.0,
                 jitter: float = 0.5, deadline: float | None = 30.0,
                 retryable: Iterable[type[BaseException]] = _TRANSIENT,
                 seed: int = 0, sleep: Callable[[float], None] = time.sleep):
        """deadline: wall-clock budget in seconds for ALL attempts of one
        call (None = unbounded); jitter: fraction of the backoff delay drawn
        deterministically in [0, jitter)."""
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = int(max_attempts)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.deadline = deadline
        self.retryable = tuple(retryable)
        self.seed = int(seed)
        self._sleep = sleep

    def delay(self, attempt: int) -> float:
        """Backoff before retry number `attempt` (1-based), jittered."""
        d = min(self.base_delay * self.multiplier ** (attempt - 1),
                self.max_delay)
        if self.jitter:
            h = hashlib.sha256(f"{self.seed}:{attempt}".encode()).digest()
            frac = int.from_bytes(h[:8], "big") / 2**64
            d *= 1.0 + self.jitter * frac
        return d

    def call(self, fn: Callable, *args, on_retry: Callable | None = None,
             **kwargs):
        """Run fn until success, a non-retryable error, attempts exhaust, or
        the deadline passes. on_retry(attempt, exc) fires before each retry —
        the hook RPC callers use to drop a broken connection."""
        start = time.monotonic()
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn(*args, **kwargs)
            except self.retryable as e:
                if attempt >= self.max_attempts:
                    raise
                d = self.delay(attempt)
                if (self.deadline is not None
                        and time.monotonic() + d - start > self.deadline):
                    raise
                if on_retry is not None:
                    on_retry(attempt, e)
                self._sleep(d)

    def wrap(self, fn: Callable, on_retry: Callable | None = None) -> Callable:
        def wrapped(*args, **kwargs):
            return self.call(fn, *args, on_retry=on_retry, **kwargs)

        wrapped.__name__ = getattr(fn, "__name__", "wrapped")
        return wrapped

    def __repr__(self):
        return (f"RetryPolicy(max_attempts={self.max_attempts}, "
                f"base_delay={self.base_delay}, max_delay={self.max_delay}, "
                f"deadline={self.deadline})")


def _from_flags(**overrides) -> RetryPolicy:
    from .. import flags

    kw = dict(
        max_attempts=flags.get_flag("retry_max_attempts"),
        base_delay=flags.get_flag("retry_base_delay_ms") / 1000.0,
        max_delay=flags.get_flag("retry_max_delay_ms") / 1000.0,
        deadline=flags.get_flag("retry_deadline_s") or None,
    )
    kw.update(overrides)
    return RetryPolicy(**kw)


def rpc_policy(**overrides) -> RetryPolicy:
    """Policy for pserver RPCs, configured from FLAGS_retry_*."""
    return _from_flags(**overrides)


def serving_policy(**overrides) -> RetryPolicy:
    """Policy for serving-engine step dispatch: fast, tightly bounded
    attempts with no wall-clock deadline — a decode step is milliseconds,
    so backoff at checkpoint-I/O scale would stall every request in the
    batch. Attempt count from FLAGS_serving_step_retries; exhaustion is
    the engine supervisor's signal to run the recovery pass."""
    from .. import flags

    kw = dict(
        max_attempts=max(1, flags.get_flag("serving_step_retries")),
        base_delay=0.001, max_delay=0.02, deadline=None)
    kw.update(overrides)
    return RetryPolicy(**kw)


def fleet_policy(**overrides) -> RetryPolicy:
    """Policy for fleet-router failover placement: max_attempts IS the
    per-request failover budget (FLAGS_fleet_failover_budget — one attempt
    per replica death), and the millisecond backoff paces re-placement
    when every survivor momentarily rejects. AdmissionRejected counts as
    transient here — a shedding replica is a full replica, and another one
    (or the same one a beat later) may admit. Disaggregated handoff
    failures (ISSUE 19: a reaped lease, a bounced commit, a death on
    either side of the prefill->decode transfer) ride this same budget —
    a replay is a replay, however the request got stranded — while
    planned drain handoffs stay free."""
    from .. import flags
    from ..serving.engine import AdmissionRejected

    kw = dict(
        max_attempts=max(1, flags.get_flag("fleet_failover_budget")),
        base_delay=0.002, max_delay=0.05, deadline=None,
        retryable=_TRANSIENT + (AdmissionRejected,))
    kw.update(overrides)
    return RetryPolicy(**kw)


def connect_policy(**overrides) -> RetryPolicy:
    """Policy for first-connection dials (PSClient._conn): flat 0.2s
    interval — the server may simply still be starting, so backoff growth
    buys nothing — bounded by the FLAGS_rpc_deadline wall clock rather
    than an attempt count. Replaces the inline sleep-loop copy of this
    same math that used to live in ps_rpc."""
    from ..distributed.ps_rpc import rpc_deadline_s

    kw = dict(
        max_attempts=10_000_000, base_delay=0.2, max_delay=0.2,
        multiplier=1.0, jitter=0.0, deadline=rpc_deadline_s(),
        retryable=(ConnectionRefusedError, FileNotFoundError))
    kw.update(overrides)
    return RetryPolicy(**kw)


def io_policy(**overrides) -> RetryPolicy:
    """Policy for checkpoint I/O: fewer, slower attempts — filesystem brown-
    outs recover on the order of seconds, not milliseconds."""
    from .. import flags

    kw = dict(
        max_attempts=max(2, flags.get_flag("retry_max_attempts") - 1),
        base_delay=flags.get_flag("retry_base_delay_ms") / 1000.0 * 4)
    kw.update(overrides)
    return _from_flags(**kw)
