"""Deterministic fault injection for the training runtime.

The runtime's recovery paths (checkpoint rollback, RPC retry, compile-cache
invalidation) are only trustworthy if they can be exercised on demand, on one
host, reproducibly. This module plants named *sites* in the hot paths —

    ckpt.write        save_sharded, before the orbax commit
    ps.send           PSClient.send_var, before the wire
    ps.recv           PSClient.get_var, before the wire
    collective.step   Executor.run, once per executed step
    executor.compile  Executor._compile, before lowering
    rpc_drop          PSClient._call, before ANY request frame hits the wire
                      (send/get/prefetch retry it like a real transport drop;
                      barrier/checkpoint surface it)
    trainer_crash     PSClient.send_barrier — the trainer process dies via
                      os._exit(137) with no cleanup, the in-process stand-in
                      for a mid-round SIGKILL (only schedule it in a
                      subprocess worker's plan)
    heartbeat_loss    the PSClient heartbeat thread's tick — that beat is
                      silently skipped, so a scheduled run of hits starves
                      the server's liveness monitor into evicting
    pipeline_stall    Executor's async completion-token drain and the
                      DeviceLoader producer — the wait wedges as if the
                      device/feed hung, so the resilience watchdog must fire
    numeric_nan       Executor feed staging — a NaN is planted in the step's
                      first floating feed (the compiled step is opaque, so
                      the feed is the injection boundary); it propagates into
                      the loss and every gradient slot, which the in-graph
                      health sentinel must catch and skip
    numeric_spike     Executor feed staging — the first floating feed is
                      scaled 1e4x, driving a finite loss spike that the
                      sentinel's EMA gate (FLAGS_guard_spike_factor) must
                      catch
    collective_stall  Executor's async completion-token drain, for steps
                      dispatched under the shard_map/with_collective regime
                      only — the drain wedges as if one rank of the mesh
                      never posted its allreduce (a lost collective
                      partner), so the PR 3 watchdog must surface the hung
                      allreduce with step ids and queue depths instead of
                      blocking forever
    serving_abort     ServingEngine.step, once per scheduler iteration —
                      the oldest running generate-request is aborted
                      mid-decode (the client vanished), so its KV pages
                      must return to the free list; the chaos test drives
                      repeated abort cycles and asserts the pool leaks
                      zero pages
    serving_step_fail ServingEngine._dispatch, before every compiled
                      prefill/decode/window/COW step — the dispatch fails
                      like a lost device transport; the engine's
                      RetryPolicy must absorb isolated hits, and a run of
                      hits exhausting the attempts must trigger the
                      recovery pass (quarantine + pool rebuild + replay),
                      never a poisoned batch
    serving_pool_corrupt
                      ServingEngine.step, once per scheduler iteration —
                      one piece of host-side pool bookkeeping is
                      vandalized (phantom refcount holder, live page
                      pushed back on the free list, or a duplicate
                      ordinal in a request's page table); the periodic
                      PagedKVPool.check_consistency audit must detect it
                      and the recovery pass must rebuild a clean pool
    serving_deadline  ServingEngine.step, once per scheduler iteration —
                      the oldest live request's deadline is forced into
                      the past, so the expiry machinery must surface it
                      as deadline_exceeded with every page returned
    fleet_replica_kill
                      EngineReplica.pump_once, once per pump iteration —
                      the replica dies SIGKILL-style: its engine is never
                      touched again, its heartbeat stops, and NOTHING is
                      announced; the router's HeartbeatMonitor must
                      discover the death by missed beats and replay every
                      in-flight request from its prompt on a survivor
                      (token-deduplicated at the router, bitwise-exact
                      under greedy)
    fleet_replica_hang
                      EngineReplica.pump_once — the replica wedges: the
                      pump keeps getting called but makes no progress and
                      stamps no beats (a hung host, not a dead one); the
                      health checker must treat it exactly like a kill
    fleet_heartbeat_slow
                      EngineReplica.pump_once, at the beat stamp — ONE
                      beat is silently dropped (a slow/loaded host), so a
                      correctly-margined deadline (FLAGS_fleet_heartbeat_s
                      x FLAGS_watchdog_scale) must NOT declare the replica
                      dead; a scheduled run of hits starves the monitor
                      into a (correct) death verdict
    disagg_prefill_kill
                      EngineReplica.pump_once, prefill-role replicas only
                      (disaggregated serving, ISSUE 19) — the prefill
                      replica dies SIGKILL-style exactly like
                      fleet_replica_kill; requests mid-prefill (or whose
                      lease never published) must replay on a surviving
                      prefill replica within the fleet_policy budget,
                      while already-published leases survive the death
                      (the shared pool, not the dead host, owns the pin)
                      and still commit
    disagg_handoff_drop
                      FleetRouter handling of a "prepared" event — the
                      event is dropped on the floor: the lease is
                      published and pinned but the commit is never
                      dispatched (a lost message between the stages), so
                      the lease REAPER must reclaim the orphaned pin at
                      TTL and the router must replay the prompt
    disagg_lease_expire_race
                      HandoffManager.commit — the lease's expiry is
                      forced into the past at the exact moment the commit
                      arrives, so the reap-vs-commit race resolves REAP:
                      the commit must be rejected atomically (never a
                      half-adopted table), the pin reclaimed once, and
                      the request replayed cleanly
    emb_host_stall    the tiered-embedding miss resolver
                      (embedding/engine.resolve_feed) — the host-tier
                      prefetch parks forever (a hung remote shard / page-in
                      storm stand-in) on the DeviceLoader's producer
                      thread, so the PR 3 consumer-side stall watchdog
                      must surface it with queue depths instead of the
                      trainer hanging on an empty staging queue

— and a *plan* that decides, per site and per hit, whether to raise an
`InjectedFault`. Plans are either explicit hit schedules or seeded Bernoulli
draws; both are pure functions of (site, hit index), so a failing chaos run
replays exactly from its plan string.

Plan spec grammar (the `FLAGS_fault_plan` value / `fault_scope` argument):

    "ckpt.write:2;ps.send:1,4"      raise on those 1-based hits of each site
    "rand:p=0.2,seed=7"             each hit at every site fails w.p. 0.2
    "rand:p=0.2,seed=7,sites=ps.send|ps.recv,max=5"
                                    restrict sites; stop after 5 faults total

The schedule is *per-process*: subprocess trainers inherit the plan through
the FLAGS_fault_plan environment variable (flags.py reads FLAGS_* at import).
"""
from __future__ import annotations

import hashlib
import threading
from contextlib import contextmanager

__all__ = ["FAULT_SITES", "InjectedFault", "FaultPlan", "fault_point",
           "fault_scope", "fault_stats", "install_plan"]

# the named sites the runtime instruments; fault_point accepts only these so
# a typo'd site name fails loudly instead of silently never firing
FAULT_SITES = frozenset({
    "ckpt.write", "ps.send", "ps.recv", "collective.step", "executor.compile",
    "rpc_drop", "trainer_crash", "heartbeat_loss", "pipeline_stall",
    "collective_stall", "numeric_nan", "numeric_spike", "serving_abort",
    "emb_host_stall", "serving_step_fail", "serving_pool_corrupt",
    "serving_deadline", "fleet_replica_kill", "fleet_replica_hang",
    "fleet_heartbeat_slow", "disagg_prefill_kill", "disagg_handoff_drop",
    "disagg_lease_expire_race",
})


class InjectedFault(ConnectionError):
    """Raised by fault_point on schedule.

    Subclasses ConnectionError so the injected failure travels the same
    except-clauses real transport faults do — the recovery code under test
    must not need to know it is being tested.
    """

    def __init__(self, site: str, hit: int):
        super().__init__(f"injected fault at '{site}' (hit {hit})")
        self.site = site
        self.hit = hit


class FaultPlan:
    """A deterministic (site, hit index) -> should-raise schedule."""

    def __init__(self, schedule: dict[str, frozenset[int]] | None = None,
                 p: float = 0.0, seed: int = 0,
                 sites: frozenset[str] | None = None,
                 max_faults: int | None = None, spec: str = ""):
        self.schedule = schedule or {}
        self.p = float(p)
        self.seed = int(seed)
        self.sites = sites  # None = every site (random mode only)
        self.max_faults = max_faults
        self.spec = spec
        self._hits: dict[str, int] = {}
        self._fired: list[tuple[str, int]] = []
        self._lock = threading.Lock()

    # -- construction --------------------------------------------------------
    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        spec = (spec or "").strip()
        if not spec:
            return cls(spec=spec)
        if spec.startswith("rand:"):
            p, seed, sites, max_faults = 0.0, 0, None, None
            for kv in spec[len("rand:"):].split(","):
                k, _, v = kv.partition("=")
                k, v = k.strip(), v.strip()
                if k == "p":
                    p = float(v)
                elif k == "seed":
                    seed = int(v)
                elif k == "sites":
                    sites = frozenset(s.strip() for s in v.split("|") if s)
                elif k == "max":
                    max_faults = int(v)
                else:
                    raise ValueError(f"unknown fault-plan key '{k}' in {spec!r}")
            unknown = (sites or frozenset()) - FAULT_SITES
            if unknown:
                raise ValueError(f"unknown fault sites {sorted(unknown)}; "
                                 f"known: {sorted(FAULT_SITES)}")
            return cls(p=p, seed=seed, sites=sites, max_faults=max_faults,
                       spec=spec)
        schedule: dict[str, frozenset[int]] = {}
        for part in spec.split(";"):
            part = part.strip()
            if not part:
                continue
            site, _, hits = part.partition(":")
            site = site.strip()
            if site not in FAULT_SITES:
                raise ValueError(f"unknown fault site '{site}'; known: "
                                 f"{sorted(FAULT_SITES)}")
            schedule[site] = frozenset(int(h) for h in hits.split(",") if h)
        return cls(schedule=schedule, spec=spec)

    # -- the decision --------------------------------------------------------
    def _draw(self, site: str, hit: int) -> bool:
        """Seeded Bernoulli, pure in (seed, site, hit): a replayed plan makes
        identical decisions regardless of thread interleaving."""
        h = hashlib.sha256(f"{self.seed}:{site}:{hit}".encode()).digest()
        return int.from_bytes(h[:8], "big") / 2**64 < self.p

    def check(self, site: str) -> None:
        with self._lock:
            hit = self._hits.get(site, 0) + 1
            self._hits[site] = hit
            fire = False
            if site in self.schedule:
                fire = hit in self.schedule[site]
            elif self.p > 0.0 and (self.sites is None or site in self.sites):
                if (self.max_faults is None
                        or len(self._fired) < self.max_faults):
                    fire = self._draw(site, hit)
            if fire:
                self._fired.append((site, hit))
        if fire:
            raise InjectedFault(site, hit)

    # -- introspection -------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {"hits": dict(self._hits), "fired": list(self._fired),
                    "spec": self.spec}


_active: FaultPlan | None = None
_install_lock = threading.Lock()


def _bootstrap_from_flags() -> None:
    """Pick up FLAGS_fault_plan (env or set_flags) lazily, once."""
    global _active
    from .. import flags

    try:
        spec = flags.get_flag("fault_plan")
    except KeyError:  # flags module not fully imported yet
        return
    if spec:
        with _install_lock:
            if _active is None:
                _active = FaultPlan.parse(spec)


_bootstrapped = False


def install_plan(plan: "FaultPlan | str | None") -> FaultPlan | None:
    """Install (or clear, with None) the process-wide plan; returns the
    previous one. Prefer `fault_scope` in tests."""
    global _active, _bootstrapped
    if isinstance(plan, str):
        plan = FaultPlan.parse(plan)
    with _install_lock:
        prev, _active = _active, plan
        _bootstrapped = True  # explicit install wins over the env flag
    return prev


def fault_point(site: str) -> None:
    """The instrumented sites call this; near-free when no plan is active."""
    global _bootstrapped
    if _active is None:
        if not _bootstrapped:
            _bootstrapped = True
            _bootstrap_from_flags()
            if _active is None:
                return
        else:
            return
    if site not in FAULT_SITES:
        raise ValueError(f"unknown fault site '{site}'; known: "
                         f"{sorted(FAULT_SITES)}")
    _active.check(site)


@contextmanager
def fault_scope(plan: "FaultPlan | str"):
    """Scoped plan for tests: install on entry, restore the previous plan on
    exit. Yields the plan so the test can assert on .stats()."""
    if isinstance(plan, str):
        plan = FaultPlan.parse(plan)
    prev = install_plan(plan)
    try:
        yield plan
    finally:
        install_plan(prev)


def fault_stats() -> dict:
    """Hit/fire counters of the active plan ({} when none)."""
    return _active.stats() if _active is not None else {}
