"""Hang watchdogs: bounded waits that dump state instead of blocking forever.

A distributed training step can wedge in ways a retry policy never sees —
the device executable deadlocks, a feed pipeline's producer thread dies
holding its queue, a pserver stops mid-round. The symptom is always the
same: some host-side wait (`Executor.wait` draining a completion token, a
`DeviceLoader` consumer blocking on the staging queue) simply never
returns, and the job hangs with zero diagnostics until an external timeout
kills it.

This module turns those waits into *bounded* waits. `Watchdog.wait`
polls a readiness predicate; if `FLAGS_watchdog_stall_s` passes with no
progress it raises `StallError` carrying a state dump (in-flight step ids,
queue depths, per-stage profiler counters) assembled at the moment of the
stall — the forensic record the reference stack's `GetMonitorThreadPool`
style hang reports provide, but as a structured exception the caller (or a
CheckpointedRunner) can act on.

The `pipeline_stall` fault site (resilience/faults.py) simulates a wedge on
demand so the watchdog path is testable on one healthy host.
"""
from __future__ import annotations

import json
import logging
import time
from typing import Callable

__all__ = ["StallError", "Watchdog", "HeartbeatMonitor", "stall_window_s",
           "watchdog_scale", "runtime_state"]

logger = logging.getLogger("paddle_tpu.resilience.watchdog")


def watchdog_scale() -> float:
    """FLAGS_watchdog_scale, clamped to >= 1.0: one global multiplier every
    watchdog window and heartbeat deadline applies, so a loaded CI runner
    widens every margin at once instead of flaking site by site."""
    from .. import flags

    try:
        return max(1.0, float(flags.get_flag("watchdog_scale")))
    except KeyError:  # flags module mid-import
        return 1.0


def stall_window_s() -> float:
    """The configured watchdog window in seconds (<=0 = disabled), widened
    by FLAGS_watchdog_scale."""
    from .. import flags

    try:
        return float(flags.get_flag("watchdog_stall_s")) * watchdog_scale()
    except KeyError:  # flags module mid-import
        return 0.0


class StallError(RuntimeError):
    """No progress within the watchdog window; `.state` holds the dump."""

    def __init__(self, what: str, window_s: float, state: dict | None = None):
        self.what = what
        self.window_s = float(window_s)
        self.state = dict(state or {})
        try:
            dump = json.dumps(self.state, indent=1, default=str, sort_keys=True)
        except (TypeError, ValueError):
            dump = repr(self.state)
        super().__init__(
            f"{what}: no progress within {window_s:.3g}s "
            f"(FLAGS_watchdog_stall_s) — in-flight state:\n{dump}")
        # structured copies of the dump: the exception message above stays
        # the human-readable record, while the telemetry registry and the
        # logging tree carry the same state for machine consumers
        try:
            from .. import observability as obs

            obs.counter_inc("watchdog.stalls")
            obs.event("watchdog.stall",
                      {"what": what, "window_s": self.window_s,
                       "state": self.state}, level="error")
        except Exception:  # noqa: BLE001 — telemetry never masks the stall
            pass
        logger.error("stall: %s (no progress within %.3gs)", what, window_s,
                     extra={"stall_state": self.state})


class Watchdog:
    """Poll-based stall detector for host-side waits.

    `wait(ready, state, what)` returns as soon as `ready()` is truthy and
    raises `StallError(what, window, state())` once `window_s` elapses.
    The poll interval self-scales (1ms .. 50ms) so short waits stay cheap
    and long ones don't spin.
    """

    def __init__(self, window_s: float | None = None):
        self.window_s = (stall_window_s() if window_s is None
                         else float(window_s))

    @property
    def enabled(self) -> bool:
        return self.window_s > 0.0

    def wait(self, ready: Callable[[], bool],
             state: Callable[[], dict] | None = None,
             what: str = "wait") -> None:
        deadline = time.monotonic() + self.window_s
        interval = 0.001
        while not ready():
            if time.monotonic() > deadline:
                raise StallError(what, self.window_s,
                                 state() if state is not None else {})
            time.sleep(interval)
            interval = min(interval * 2, 0.05)


class HeartbeatMonitor:
    """Per-participant heartbeat ledger: the Watchdog generalized from one
    bounded wait to N long-lived peers (fleet engine replicas, and the
    same shape the pserver's trainer-liveness monitor keeps server-side).

    Participants `register()` once and `beat()` whenever they make
    progress; `overdue(now)` returns everyone whose last beat is older
    than the deadline — the caller owns what "dead" means (the fleet
    router fails their work over, a trainer monitor evicts them from the
    barrier). The deadline is widened by FLAGS_watchdog_scale exactly like
    the stall windows, so one CI knob de-flakes every liveness check.
    A deadline <= 0 disables the monitor (`overdue` is always empty)."""

    def __init__(self, deadline_s: float, scale: float | None = None):
        self.deadline_s = float(deadline_s) * (
            watchdog_scale() if scale is None else max(1.0, float(scale)))
        self._last: dict[str, float] = {}

    @property
    def enabled(self) -> bool:
        return self.deadline_s > 0.0

    def register(self, name: str, now: float | None = None) -> None:
        self._last[name] = time.monotonic() if now is None else now

    def deregister(self, name: str) -> None:
        self._last.pop(name, None)

    def beat(self, name: str, now: float | None = None) -> None:
        if name in self._last:
            self._last[name] = time.monotonic() if now is None else now

    def age(self, name: str, now: float | None = None) -> float:
        now = time.monotonic() if now is None else now
        return now - self._last[name]

    def overdue(self, now: float | None = None) -> list[str]:
        if not self.enabled:
            return []
        now = time.monotonic() if now is None else now
        return [n for n, t in self._last.items()
                if now - t > self.deadline_s]


def runtime_state(**extra) -> dict:
    """Common state-dump fields every watchdog site includes: per-stage
    profiler counters plus whatever the site knows (step ids, depths)."""
    from .. import profiler

    out = {"profiler_stages": profiler.stage_counters()}
    out.update(extra)
    return out
