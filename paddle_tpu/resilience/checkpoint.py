"""CheckpointManager: atomic, versioned, self-describing train checkpoints.

Layered on io.save_sharded/load_sharded (the host-parallel orbax path). A
checkpoint root looks like:

    root/
      step_00000010/
        manifest.json     step, program hash, RNG run-counter, var names
        state/            orbax/TensorStore sharded arrays
      step_00000020/
        ...

Guarantees the bare save_sharded cannot give:

  * atomic visibility — a step directory appears under its final name only
    after every byte (state + manifest) is on disk and fsync'd; a crash
    mid-save leaves a `.tmp-*` orphan that the next GC sweeps, never a
    half-checkpoint that a resume could trust;
  * versioning + GC — per-step directories, keep-last-k pruning;
  * provenance — the manifest records the program hash (a resume against a
    different program warns/fails instead of silently loading mismatched
    state) and the scope's RNG run-counter (so counter-derived randomness
    continues, not restarts, after resume);
  * rollback — restore() walks steps newest-first, quarantines unreadable or
    corrupt candidates to `.corrupt-*`, and falls back to the newest good
    one (the reference trainer's "load last good checkpoint" loop).
"""
from __future__ import annotations

import hashlib
import json
import os
import shutil
import time
import warnings

__all__ = ["CheckpointManager"]

_MANIFEST = "manifest.json"
_STATE = "state"
_STEP_PREFIX = "step_"
_GUARD_EVENTS = "guard_events.json"
_FORMAT = 1


def _program_hash(program) -> str:
    blob = json.dumps(program.to_dict(), sort_keys=True,
                      separators=(",", ":")).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # e.g. platforms without O_RDONLY dirs
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class CheckpointManager:
    def __init__(self, root: str, keep_last_k: int | None = None,
                 main_program=None, scope=None):
        from .. import flags

        self.root = os.path.abspath(root)
        self.keep_last_k = (flags.get_flag("ckpt_keep_last_k")
                            if keep_last_k is None else int(keep_last_k))
        self._program = main_program
        self._scope = scope
        os.makedirs(self.root, exist_ok=True)
        # numeric-guard forensic record (resilience/guardrails.StepGuard):
        # every skip/rewind lands here, is mirrored into each saved
        # manifest, AND persists in root/guard_events.json — so the
        # post-mortem survives a process restart even if no save follows
        # the event. steps()/latest_step() never see this file.
        self._guard_events: list[dict] = self._load_guard_events()
        # auxiliary state providers (e.g. the tiered-embedding host tier,
        # embedding/checkpoint.py): each writes extra files into the atomic
        # step directory at save and re-reads them at restore, with its
        # manifest fragment under manifest["extra"][provider.name]
        self._providers: list = []

    # -- auxiliary state providers -------------------------------------------
    def register_state_provider(self, provider) -> None:
        """provider contract: `.name`, `.save_state(manager, tmp_dir, step,
        executor=, program=, scope=) -> frag`, `.restore_state(manager,
        step_dir, step, frag, executor=, program=, scope=)`."""
        self._providers.append(provider)

    def _providers_for(self, program) -> list:
        """Registered providers, plus auto-discovery: a program carrying a
        tiered-embedding engine (passes.rewrite_tiered_embeddings) gets its
        host-tier delta provider without explicit wiring — the runner /
        train_from_dataset checkpoint paths stay zero-config."""
        engine = getattr(program, "_tiered_engine", None)
        if engine is not None and not any(
                getattr(p, "_engine", None) is engine
                for p in self._providers):
            from ..embedding.checkpoint import EmbeddingStateProvider

            self._providers.append(EmbeddingStateProvider(engine))
        return list(self._providers)

    # -- context defaults ----------------------------------------------------
    def _resolve(self, main_program, scope):
        from ..executor import global_scope
        from ..framework import default_main_program

        return (main_program or self._program or default_main_program(),
                scope or self._scope or global_scope())

    # -- directory naming ----------------------------------------------------
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.root, f"{_STEP_PREFIX}{step:08d}")

    def steps(self) -> list[int]:
        """Steps with a committed (renamed) directory, ascending. Commit
        atomicity means presence under the final name implies a complete
        write; manifest validity is still re-checked at restore time."""
        out = []
        try:
            entries = os.listdir(self.root)
        except FileNotFoundError:
            return out
        for name in entries:
            if not name.startswith(_STEP_PREFIX):
                continue
            try:
                out.append(int(name[len(_STEP_PREFIX):]))
            except ValueError:
                continue
        return sorted(out)

    def latest_step(self) -> int | None:
        steps = self.steps()
        return steps[-1] if steps else None

    def read_manifest(self, step: int) -> dict:
        with open(os.path.join(self._step_dir(step), _MANIFEST)) as f:
            return json.load(f)

    # -- guard events --------------------------------------------------------
    def _events_path(self) -> str:
        return os.path.join(self.root, _GUARD_EVENTS)

    def _load_guard_events(self) -> list[dict]:
        try:
            with open(self._events_path()) as f:
                data = json.load(f)
            return list(data) if isinstance(data, list) else []
        except (OSError, ValueError):
            return []

    def record_guard_event(self, step: int, reason: str, action: str,
                           detail=None) -> dict:
        """Append one numeric-guard event (skip/rewind/surface). Durable
        immediately via an atomic write of guard_events.json; also embedded
        in every later manifest. latest_step() is unaffected."""
        evt = {"step": int(step), "reason": str(reason),
               "action": str(action), "time": time.time()}
        if detail is not None:
            evt["detail"] = detail
        self._guard_events.append(evt)
        tmp = self._events_path() + ".tmp"
        try:
            with open(tmp, "w") as f:
                # default=str: blame reports may carry non-JSON leaves
                json.dump(self._guard_events, f, indent=1, default=str)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._events_path())
        except OSError:
            # forensics must never take training down with them
            try:
                os.unlink(tmp)
            except OSError:
                pass
        return evt

    def guard_events(self) -> list[dict]:
        return list(self._guard_events)

    # -- save ----------------------------------------------------------------
    def save(self, step: int, executor=None, main_program=None,
             scope=None) -> str:
        """Write the checkpoint for `step`; returns the committed path.

        On a multi-process mesh every process calls this (save_sharded needs
        all of them for its shard writes); the manifest + commit rename are
        process-0-only, mirroring save_sharded's own commit."""
        import jax

        from .. import io

        program, scope = self._resolve(main_program, scope)
        primary = jax.process_index() == 0
        step = int(step)
        final = self._step_dir(step)
        # same stage path on every process — save_sharded coordinates the
        # multi-host orbax write against it
        tmp = os.path.join(self.root, f".tmp-{step:08d}")
        if primary:
            if os.path.exists(tmp):
                shutil.rmtree(tmp)
            os.makedirs(tmp)
        try:
            io.save_sharded(executor, os.path.join(tmp, _STATE),
                            main_program=program, scope=scope)
            if not primary:
                return final
            extra = {}
            for provider in self._providers_for(program):
                frag = provider.save_state(self, tmp, step,
                                           executor=executor,
                                           program=program, scope=scope)
                if frag is not None:
                    extra[provider.name] = frag
            manifest = {
                "format": _FORMAT,
                "step": step,
                "program_hash": _program_hash(program),
                "rng_counter": scope._run_counter,
                "random_seed": program.random_seed or 0,
                "var_names": sorted(
                    v.name for v in program.list_vars()
                    if getattr(v, "persistable", False)
                    and scope.has_var(v.name)),
                "guard_events": json.loads(
                    json.dumps(self._guard_events, default=str)),
                "time": time.time(),
            }
            if extra:
                manifest["extra"] = extra
            mpath = os.path.join(tmp, _MANIFEST)
            with open(mpath, "w") as f:
                json.dump(manifest, f, indent=1)
                f.flush()
                os.fsync(f.fileno())
            _fsync_dir(tmp)
            # commit: the final name appears in one rename; re-saving the
            # same step replaces the old directory
            if os.path.exists(final):
                shutil.rmtree(final)
            os.replace(tmp, final)
            _fsync_dir(self.root)
        except BaseException:
            if primary:
                shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._gc()
        return final

    def _gc(self) -> None:
        """Prune beyond keep-last-k and sweep crash orphans (runs after a
        successful commit, so any remaining .tmp-* is a dead save)."""
        if self.keep_last_k and self.keep_last_k > 0:
            for step in self.steps()[:-self.keep_last_k]:
                shutil.rmtree(self._step_dir(step), ignore_errors=True)
        for name in os.listdir(self.root):
            if name.startswith(".tmp-") or name.startswith(".corrupt-"):
                shutil.rmtree(os.path.join(self.root, name),
                              ignore_errors=True)

    # -- restore -------------------------------------------------------------
    def _validate(self, step: int, program) -> dict:
        manifest = self.read_manifest(step)
        if manifest.get("format") != _FORMAT:
            raise ValueError(
                f"checkpoint step {step}: unknown manifest format "
                f"{manifest.get('format')!r}")
        if not os.path.isdir(os.path.join(self._step_dir(step), _STATE)):
            raise FileNotFoundError(
                f"checkpoint step {step}: missing state directory")
        want = _program_hash(program)
        got = manifest.get("program_hash")
        if got != want:
            warnings.warn(
                f"checkpoint step {step} was saved from a different program "
                f"(hash {got} != {want}); restoring the intersection of "
                f"persistables", stacklevel=3)
        return manifest

    def _quarantine(self, step: int, reason: Exception) -> None:
        src = self._step_dir(step)
        dst = os.path.join(self.root, f".corrupt-{_STEP_PREFIX}{step:08d}")
        warnings.warn(
            f"checkpoint step {step} is unreadable ({reason}); quarantined "
            f"to {dst} — rolling back to the previous checkpoint",
            stacklevel=3)
        shutil.rmtree(dst, ignore_errors=True)
        try:
            os.replace(src, dst)
        except OSError:
            shutil.rmtree(src, ignore_errors=True)

    def restore(self, step: int | None = None, executor=None,
                main_program=None, scope=None, shardings=None) -> int | None:
        """Load the newest good checkpoint (or exactly `step` if given).

        Returns the restored step, or None when the root holds no
        checkpoint at all (fresh start). Corrupt candidates are quarantined
        and the next-older one is tried — unless an explicit `step` was
        requested, which fails hard rather than silently substituting."""
        from .. import io

        program, scope = self._resolve(main_program, scope)
        explicit = step is not None
        candidates = [int(step)] if explicit else list(reversed(self.steps()))
        if explicit and int(step) not in self.steps():
            raise FileNotFoundError(
                f"no checkpoint for step {step} under {self.root}")
        for cand in candidates:
            try:
                manifest = self._validate(cand, program)
                io.load_sharded(executor,
                                os.path.join(self._step_dir(cand), _STATE),
                                main_program=program, scope=scope,
                                shardings=shardings)
                extra = manifest.get("extra") or {}
                for provider in self._providers_for(program):
                    # a provider whose files are gone/corrupt raises here,
                    # so the candidate quarantines and the next-older one
                    # is tried — same contract as the state dir itself
                    provider.restore_state(
                        self, self._step_dir(cand), cand,
                        extra.get(provider.name), executor=executor,
                        program=program, scope=scope)
            except Exception as e:
                if explicit:
                    raise
                self._quarantine(cand, e)
                continue
            # resume counter-derived RNG where the save left off, not at 0
            scope._run_counter = int(manifest.get("rng_counter", 0))
            return cand
        return None
