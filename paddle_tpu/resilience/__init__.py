"""Fault-tolerant training runtime (SURVEY §5 checkpoint/resume, grown up).

On a pod, preemption and transient I/O or RPC failure are the normal case:
the reference stack leaned on pserver-side checkpointing and retry loops for
exactly this. This package is the TPU-native equivalent — four pieces that
compose into a training loop that survives partial failure:

  * faults    — seeded, deterministic fault-injection registry; named sites
                raise on a reproducible schedule so every recovery path is
                testable on one host (`FLAGS_fault_plan` / `fault_scope`).
  * retry     — `RetryPolicy` (exponential backoff + deterministic jitter +
                deadline) applied to pserver RPCs and orbax checkpoint I/O.
  * checkpoint— `CheckpointManager`: atomic per-step versioned directories
                over save_sharded/load_sharded with a manifest (step,
                program hash, RNG counter), keep-last-k GC, corrupt-
                checkpoint rollback, and `latest_step()` auto-resume.
  * runner    — `CheckpointedRunner`: an Executor.run training loop with
                periodic save, restore-and-replay on fault, and graceful
                degradation (cache invalidation, then jax.disable_jit)
                before surfacing the error.
  * watchdog  — `Watchdog`/`StallError`: bounded host-side waits for the
                async executor drain and DeviceLoader; a wedged step dumps
                in-flight state instead of hanging forever
                (`FLAGS_watchdog_stall_s`).
  * guardrails— numeric-fault recovery: the in-graph health sentinel
                (`FLAGS_guard_numerics`, appended by minimize()) plus
                `StepGuard` — bad steps skip in-graph, budget overruns
                rewind via CheckpointManager with LR backoff, and the
                offending step replays eagerly for an op-attributed blame
                report (`replay_blame`).
"""
from .faults import (  # noqa: F401
    FAULT_SITES,
    FaultPlan,
    InjectedFault,
    fault_point,
    fault_scope,
    fault_stats,
    install_plan,
)
from .retry import (  # noqa: F401
    RetryPolicy, connect_policy, fleet_policy, io_policy, rpc_policy)
from .checkpoint import CheckpointManager  # noqa: F401
from .runner import CheckpointedRunner, StepFailure  # noqa: F401
from .watchdog import (  # noqa: F401
    HeartbeatMonitor, StallError, Watchdog, stall_window_s, watchdog_scale)
from .guardrails import (  # noqa: F401
    GUARD_HEALTH_NAME,
    GUARD_STATE_NAME,
    GuardError,
    GuardRewind,
    StepGuard,
    replay_blame,
)

__all__ = [
    "FAULT_SITES", "FaultPlan", "InjectedFault", "fault_point",
    "fault_scope", "fault_stats", "install_plan",
    "RetryPolicy", "io_policy", "rpc_policy", "fleet_policy",
    "connect_policy",
    "CheckpointManager", "CheckpointedRunner", "StepFailure",
    "StallError", "Watchdog", "HeartbeatMonitor", "stall_window_s",
    "watchdog_scale",
    "GUARD_HEALTH_NAME", "GUARD_STATE_NAME", "GuardError", "GuardRewind",
    "StepGuard", "replay_blame",
]
