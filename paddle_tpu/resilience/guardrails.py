"""Numeric guardrails: in-graph health sentinel + StepGuard skip/rewind
policy + op-level blame isolation.

A long-lived compiled XLA step gives numeric faults nowhere to surface: one
NaN/Inf (corrupt batch, fp16 overflow, LR too hot) silently poisons the
optimizer state, and on the PS path every worker downstream of it. The
eager-only FLAGS_check_nan_inf debug mode cannot help — real training never
leaves the jit path. Following the AMP decorator's found_inf pattern
(contrib/mixed_precision/decorator.py) and the program-transformation stance
of the compiler literature (PAPERS.md TVM), the guard is APPENDED TO THE
PROGRAM, not bolted onto user code:

  * `health_sentinel` op (ops/optimizer_ops.py), appended by
    Optimizer.apply_gradients under FLAGS_guard_numerics: computes
    [loss, global_grad_norm, nonfinite, bad] INSIDE the compiled step and
    zeroes every gradient on a bad step (branchless skip — the AMP
    found_inf mechanism generalized to fp32; both share one verdict when
    composed). The vector lands in the persistable @GUARD_HEALTH@ var and
    the executor emits it alongside the PR 2 completion token, so health
    is observable every step with no interpreter fallback and no sync.

  * `StepGuard` — the host-side policy. Executor.run_async hands it each
    drained step's health (the read happens after the step's token
    completed, so it costs a 4-float transfer). The recovery ladder:

      skip      in-graph, always: a bad step's update never lands
      rewind    after FLAGS_guard_bad_step_budget CONSECUTIVE bad steps,
                restore the newest good checkpoint (CheckpointManager)
      backoff   multiply the LR by FLAGS_guard_lr_backoff on each rewind
      surface   after FLAGS_guard_max_rewinds rewinds, raise GuardError

  * blame isolation — after a rewind, `replay_blame` re-runs the offending
    step EAGERLY (jax.disable_jit + FLAGS_check_nan_inf) on a scratch copy
    of the restored scope: the first op producing a non-finite output is
    named with its creation stack, yielding an op/var/batch-attributed
    report that is recorded as a guard event (CheckpointManager manifest)
    and never touches live training state.

Every event (step, reason, action, detail) is mirrored into
CheckpointManager.record_guard_event so post-mortems survive restarts.
"""
from __future__ import annotations

import collections
import warnings

import numpy as np

from .. import flags

__all__ = [
    "GUARD_HEALTH_NAME", "GUARD_STATE_NAME",
    "H_LOSS", "H_GNORM", "H_NONFINITE", "H_BAD",
    "GuardError", "GuardRewind", "StepGuard",
    "append_health_sentinel", "enabled", "replay_blame",
]

# the sentinel's program-level contract (AMP-style reserved names): the op
# writes the health vector here and the executor looks it up by name
GUARD_HEALTH_NAME = "@GUARD_HEALTH@"
GUARD_STATE_NAME = "@GUARD_STATE@"

# health vector layout (ops/optimizer_ops.py health_sentinel)
H_LOSS, H_GNORM, H_NONFINITE, H_BAD = 0, 1, 2, 3


def enabled() -> bool:
    return bool(flags.get_flag("guard_numerics"))


def append_health_sentinel(params_grads, loss_name: str | None = None):
    """Program transformation: route every gradient through one
    `health_sentinel` op (called by Optimizer.apply_gradients after
    clip/regularization, so a NaN that clip smeared over all grads is still
    caught). Returns params_grads rebuilt over the gated gradients."""
    from ..framework import default_main_program
    from ..initializer import Constant
    from ..layer_helper import LayerHelper

    program = default_main_program()
    loss_name = loss_name or getattr(program, "_guard_loss_name", None)
    if loss_name is None:
        raise RuntimeError(
            "FLAGS_guard_numerics needs the loss variable: call "
            "optimizer.minimize(loss) (Optimizer.backward records it)")
    helper = LayerHelper("guardrails")
    health = helper.create_or_get_global_variable(
        GUARD_HEALTH_NAME, [4], "float32", initializer=Constant(0.0))
    state = helper.create_or_get_global_variable(
        GUARD_STATE_NAME, [2], "float32", initializer=Constant(0.0))
    live = [(p, g) for p, g in params_grads if g is not None]
    if not live:
        return params_grads
    gated = [helper.create_variable_for_type_inference(g.dtype)
             for _, g in live]
    inputs = {"X": [g.name for _, g in live], "Loss": [loss_name],
              "State": [state.name]}
    amp_found = getattr(program, "_guard_found_inf_name", None)
    if amp_found is not None:
        # AMP already votes: its @FOUND_INF@ ORs into the sentinel verdict
        inputs["FoundInfinite"] = [amp_found]
    helper.append_op(
        "health_sentinel", inputs,
        {"Out": [u.name for u in gated], "Health": [health.name],
         "StateOut": [state.name]},
        {"spike_factor": float(flags.get_flag("guard_spike_factor")),
         "ema_decay": 0.9},
    )
    it = iter(gated)
    return [(p, next(it) if g is not None else None)
            for p, g in params_grads]


class GuardError(RuntimeError):
    """The recovery ladder is exhausted (rewind budget spent, or a rewind
    was needed with nothing to rewind to) — training must stop."""

    def __init__(self, msg: str, events=None):
        super().__init__(msg)
        self.events = list(events or [])


class GuardRewind(RuntimeError):
    """Raised out of Executor.run_async/wait when StepGuard's consecutive
    bad-step budget is exhausted. train_from_dataset handles it (rewind +
    continue past the poison batch); manual run_async loops catch it and
    call guard.rewind(exe, err)."""

    def __init__(self, step_id: int, health, reason: str):
        super().__init__(
            f"numeric guard: bad-step budget exhausted at async step "
            f"{step_id} ({reason}; health={np.asarray(health).tolist()})")
        self.step_id = step_id
        self.health = np.asarray(health, np.float32)
        self.reason = reason


class StepGuard:
    """Host-side bad-step policy over the in-graph health vector.

    manager: CheckpointManager (or a checkpoint-root path) used for the
    rewind rung and for durable guard-event recording; without one the
    guard still skips in-graph but surfaces GuardError instead of
    rewinding. program/scope default to the executor's at rewind time.
    """

    def __init__(self, manager=None, budget: int | None = None,
                 lr_backoff: float | None = None,
                 max_rewinds: int | None = None, program=None, scope=None,
                 blame: bool = True):
        from .checkpoint import CheckpointManager

        if isinstance(manager, str):
            manager = CheckpointManager(manager)
        self.manager = manager
        self.budget = (int(flags.get_flag("guard_bad_step_budget"))
                       if budget is None else int(budget))
        self.lr_backoff = (float(flags.get_flag("guard_lr_backoff"))
                           if lr_backoff is None else float(lr_backoff))
        self.max_rewinds = (int(flags.get_flag("guard_max_rewinds"))
                            if max_rewinds is None else int(max_rewinds))
        self.program = program
        self.scope = scope
        self.blame = blame
        self.skips = 0
        self.rewinds = 0
        self.events: list[dict] = []
        self.last_blame: dict | None = None
        self._consec_bad = 0
        # step_id -> feed, for the blame replay; bounded to the async window
        self._feeds: "collections.OrderedDict[int, dict]" = \
            collections.OrderedDict()
        self._feed_cap = max(int(flags.get_flag("max_inflight_steps")), 1) + 4

    # -- executor hooks ------------------------------------------------------
    def note_dispatch(self, step_id: int, feed: dict | None) -> None:
        """run_async calls this at dispatch so the poison batch is still
        around when its (window-delayed) health verdict arrives."""
        if feed is not None:
            self._feeds[step_id] = feed
            while len(self._feeds) > self._feed_cap:
                self._feeds.popitem(last=False)

    def observe(self, exe, step_id: int, health) -> str:
        """Called by Executor._drain_oldest AFTER the step's completion
        token resolved (the 4-float health read is free by then). Returns
        "ok"/"skip"; raises GuardRewind when the consecutive-bad budget is
        exhausted."""
        h = np.asarray(health, np.float32).reshape(-1)
        if not (h[H_BAD] > 0 or not np.isfinite(h[H_BAD])):
            self._consec_bad = 0
            self._feeds.pop(step_id, None)
            return "ok"
        self._consec_bad += 1
        self.skips += 1
        reason = ("nonfinite" if (h[H_NONFINITE] > 0
                                  or not np.isfinite(h[H_NONFINITE]))
                  else "loss_spike")
        self._record(step_id, reason, "skip",
                     {"loss": float(h[H_LOSS]),
                      "grad_norm": float(h[H_GNORM]),
                      "consecutive": self._consec_bad})
        if self._consec_bad > self.budget:
            raise GuardRewind(step_id, h, reason)
        return "skip"

    # -- the rewind rung -----------------------------------------------------
    def rewind(self, exe, err: GuardRewind) -> dict | None:
        """Restore the newest good checkpoint, back off the LR, replay the
        poison step eagerly for an op-attributed blame report, record
        everything. Returns the blame report (None if replay disabled).
        Raises GuardError when the ladder is exhausted."""
        from ..executor import global_scope
        from ..framework import default_main_program

        exe.drain_quiet()  # steps behind the bad one: complete, discard
        self._consec_bad = 0
        self.rewinds += 1
        if self.manager is None:
            raise GuardError(
                f"numeric guard: {err} — and no CheckpointManager is "
                f"attached, so there is nothing to rewind to; attach one "
                f"(StepGuard(manager=...)) or fix the data/LR",
                self.events) from err
        if self.rewinds > self.max_rewinds:
            raise GuardError(
                f"numeric guard: {self.rewinds} rewinds exceed "
                f"FLAGS_guard_max_rewinds={self.max_rewinds} — numeric "
                f"faults keep recurring after restore+LR-backoff; "
                f"surfacing. Last: {err}", self.events) from err
        program = self.program or default_main_program()
        scope = self.scope or global_scope()
        restored = self.manager.restore(executor=exe, main_program=program,
                                        scope=scope)
        if restored is None:
            warnings.warn(
                "StepGuard rewind found no checkpoint to restore — "
                "continuing from current (post-skip) state", stacklevel=2)
        backed_off = None
        if self.lr_backoff and self.lr_backoff != 1.0:
            backed_off = self._apply_lr_backoff(program, scope)
        report = None
        if self.blame:
            feed = self._feeds.get(err.step_id)
            if feed is not None:
                report = replay_blame(exe, program, feed, scope,
                                      step_id=err.step_id)
                self.last_blame = report
        self._record(err.step_id, err.reason, "rewind",
                     {"restored_step": restored, "lr_backoff": backed_off,
                      "rewind_index": self.rewinds, "blame": report})
        self._feeds.clear()
        return report

    def _apply_lr_backoff(self, program, scope):
        import jax.numpy as jnp

        lr_name = getattr(program, "_guard_lr_name", None)
        if not lr_name or not scope.has_var(lr_name):
            return None
        # the restore above reloaded the CHECKPOINT's LR, so compound the
        # backoff by how many rewinds this run has needed — each recurrence
        # halves (by default) the rate the replay resumes with
        factor = self.lr_backoff ** self.rewinds
        old = scope.find_var(lr_name)
        new = jnp.asarray(old) * factor
        scope.set_var(lr_name, new)
        return {"lr_name": lr_name, "factor": factor,
                "new_lr": float(np.asarray(new).reshape(-1)[0])}

    # -- bookkeeping ---------------------------------------------------------
    def _record(self, step_id: int, reason: str, action: str,
                detail: dict | None) -> None:
        evt = {"step": int(step_id), "reason": reason, "action": action}
        if detail:
            evt["detail"] = detail
        self.events.append(evt)
        from .. import observability as obs

        obs.counter_inc("guard.events", labels={"action": action})
        obs.event("guard.step", evt,
                  level="warning" if action != "note" else "info")
        if self.manager is not None:
            self.manager.record_guard_event(step_id, reason, action, detail)


def replay_blame(exe, program, feed: dict, scope, step_id=None) -> dict:
    """Op-level blame isolation: re-run one step EAGERLY (jax.disable_jit)
    under FLAGS_check_nan_inf on a scratch copy of the scope, so the first
    op emitting a non-finite value is named with its creation stack and live
    training state is untouched (jax arrays are immutable; the scratch scope
    absorbs every write). Returns an attribution report dict."""
    import jax

    from ..executor import Scope
    from ..framework import OpError

    scratch = Scope()
    scratch._vars.update(scope._vars)
    report: dict = {"step": step_id, "feed_keys": sorted(feed),
                    "op_type": None, "var": None}
    old = flags.get_flag("check_nan_inf")
    flags.set_flags({"check_nan_inf": True})
    try:
        with jax.disable_jit():
            exe.run(program, feed=feed, scope=scratch, fetch_list=[])
    except OpError as e:
        report["op_type"] = e.op.type
        report["var"] = next(
            (ns[0] for ns in e.op.outputs.values() if ns), None)
        report["detail"] = f"{type(e.cause).__name__}: {e.cause}"
        report["callstack"] = e.op.callstack_str()
    except Exception as e:  # noqa: BLE001 — forensic path must not throw
        report["detail"] = f"replay failed: {type(e).__name__}: {e}"
    else:
        # a loss spike replays finite: the batch itself is the attribution
        report["detail"] = ("replay finite after restore — batch-level "
                            "anomaly (loss spike), no single op to blame")
    finally:
        flags.set_flags({"check_nan_inf": old})
    return report
