"""Composite network helpers (reference python/paddle/fluid/nets.py:
simple_img_conv_pool, img_conv_group, glu:—, scaled_dot_product_attention:345)."""
from __future__ import annotations

from . import layers as L

__all__ = ["simple_img_conv_pool", "img_conv_group", "glu",
           "scaled_dot_product_attention"]


def simple_img_conv_pool(
    input, num_filters, filter_size, pool_size, pool_stride,
    pool_padding=0, pool_type="max", global_pooling=False,
    conv_stride=1, conv_padding=0, conv_dilation=1, conv_groups=1,
    param_attr=None, bias_attr=None, act=None,
):
    conv_out = L.conv2d(
        input, num_filters=num_filters, filter_size=filter_size,
        stride=conv_stride, padding=conv_padding, dilation=conv_dilation,
        groups=conv_groups, param_attr=param_attr, bias_attr=bias_attr, act=act,
    )
    return L.pool2d(conv_out, pool_size=pool_size, pool_type=pool_type,
                    pool_stride=pool_stride, pool_padding=pool_padding,
                    global_pooling=global_pooling)


def img_conv_group(
    input, conv_num_filter, pool_size, conv_padding=1, conv_filter_size=3,
    conv_act=None, param_attr=None, conv_with_batchnorm=False,
    conv_batchnorm_drop_rate=0.0, pool_stride=1, pool_type="max",
):
    tmp = input
    n = len(conv_num_filter)

    def _bcast(v):
        return v if isinstance(v, (list, tuple)) else [v] * n

    paddings, fsizes, attrs = _bcast(conv_padding), _bcast(conv_filter_size), _bcast(param_attr)
    with_bn, drops = _bcast(conv_with_batchnorm), _bcast(conv_batchnorm_drop_rate)
    for i in range(n):
        act = conv_act if not with_bn[i] else None
        tmp = L.conv2d(tmp, num_filters=conv_num_filter[i], filter_size=fsizes[i],
                       padding=paddings[i], param_attr=attrs[i], act=act)
        if with_bn[i]:
            tmp = L.batch_norm(tmp, act=conv_act)
            if drops[i] > 0:
                tmp = L.dropout(tmp, dropout_prob=drops[i])
    return L.pool2d(tmp, pool_size=pool_size, pool_type=pool_type,
                    pool_stride=pool_stride)


def glu(input, dim=-1):
    """Gated linear unit: split in half on `dim`, a * sigmoid(b)."""
    a, b = L.split(input, num_or_sections=2, dim=dim)
    return L.elementwise_mul(a, L.sigmoid(b))


def scaled_dot_product_attention(queries, keys, values, num_heads=1,
                                 dropout_rate=0.0):
    """Multi-head scaled dot-product attention over [B, S, H] tensors
    (reference nets.py:345). Returns [B, Sq, H_v]."""
    dh = queries.shape[-1] // num_heads
    sq, sk = queries.shape[-2], keys.shape[-2]

    def _split_heads(x, s):
        if num_heads == 1:
            return x
        x = L.reshape(x, shape=[0, s, num_heads, x.shape[-1] // num_heads])
        return L.transpose(x, perm=[0, 2, 1, 3])

    q = _split_heads(queries, sq)
    k = _split_heads(keys, sk)
    v = _split_heads(values, sk)
    scores = L.matmul(q, k, transpose_y=True, alpha=float(dh) ** -0.5)
    weights = L.softmax(scores)
    if dropout_rate:
        weights = L.dropout(weights, dropout_prob=dropout_rate,
                            dropout_implementation="upscale_in_train")
    ctx = L.matmul(weights, v)
    if num_heads == 1:
        return ctx
    ctx = L.transpose(ctx, perm=[0, 2, 1, 3])
    return L.reshape(ctx, shape=[0, sq, ctx.shape[-2] * ctx.shape[-1]])
