"""Checkpoint / serialization — save/load variables and inference models.

Reference: /root/reference/python/paddle/fluid/io.py (save_vars:128,
save_params:216, save_persistables:487, load_vars:566, load_params:662,
load_persistables:726, save_inference_model:933, load_inference_model:1113).

Design departure (SURVEY.md §5 checkpoint/resume): the reference executes
`save`/`load` OPS inside throwaway programs because its executor interprets
ops one-by-one on the host. Here the executor compiles whole blocks to XLA, so
file IO stays host-side: variables are read from the Scope (device→host
gather happens in np.asarray, which also reassembles GSPMD-sharded arrays)
and written one .npy per variable — the same name-keyed layout the reference
uses one file per var for. `filename=` packs everything into one .npz
(save_combine/load_combine equivalent). Programs serialize as JSON via
Program.to_dict (the framework.proto equivalent).
"""
from __future__ import annotations

import json
import os
import shutil
from typing import Callable, Sequence

import numpy as np

from .executor import Executor, Scope, global_scope
from .framework import Parameter, Program, Variable, default_main_program

__all__ = [
    "save_sharded",
    "load_sharded",
    "save_vars", "save_params", "save_persistables",
    "load_vars", "load_params", "load_persistables",
    "save_inference_model", "load_inference_model",
    "save_train_model",
    "load_train_model",
]

_MODEL_FILENAME = "__model__.json"
_SAFE = "%"


def _encode_name(name: str) -> str:
    """Var names may contain '/' etc.; make them filesystem-safe."""
    return "".join(c if (c.isalnum() or c in "._-@") else f"{_SAFE}{ord(c):02x}"
                   for c in name)


def _is_param(var: Variable) -> bool:
    return isinstance(var, Parameter)


def _is_persistable(var: Variable) -> bool:
    return bool(getattr(var, "persistable", False))


def _select_vars(program: Program, vars=None, predicate: Callable | None = None):
    if vars is not None:
        out = []
        for v in vars:
            out.append(program.global_block.var(v) if isinstance(v, str) else v)
        return out
    predicate = predicate or _is_persistable
    return [v for v in program.list_vars() if predicate(v)]


def save_vars(executor: Executor | None = None, dirname: str = "",
              main_program: Program | None = None, vars=None,
              predicate: Callable | None = None, filename: str | None = None,
              scope: Scope | None = None):
    """Write selected vars' scope values under `dirname` (io.py:128)."""
    if not dirname:
        raise ValueError("save_vars requires a target dirname")
    program = main_program or default_main_program()
    scope = scope or global_scope()
    selected = _select_vars(program, vars, predicate)
    os.makedirs(dirname, exist_ok=True)
    arrays = {}
    for v in selected:
        val = scope.find_var(v.name)
        if val is None:
            raise RuntimeError(
                f"variable '{v.name}' has no value in scope — run the startup "
                f"program (and a train step for accumulators) before saving")
        arrays[v.name] = np.asarray(val)
    if filename is not None:
        np.savez(os.path.join(dirname, filename),
                 **{_encode_name(k): a for k, a in arrays.items()})
    else:
        for k, a in arrays.items():
            np.save(os.path.join(dirname, _encode_name(k) + ".npy"), a)
    return sorted(arrays)


def save_params(executor=None, dirname="", main_program=None, filename=None,
                scope=None):
    return save_vars(executor, dirname, main_program, predicate=_is_param,
                     filename=filename, scope=scope)


def save_persistables(executor=None, dirname="", main_program=None,
                      filename=None, scope=None):
    return save_vars(executor, dirname, main_program,
                     predicate=_is_persistable, filename=filename, scope=scope)


def load_vars(executor: Executor | None = None, dirname: str = "",
              main_program: Program | None = None, vars=None,
              predicate: Callable | None = None, filename: str | None = None,
              scope: Scope | None = None):
    """Load vars saved by save_vars into the scope (io.py:566)."""
    if not dirname:
        raise ValueError("load_vars requires a source dirname")
    program = main_program or default_main_program()
    scope = scope or global_scope()
    selected = _select_vars(program, vars, predicate)
    if filename is not None:
        path = os.path.join(dirname, filename)
        if not os.path.exists(path) and os.path.exists(path + ".npz"):
            path = path + ".npz"
        packed = np.load(path)
        for v in selected:
            key = _encode_name(v.name)
            if key not in packed:
                raise FileNotFoundError(
                    f"variable '{v.name}' not found in {path}")
            scope.set_var(v.name, packed[key])
    else:
        for v in selected:
            path = os.path.join(dirname, _encode_name(v.name) + ".npy")
            if not os.path.exists(path):
                raise FileNotFoundError(
                    f"no saved file for variable '{v.name}' at {path}")
            scope.set_var(v.name, np.load(path))
    return sorted(v.name for v in selected)


def load_params(executor=None, dirname="", main_program=None, filename=None,
                scope=None):
    return load_vars(executor, dirname, main_program, predicate=_is_param,
                     filename=filename, scope=scope)


def load_persistables(executor=None, dirname="", main_program=None,
                      filename=None, scope=None):
    return load_vars(executor, dirname, main_program,
                     predicate=_is_persistable, filename=filename, scope=scope)


# ---------------------------------------------------------------------------
# Inference model export (prune + serialize)
# ---------------------------------------------------------------------------


def _prune_for_targets(program: Program, feed_names: Sequence[str],
                       target_names: Sequence[str]) -> Program:
    """Keep only ops on the path feeds -> targets (reference prune.cc via
    Program._prune, io.py:1005): reverse reachability over the op list,
    stopping at the feed boundary. Mutates and returns `program` (callers pass
    a private clone)."""
    blk = program.global_block
    feeds = set(feed_names)
    needed = set(target_names) - feeds
    keep_flags = [False] * len(blk.ops)
    for i in range(len(blk.ops) - 1, -1, -1):
        op = blk.ops[i]
        # an op is needed iff it produces a needed var; ops that (re)compute a
        # FED var must go — keeping them would recompute and overwrite the feed
        if any(n in needed for n in op.output_names):
            keep_flags[i] = True
            needed.update(n for n in op.input_names if n and n not in feeds)
    blk.ops = [op for op, keep in zip(blk.ops, keep_flags) if keep]
    # drop vars no longer referenced (params kept only if referenced)
    referenced = set(feed_names) | set(target_names)
    for op in blk.ops:
        referenced.update(n for n in op.input_names if n)
        referenced.update(n for n in op.output_names if n)
    blk.vars = {k: v for k, v in blk.vars.items() if k in referenced}
    return program


def save_inference_model(dirname: str, feeded_var_names: Sequence[str],
                         target_vars, executor: Executor | None = None,
                         main_program: Program | None = None,
                         model_filename: str | None = None,
                         params_filename: str | None = None,
                         scope: Scope | None = None):
    """Prune to the inference subgraph and save program + params (io.py:933)."""
    program = main_program or default_main_program()
    scope = scope or global_scope()
    target_names = [v.name if isinstance(v, Variable) else str(v)
                    for v in target_vars]
    inference = _prune_for_targets(program.clone(for_test=True),
                                   feeded_var_names, target_names)
    os.makedirs(dirname, exist_ok=True)
    desc = inference.to_dict()
    desc["__meta__"] = {"feed_names": list(feeded_var_names),
                        "fetch_names": target_names,
                        "params_filename": params_filename}
    with open(os.path.join(dirname, model_filename or _MODEL_FILENAME), "w") as f:
        json.dump(desc, f)
    # save every referenced param/persistable the pruned program still needs
    needed = {v.name for v in inference.list_vars()
              if _is_param(v) or _is_persistable(v)}
    save_vars(executor, dirname, program,
              vars=[n for n in sorted(needed)
                    if program.global_block.has_var(n)],
              filename=params_filename, scope=scope)
    return target_names


def load_inference_model(dirname: str, executor: Executor | None = None,
                         model_filename: str | None = None,
                         params_filename: str | None = None,
                         scope: Scope | None = None):
    """Returns (program, feed_names, fetch_var_names) (io.py:1113)."""
    scope = scope or global_scope()
    with open(os.path.join(dirname, model_filename or _MODEL_FILENAME)) as f:
        desc = json.load(f)
    meta = desc.pop("__meta__", {})
    program = Program.from_dict(desc)
    params_filename = params_filename or meta.get("params_filename")
    load_vars(executor, dirname, program,
              vars=[v for v in program.list_vars()
                    if _is_param(v) or _is_persistable(v)],
              filename=params_filename, scope=scope)
    return program, meta.get("feed_names", []), meta.get("fetch_names", [])


# ---------------------------------------------------------------------------
# sharded, host-parallel checkpoints (SURVEY §5)
# ---------------------------------------------------------------------------


def save_sharded(executor=None, dirname="", main_program=None, scope=None):
    """Sharded, host-parallel checkpoint via orbax/TensorStore.

    TPU-native replacement for the reference's distributed checkpoint story
    (pserver-side save in the DistributeTranspiler flow,
    python/paddle/fluid/io.py save_persistables + trainer.save_checkpoint):
    every process writes only its addressable shards of each persistable var
    (no gather to host 0 — the single-host gather in save_persistables is
    exactly what SURVEY §5 says does not scale to pods). Arrays keep their
    NamedShardings, so ZeRO-sharded optimizer states and TP-sharded params
    round-trip without ever materializing on one host.

    Atomic: the checkpoint is written into a sibling temp directory and
    renamed over `dirname` only after the orbax commit finishes — an
    interrupted or failed save leaves at worst a `.tmp-*` orphan, never a
    half-written tree under the target name. Transient I/O failures retry
    under the resilience io_policy (`ckpt.write` fault site).
    """
    import orbax.checkpoint as ocp

    from .executor import global_scope
    from .framework import default_main_program
    from .resilience.faults import fault_point
    from .resilience.retry import io_policy

    program = main_program or default_main_program()
    scope = scope or global_scope()
    tree = {}
    for v in program.list_vars():
        if not (_is_param(v) or _is_persistable(v)):
            continue
        val = scope.find_var(v.name)
        if val is not None:
            tree[_encode_name(v.name)] = val
    import jax

    path = os.path.abspath(dirname)
    # the stage name must be IDENTICAL across processes (orbax coordinates
    # the multi-host write against one directory), so no pid in it; only
    # process 0 performs the commit rename after the write barrier
    tmp = f"{path}.tmp-stage"
    primary = jax.process_index() == 0
    ckptr = ocp.StandardCheckpointer()
    try:
        def _write():
            fault_point("ckpt.write")
            ckptr.save(tmp, tree, force=True)
            ckptr.wait_until_finished()

        io_policy().call(_write)
        if primary:
            # swap into place; keep the previous checkpoint aside until the
            # new one is committed so a crash mid-swap still leaves a
            # loadable copy
            old = f"{path}.old"
            shutil.rmtree(old, ignore_errors=True)
            if os.path.exists(path):
                os.replace(path, old)
            os.replace(tmp, path)
            shutil.rmtree(old, ignore_errors=True)
    finally:
        ckptr.close()
        if primary:
            shutil.rmtree(tmp, ignore_errors=True)


def load_sharded(executor=None, dirname="", main_program=None, scope=None,
                 shardings=None):
    """Restore a save_sharded checkpoint.

    shardings: optional {var name: jax.sharding.Sharding} to place restored
    arrays directly onto a (possibly different) mesh — the resharding-on-load
    path; defaults to the sharding/type of the value currently in the scope,
    or host numpy when the scope has none.
    """
    import jax
    import orbax.checkpoint as ocp

    from .executor import global_scope
    from .framework import default_main_program

    program = main_program or default_main_program()
    scope = scope or global_scope()
    names = [v.name for v in program.list_vars()
             if _is_param(v) or _is_persistable(v)]
    # restore only what the checkpoint actually holds: a program may have
    # grown new persistables (EMA shadows, slow weights) since the save, and
    # orbax's restore raises on tree mismatches
    path = os.path.abspath(dirname)
    ckptr = ocp.StandardCheckpointer()
    try:
        # restore targets must match the on-disk tree exactly, so read the
        # saved key set from the checkpoint metadata (a dict of per-array
        # metadata on current orbax, an object with .item_metadata on older
        # releases); a layout whose metadata can't be read falls back to the
        # full program tree (which still restores when the trees happen to
        # match)
        try:
            md = ckptr.metadata(path)
            items = getattr(md, "item_metadata", md)
            saved_keys = set(items.keys())
            names = [n for n in names if _encode_name(n) in saved_keys]
        except (AttributeError, TypeError, ValueError, KeyError,
                FileNotFoundError):
            pass
        # abstract restore targets: shape/dtype from the program, placement
        # from `shardings` / current scope values
        target = {}
        for n in names:
            enc = _encode_name(n)
            cur = scope.find_var(n)
            if shardings and n in shardings:
                var = program.global_block.var(n)
                target[enc] = jax.ShapeDtypeStruct(
                    tuple(var.shape), var.np_dtype, sharding=shardings[n])
            elif cur is not None and hasattr(cur, "sharding"):
                target[enc] = jax.ShapeDtypeStruct(
                    tuple(cur.shape), cur.dtype, sharding=cur.sharding)
            else:
                var = program.global_block.var(n)
                target[enc] = jax.ShapeDtypeStruct(tuple(var.shape),
                                                   var.np_dtype)
        from .resilience.retry import io_policy

        restored = io_policy().call(ckptr.restore, path, target)
    finally:
        ckptr.close()
    for n in names:
        enc = _encode_name(n)
        if enc in restored:
            scope.set_var(n, restored[enc])


# ---------------------------------------------------------------------------
# full train-model save/load (the native standalone trainer's input format)
# ---------------------------------------------------------------------------


def save_train_model(dirname: str, feed_order, loss, executor=None,
                     main_program=None, startup_program=None, scope=None):
    """Persist the FULL training state: main + startup programs (with
    backward and optimizer ops), every persistable value, and a meta file
    naming the feeds/loss. This is what the native standalone trainer
    (native/standalone_trainer.c) consumes — the reference's role of the
    saved ProgramDesc that train/demo_trainer.cc loads."""
    from .framework import default_startup_program

    program = main_program or default_main_program()
    startup = startup_program or default_startup_program()
    scope = scope or global_scope()
    os.makedirs(dirname, exist_ok=True)
    with open(os.path.join(dirname, "train_main.json"), "w") as f:
        json.dump(program.to_dict(), f)
    with open(os.path.join(dirname, "train_startup.json"), "w") as f:
        json.dump(startup.to_dict(), f)
    meta = {
        "feed_names": [v.name if isinstance(v, Variable) else str(v)
                       for v in feed_order],
        "loss_name": loss.name if isinstance(loss, Variable) else str(loss),
    }
    with open(os.path.join(dirname, "train_meta.json"), "w") as f:
        json.dump(meta, f)
    save_persistables(executor, dirname, program, scope=scope)
    return meta


def load_train_model(dirname: str, executor=None, scope=None):
    """Inverse of save_train_model: returns (main, startup, meta) with
    persistables loaded into the scope."""
    scope = scope or global_scope()
    with open(os.path.join(dirname, "train_main.json")) as f:
        program = Program.from_dict(json.load(f))
    with open(os.path.join(dirname, "train_startup.json")) as f:
        startup = Program.from_dict(json.load(f))
    with open(os.path.join(dirname, "train_meta.json")) as f:
        meta = json.load(f)
    load_persistables(executor, dirname, program, scope=scope)
    return program, startup, meta
