"""Paged KV-cache manager: fixed-size pages over a preallocated HBM pool.

The serving problem this solves (ROADMAP item 1 / "Ragged Paged Attention",
arXiv:2604.15464): a max-seq-len KV buffer per request wastes
(max_len - actual_len) slots of HBM per request, which is what actually caps
concurrent requests — not compute. Instead:

  * the DEVICE side is one preallocated pool per layer,
    [num_pages, page_size, num_heads, head_dim] for K and V each, living in
    the serving scope as persistable vars the compiled prefill/decode steps
    read AND write (the executor donates the buffers, so every append is an
    in-place HBM scatter, never a reallocation);
  * the HOST side (this module) is pure bookkeeping: a free-list of page
    ids and a per-request page table (list of page ids). allocate/free are
    O(pages moved); nothing here touches the device.

Admission control is the caller's job (engine.py): `can_allocate` is the
backpressure predicate — when the free list runs dry, new requests queue
instead of OOMing the pool, and mid-decode growth preempts rather than
corrupts.
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["PagedKVPool", "pool_var_names", "create_device_pools",
           "declare_pool_vars"]


def pool_var_names(num_layers: int) -> list[tuple[str, str]]:
    """The (K, V) pool var names per layer — the one spelling shared by the
    program builders (model.py), the scope initializer, and tests."""
    return [(f"kv_cache.k{i}", f"kv_cache.v{i}") for i in range(num_layers)]


def declare_pool_vars(block, num_layers: int, num_pages: int, page_size: int,
                      num_heads: int, head_dim: int, dtype: str = "float32"):
    """Declare the pool vars in a program block (both the prefill and the
    decode program must see them so the executor's def-use analysis
    classifies them read-write and donates their buffers)."""
    for kn, vn in pool_var_names(num_layers):
        for name in (kn, vn):
            block.create_var(name=name,
                             shape=[num_pages, page_size, num_heads, head_dim],
                             dtype=dtype, persistable=True,
                             stop_gradient=True)


def create_device_pools(scope, num_layers: int, num_pages: int,
                        page_size: int, num_heads: int, head_dim: int,
                        dtype: str = "float32") -> None:
    """Preallocate the zeroed device pools into `scope` (once, at engine
    construction — this is the only allocation the cache ever does)."""
    for kn, vn in pool_var_names(num_layers):
        for name in (kn, vn):
            scope.set_var(name, jnp.zeros(
                (num_pages, page_size, num_heads, head_dim),
                jnp.dtype(dtype)))


class PagedKVPool:
    """Free-list allocator over `num_pages` page ids.

    Deliberately not thread-safe: the continuous-batching engine owns it
    from one scheduler thread (the compiled steps carry the parallelism).
    """

    def __init__(self, num_pages: int, page_size: int):
        if num_pages <= 0 or page_size <= 0:
            raise ValueError(
                f"pool needs positive pages/page_size, got {num_pages}/"
                f"{page_size} (FLAGS_serving_pool_pages / "
                f"FLAGS_serving_page_size)")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        # LIFO free list: recently-freed pages are re-used first, keeping
        # the pool's hot working set small
        self._free: list[int] = list(range(self.num_pages - 1, -1, -1))

    # -- sizing ---------------------------------------------------------------
    def pages_for(self, n_tokens: int) -> int:
        """Pages a context of `n_tokens` slots needs (ceil)."""
        return max(1, -(-int(n_tokens) // self.page_size))

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.num_pages - len(self._free)

    def occupancy(self) -> float:
        return self.pages_in_use / self.num_pages

    # -- allocation -----------------------------------------------------------
    def can_allocate(self, n: int) -> bool:
        return n <= len(self._free)

    def allocate(self, n: int) -> list[int] | None:
        """Pop `n` page ids, or None (backpressure — never a partial grab,
        so a failed admission leaves the pool exactly as it found it)."""
        if n > len(self._free):
            return None
        got = self._free[-n:]
        del self._free[-n:]
        return got

    def free(self, pages: list[int]) -> None:
        for p in pages:
            if not (0 <= p < self.num_pages):
                raise ValueError(f"freeing page {p} outside pool "
                                 f"[0, {self.num_pages})")
            if p in self._free:
                raise ValueError(f"double-free of page {p}")
        self._free.extend(pages)
