"""Paged KV-cache manager: fixed-size pages over a preallocated HBM pool.

The serving problem this solves (ROADMAP item 1 / "Ragged Paged Attention",
arXiv:2604.15464): a max-seq-len KV buffer per request wastes
(max_len - actual_len) slots of HBM per request, which is what actually caps
concurrent requests — not compute. Instead:

  * the DEVICE side is one preallocated pool per layer,
    [num_pages, page_size, num_heads, head_dim] for K and V each, living in
    the serving scope as persistable vars the compiled prefill/decode steps
    read AND write (the executor donates the buffers, so every append is an
    in-place HBM scatter, never a reallocation);
  * the HOST side (this module) is pure bookkeeping: a free-list of page
    ids, a PER-PAGE REFCOUNT, and a per-request page table (list of page
    ids). allocate/share/release are O(pages moved); nothing here touches
    the device.

Multi-tenancy (ISSUE 11) rides the refcounts: requests sharing a system
prompt map the SAME physical pages into their page tables (`share` — a
refcount bump, not a copy), and the `PrefixCache` below keeps prompt pages
alive past their request's lifetime so later arrivals reuse them. A page
returns to the free list only when its LAST holder releases it; a holder
that wants to WRITE a shared page must copy-on-write first (engine.py).

Admission control is the caller's job (engine.py): `can_allocate` is the
backpressure predicate — when the free list runs dry, new requests queue
instead of OOMing the pool, and mid-decode growth preempts rather than
corrupts.

Disaggregated serving (ISSUE 19) adds two more pieces of pure bookkeeping:

  * LEASES — a page can be pinned by a named lease (`lease_grant`), the
    in-transit holder class of the prefill->decode KV handoff: the pin
    keeps the pages alive while neither engine's request table maps them,
    `lease_transfer` hands the refcount to the adopting side without a
    release/share round-trip, and `check_consistency` models leases as
    first-class holders so a mid-handoff audit neither false-flags nor
    misses them;
  * `OwnedPoolView` — a per-engine facade over ONE shared pool that
    mirrors the allocator API while keeping the owner's own holder
    ledger. The ledger belongs to the pool layer (what a disaggregated
    memory node tracks per client), so when a replica dies the router
    reclaims its pins through `forfeit()` without ever touching the dead
    engine.
"""
from __future__ import annotations

import heapq

import jax.numpy as jnp

__all__ = ["PagedKVPool", "PrefixCache", "OwnedPoolView", "pool_var_names",
           "create_device_pools", "declare_pool_vars"]


def pool_var_names(num_layers: int) -> list[tuple[str, str]]:
    """The (K, V) pool var names per layer — the one spelling shared by the
    program builders (model.py), the scope initializer, and tests."""
    return [(f"kv_cache.k{i}", f"kv_cache.v{i}") for i in range(num_layers)]


def declare_pool_vars(block, num_layers: int, num_pages: int, page_size: int,
                      num_heads: int, head_dim: int, dtype: str = "float32"):
    """Declare the pool vars in a program block (both the prefill and the
    decode program must see them so the executor's def-use analysis
    classifies them read-write and donates their buffers). Under TP,
    model.apply_tp_annotations shards their heads dim afterwards."""
    for kn, vn in pool_var_names(num_layers):
        for name in (kn, vn):
            block.create_var(name=name,
                             shape=[num_pages, page_size, num_heads,
                                    head_dim],
                             dtype=dtype, persistable=True,
                             stop_gradient=True)


def create_device_pools(scope, num_layers: int, num_pages: int,
                        page_size: int, num_heads: int, head_dim: int,
                        dtype: str = "float32") -> None:
    """Preallocate the zeroed device pools into `scope` (once, at engine
    construction — this is the only allocation the cache ever does)."""
    for kn, vn in pool_var_names(num_layers):
        for name in (kn, vn):
            scope.set_var(name, jnp.zeros(
                (num_pages, page_size, num_heads, head_dim),
                jnp.dtype(dtype)))


class PagedKVPool:
    """Refcounted free-list allocator over `num_pages` page ids.

    Deliberately not thread-safe: the continuous-batching engine owns it
    from one scheduler thread (the compiled steps carry the parallelism).
    """

    def __init__(self, num_pages: int, page_size: int):
        if num_pages <= 0 or page_size <= 0:
            raise ValueError(
                f"pool needs positive pages/page_size, got {num_pages}/"
                f"{page_size} (FLAGS_serving_pool_pages / "
                f"FLAGS_serving_page_size)")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        # LIFO free list: recently-freed pages are re-used first, keeping
        # the pool's hot working set small
        self._free: list[int] = list(range(self.num_pages - 1, -1, -1))
        self._refs: list[int] = [0] * self.num_pages
        # in-transit holder class (ISSUE 19): lease id -> pinned page table
        self._leases: dict[str, list[int]] = {}

    # -- sizing ---------------------------------------------------------------
    def pages_for(self, n_tokens: int) -> int:
        """Pages a context of `n_tokens` slots needs (ceil)."""
        return max(1, -(-int(n_tokens) // self.page_size))

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def pages_in_use(self) -> int:
        return self.num_pages - len(self._free)

    def occupancy(self) -> float:
        return self.pages_in_use / self.num_pages

    def refcount(self, page: int) -> int:
        return self._refs[page]

    # -- allocation -----------------------------------------------------------
    def can_allocate(self, n: int) -> bool:
        return n <= len(self._free)

    def allocate(self, n: int) -> list[int] | None:
        """Pop `n` page ids at refcount 1, or None (backpressure — never a
        partial grab, so a failed admission leaves the pool exactly as it
        found it)."""
        if n > len(self._free):
            return None
        got = self._free[-n:]
        del self._free[-n:]
        for p in got:
            self._refs[p] = 1
        return got

    def share(self, pages: list[int]) -> None:
        """Add one holder to each page (prefix reuse: a refcount bump, not a
        copy). Only live pages can be shared — sharing a free page would
        resurrect garbage."""
        for p in pages:
            if not (0 <= p < self.num_pages):
                raise ValueError(f"sharing page {p} outside pool "
                                 f"[0, {self.num_pages})")
            if self._refs[p] <= 0:
                raise ValueError(f"sharing free page {p} (refcount 0)")
        for p in pages:
            self._refs[p] += 1

    def release(self, pages: list[int]) -> int:
        """Drop one holder from each page; pages whose refcount hits zero
        return to the free list. Returns how many pages were actually freed.
        Releasing below zero (a double-free) raises BEFORE any mutation."""
        counts: dict[int, int] = {}
        for p in pages:
            if not (0 <= p < self.num_pages):
                raise ValueError(f"freeing page {p} outside pool "
                                 f"[0, {self.num_pages})")
            counts[p] = counts.get(p, 0) + 1
        for p, c in counts.items():
            if c > self._refs[p]:
                raise ValueError(
                    f"double-free of page {p} (releasing {c} holders, "
                    f"refcount {self._refs[p]})")
        freed = 0
        for p in pages:
            self._refs[p] -= 1
            if self._refs[p] == 0:
                self._free.append(p)
                freed += 1
        return freed

    def free(self, pages: list[int]) -> None:
        """Single-holder spelling of `release` (the PR 7 API)."""
        self.release(pages)

    # -- leases: the in-transit holder class (ISSUE 19) -----------------------
    def lease_grant(self, lease_id: str, pages: list[int]) -> None:
        """Pin `pages` under a named lease (one extra holder per page, via
        `share` — only live pages can be leased). The lease is the handoff
        protocol's safety net: it keeps the pages alive even if BOTH the
        granting and the adopting engine die mid-transfer."""
        if lease_id in self._leases:
            raise ValueError(f"lease {lease_id!r} already granted")
        self.share(pages)
        self._leases[lease_id] = list(pages)

    def lease_transfer(self, lease_id: str) -> list[int]:
        """Commit a lease: drop the lease record WITHOUT releasing the
        refcount — ownership of the pin moves to the adopting holder (its
        page table / owner ledger), so the handoff is a pure metadata move
        with no release/share window where the pages could be freed."""
        if lease_id not in self._leases:
            raise KeyError(f"lease {lease_id!r} not held")
        return self._leases.pop(lease_id)

    def lease_release(self, lease_id: str) -> int:
        """Reap a lease: drop the record AND its pin (the orphaned-prepare
        path — commit never arrived). Returns pages actually freed."""
        if lease_id not in self._leases:
            raise KeyError(f"lease {lease_id!r} not held")
        return self.release(self._leases.pop(lease_id))

    def lease_pages(self, lease_id: str) -> list[int]:
        return list(self._leases[lease_id])

    @property
    def leased_page_count(self) -> int:
        return sum(len(p) for p in self._leases.values())

    # -- invariant audit (ISSUE 14) -------------------------------------------
    def check_consistency(self,
                          holders: "dict[int, int] | None" = None
                          ) -> list[str]:
        """Audit the pool invariants; returns the violations found ([] =
        clean). The two invariants every allocate/share/release must
        preserve:

          * the free list and the mapped pages PARTITION the pool: every
            page is either on the free list with refcount 0 or off it with
            refcount > 0, exactly once;
          * with `holders` (page id -> how many live page-table/cache
            entries map it, built by the engine), each page's refcount
            equals its holder count — a phantom holder pins HBM forever, a
            missing one frees a page someone still reads. Leased pages
            (ISSUE 19) count as one holder per lease pin, so a page that is
            mid-handoff — pinned by a lease while no request table maps
            it — audits clean, and a forged lease record (a pin the
            refcount never backed) audits dirty.

        Pure read; the recovery pass runs it before and after a rebuild."""
        problems: list[str] = []
        free_set = set(self._free)
        lease_holds: dict[int, int] = {}
        for lid, pages in self._leases.items():
            for p in pages:
                if not (0 <= p < self.num_pages):
                    problems.append(f"lease {lid!r} pins page {p} outside "
                                    f"the pool [0, {self.num_pages})")
                    continue
                lease_holds[p] = lease_holds.get(p, 0) + 1
        for p, c in sorted(lease_holds.items()):
            if self._refs[p] < c:
                problems.append(
                    f"page {p} carries {c} lease pins but refcount "
                    f"{self._refs[p]} (forged or duplicate lease)")
        if len(free_set) != len(self._free):
            dupes = sorted({p for p in self._free if self._free.count(p) > 1})
            problems.append(f"free list holds duplicate entries {dupes[:8]}")
        for p in sorted(free_set):
            if not (0 <= p < self.num_pages):
                problems.append(f"free list holds page {p} outside the pool "
                                f"[0, {self.num_pages})")
            elif self._refs[p] != 0:
                problems.append(f"page {p} is on the free list with "
                                f"refcount {self._refs[p]}")
        for p in range(self.num_pages):
            r = self._refs[p]
            if r < 0:
                problems.append(f"page {p} has negative refcount {r}")
            elif r == 0 and p not in free_set:
                problems.append(f"page {p} has refcount 0 but is missing "
                                f"from the free list")
        if holders is not None:
            for p in range(self.num_pages):
                h = holders.get(p, 0) + lease_holds.get(p, 0)
                if self._refs[p] > 0 and self._refs[p] != h:
                    leased = lease_holds.get(p, 0)
                    suffix = f" (of which {leased} leased)" if leased else ""
                    problems.append(f"page {p} refcount {self._refs[p]} != "
                                    f"{h} live holders{suffix}")
                elif self._refs[p] == 0 and h:
                    problems.append(f"page {p} is free but {h} live holders "
                                    f"still map it")
        return problems

    def reset(self) -> None:
        """Rebuild the pristine state: every page free at refcount 0 — the
        recovery pass's pool rebuild. The caller must drop every page table
        and prefix-cache entry FIRST (their page ids are garbage after
        this); the device pools need no touch, replayed prefills overwrite
        them."""
        self._free = list(range(self.num_pages - 1, -1, -1))
        self._refs = [0] * self.num_pages
        self._leases = {}


class OwnedPoolView:
    """Per-engine facade over ONE shared `PagedKVPool` (disaggregated
    serving, ISSUE 19).

    Mirrors the allocator surface the engine and its PrefixCache use
    (allocate/share/release/free/refcount/can_allocate/pages_for), while
    keeping an OWNER LEDGER: how many holders this owner has on each page.
    The ledger buys three things a raw shared pool cannot give:

      * a per-engine audit (`check_consistency`) scoped to the engine's
        own holdings — another engine's pages are not "phantom holders";
      * per-engine leak accounting (`owned_pages_in_use`) while occupancy
        and backpressure still read the honest GLOBAL pool pressure;
      * dead-replica reclamation (`forfeit`) — the ledger is pool-layer
        state (what a disaggregated memory node tracks per client), so
        the router can return a SIGKILLed replica's pins to the free list
        without ever touching the dead engine.

    `adopt_transferred` records pins whose refcount arrived by
    `PagedKVPool.lease_transfer` — the commit half of the KV handoff.
    Not thread-safe, like the pool underneath: disaggregated fleets run
    the inline pump (one scheduler thread owns the shared pool).
    """

    def __init__(self, pool: PagedKVPool, owner: str):
        self.pool = pool
        self.owner = str(owner)
        self._held: dict[int, int] = {}

    # -- delegated sizing/pressure (GLOBAL: backpressure must be honest) ----
    @property
    def num_pages(self) -> int:
        return self.pool.num_pages

    @property
    def page_size(self) -> int:
        return self.pool.page_size

    @property
    def free_count(self) -> int:
        return self.pool.free_count

    @property
    def pages_in_use(self) -> int:
        return self.pool.pages_in_use

    @property
    def owned_pages_in_use(self) -> int:
        """Distinct pages this owner holds (the per-engine leak base)."""
        return len(self._held)

    # the serving_pool_corrupt chaos payload vandalizes these directly
    @property
    def _refs(self):
        return self.pool._refs

    @property
    def _free(self):
        return self.pool._free

    def occupancy(self) -> float:
        return self.pool.occupancy()

    def pages_for(self, n_tokens: int) -> int:
        return self.pool.pages_for(n_tokens)

    def refcount(self, page: int) -> int:
        return self.pool.refcount(page)

    def can_allocate(self, n: int) -> bool:
        return self.pool.can_allocate(n)

    # -- ledgered mutations --------------------------------------------------
    def _note(self, pages, d: int) -> None:
        for p in pages:
            c = self._held.get(p, 0) + d
            if c > 0:
                self._held[p] = c
            else:
                self._held.pop(p, None)

    def allocate(self, n: int) -> list[int] | None:
        got = self.pool.allocate(n)
        if got is not None:
            self._note(got, +1)
        return got

    def share(self, pages: list[int]) -> None:
        self.pool.share(pages)
        self._note(pages, +1)

    def release(self, pages: list[int]) -> int:
        freed = self.pool.release(pages)
        self._note(pages, -1)
        return freed

    def free(self, pages: list[int]) -> None:
        self.release(pages)

    def adopt_transferred(self, pages: list[int]) -> None:
        """Record pins whose refcount was moved here by `lease_transfer`
        (handoff commit): ledger only — the pool refcount already counts
        them, bumping it again would pin the pages forever."""
        for p in pages:
            if self.pool.refcount(p) <= 0:
                raise ValueError(f"adopting free page {p} (refcount 0)")
        self._note(pages, +1)

    def forfeit(self) -> int:
        """Return EVERY pin this owner holds to the shared pool (the owner
        died — its requests, admission pins, and prefix-cache refs will
        never release themselves). Lease pins are the HandoffManager's,
        not the owner's, so in-transit pages survive the forfeit. Returns
        pages actually freed."""
        freed = 0
        for p, c in list(self._held.items()):
            freed += self.pool.release([p] * c)
        self._held.clear()
        return freed

    def reset(self) -> None:
        """The engine recovery pass's pool rebuild, owner-scoped: drop this
        owner's pins only — resetting the SHARED pool underneath would
        vandalize every other engine's live state."""
        self.forfeit()

    # -- owner-scoped audit --------------------------------------------------
    def check_consistency(self,
                          holders: "dict[int, int] | None" = None
                          ) -> list[str]:
        """Global partition + lease invariants from the shared pool, plus
        the owner-scoped holder check: `holders` (built by THIS engine)
        must equal the owner ledger exactly, and the ledger can never
        exceed the global refcount."""
        problems = list(self.pool.check_consistency(None))
        if holders is not None:
            for p, c in sorted(self._held.items()):
                h = holders.get(p, 0)
                if h != c:
                    problems.append(
                        f"[{self.owner}] page {p}: owner ledger holds {c} "
                        f"but {h} live holders map it")
                if self.pool.refcount(p) < c:
                    problems.append(
                        f"[{self.owner}] page {p}: owner ledger holds {c} "
                        f"exceeding pool refcount {self.pool.refcount(p)}")
            for p, h in sorted(holders.items()):
                if h and p not in self._held:
                    problems.append(
                        f"[{self.owner}] page {p} mapped by {h} live "
                        f"holders but absent from the owner ledger")
        return problems


class _PrefixNode:
    __slots__ = ("nid", "page", "key", "parent_id", "children", "last_use")

    def __init__(self, nid, page, key, parent_id):
        self.nid = nid
        self.page = page
        self.key = key              # (parent_id, token_block) — exact match
        self.parent_id = parent_id
        self.children = 0
        self.last_use = 0


class PrefixCache:
    """Prefix index keyed on token-prefix hashes at PAGE granularity.

    A trie over full token blocks: node (parent, tuple_of_page_size_tokens)
    -> physical page id holding exactly that block's KV. The cache itself
    holds one refcount on every indexed page, so prompt pages survive their
    request and later requests with the same system prompt map them with a
    `share` instead of re-prefilling (the copy-on-write discipline in
    engine.py keeps them immutable). Keys are EXACT token tuples chained
    through parent ids — a hash collision can therefore never map the wrong
    page (correctness does not ride Python's hash).

    Eviction is LRU over leaf nodes whose page nobody else holds
    (refcount 1 == the cache's own ref): evicting a shared page would free
    no HBM anyway, and an interior node can't go before its children or the
    chain below it would dangle. The LRU order lives in a lazy min-heap of
    (last_use, nid) stamps — every touch pushes a fresh stamp, pops discard
    stale ones — so `evict(need)` is O((popped + need) log n) instead of a
    full O(nodes) scan per freed page (a scheduler-thread stall at exactly
    the pool-pressure moments eviction runs).
    """

    def __init__(self, pool: PagedKVPool):
        self.pool = pool
        self.page_size = pool.page_size
        self._nodes: dict[tuple, _PrefixNode] = {}
        self._by_id: dict[int, _PrefixNode] = {}
        self._heap: list[tuple[int, int]] = []   # (last_use, nid), lazy
        self._next_id = 1
        self._clock = 0
        self.lookups = 0
        self.hit_pages = 0
        self.inserted_pages = 0
        self.evicted_pages = 0

    def _tick(self) -> int:
        self._clock += 1
        return self._clock

    def _touch(self, node: _PrefixNode) -> None:
        node.last_use = self._tick()
        heapq.heappush(self._heap, (node.last_use, node.nid))

    @property
    def pages_held(self) -> int:
        return len(self._nodes)

    def match(self, tokens) -> list[int]:
        """Longest chain of cached pages covering a prefix of `tokens`
        (full blocks only). Bumps LRU stamps on the path."""
        self.lookups += 1
        pages: list[int] = []
        pid = 0
        for i in range(len(tokens) // self.page_size):
            block = tuple(int(t) for t in
                          tokens[i * self.page_size:(i + 1) * self.page_size])
            node = self._nodes.get((pid, block))
            if node is None:
                break
            self._touch(node)
            pages.append(node.page)
            pid = node.nid
        self.hit_pages += len(pages)
        return pages

    def insert(self, tokens, pages: list[int]) -> int:
        """Index `tokens`' full blocks onto `pages` (pages[i] must hold
        block i's KV, already written). New nodes take a cache refcount via
        pool.share; blocks already indexed are left on their existing page
        (first writer wins — both copies hold identical KV). Returns the
        number of pages newly indexed."""
        pid = 0
        added = 0
        for i in range(len(tokens) // self.page_size):
            block = tuple(int(t) for t in
                          tokens[i * self.page_size:(i + 1) * self.page_size])
            key = (pid, block)
            node = self._nodes.get(key)
            if node is None:
                self.pool.share([pages[i]])
                node = _PrefixNode(self._next_id, pages[i], key, pid)
                self._next_id += 1
                self._nodes[key] = node
                self._by_id[node.nid] = node
                if pid:
                    self._by_id[pid].children += 1
                added += 1
                self.inserted_pages += 1
            self._touch(node)
            pid = node.nid
        return added

    def evict(self, need: int) -> int:
        """Release up to `need` pages back to the free list, LRU-first over
        evictable leaves. Returns pages actually freed (may be < need when
        every remaining page is still mapped by a live request).

        Pops the stamp heap: stale stamps (node gone, or re-touched since)
        are discarded; stamps of nodes that are currently NOT evictable
        (interior, or a live request still maps the page) are set aside and
        reinserted afterwards, so a node that becomes evictable later —
        its request released the page, or its children were dropped — is
        still reachable through its standing stamp."""
        freed = 0
        skipped: list[tuple[int, int]] = []
        while freed < need and self._heap:
            stamp, nid = heapq.heappop(self._heap)
            node = self._by_id.get(nid)
            if node is None or node.last_use != stamp:
                continue                     # stale: dropped or re-touched
            if node.children or self.pool.refcount(node.page) != 1:
                skipped.append((stamp, nid))
                continue
            self._drop(node)
            freed += 1
        for entry in skipped:
            heapq.heappush(self._heap, entry)
        return freed

    def _drop(self, node: _PrefixNode) -> None:
        del self._nodes[node.key]
        del self._by_id[node.nid]
        if node.parent_id:
            parent = self._by_id[node.parent_id]
            parent.children -= 1
            if parent.children == 0:
                # the parent just became a leaf: restore its stamp so the
                # SAME evict pass can cascade up the chain (its original
                # stamp may sit in `skipped` until the pass ends)
                heapq.heappush(self._heap, (parent.last_use, parent.nid))
        self.pool.release([node.page])
        self.evicted_pages += 1

    def clear(self) -> int:
        """Drop the WHOLE index without releasing any page (recovery path:
        the pool underneath is about to be rebuilt, so the cache's
        refcounts no longer mean anything — releasing them would double-
        mutate state the rebuild resets anyway). Returns entries dropped.
        Use `flush` everywhere else."""
        n = len(self._nodes)
        self._nodes.clear()
        self._by_id.clear()
        self._heap.clear()
        return n

    def flush(self) -> int:
        """Evict every evictable entry (end-of-run accounting / tests):
        afterwards the only indexed pages left are ones a live request
        still maps."""
        total = 0
        while True:
            freed = self.evict(len(self._nodes) or 1)
            total += freed
            if freed == 0:
                return total
