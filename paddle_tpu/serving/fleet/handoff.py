"""Transactional KV handoff for disaggregated prefill/decode serving.

ROADMAP item 2(a): the per-request page tables + refcounts make the
prefill->decode transfer a TABLE move, not a copy — the windowed/decode
programs already read pooled context, so a decode engine can adopt foreign
pages the moment it learns their ids. The hard part is surviving a crash on
either side of the move without leaking a page, double-freeing one, or
changing one output token. This module is that protocol:

    PREPARE   the prefill replica finishes a prompt, extracts the request
              from its engine (`ServingEngine.extract_for_handoff` — the
              request's own pages stay held: the PREFILL PIN), and
              publishes the transfer state under a TTL'd lease
              (`HandoffManager.prepare` -> `PagedKVPool.lease_grant`,
              one more pin per page). Two pins now guard the pages; the
              lease pin lives in the SHARED pool, so it survives the
              prefill host's death.

    COMMIT    the decode replica adopts (`commit` -> `lease_transfer`):
              the lease's refcount moves to the adopting engine's owner
              ledger with no release/share window, and the engine resumes
              decoding mid-request (`adopt_request`). Only AFTER the
              commit does the router tell the prefill side to drop its
              pin (`release_handoff`). Double commits and commits that
              lose the expiry race are rejected atomically — never a
              half-adopted table.

    REAP      `reap_expired` reclaims orphaned prepares: a lease whose
              commit never arrived (dropped handoff, dead decode inbox)
              releases its pin at TTL and the router replays the prompt
              under the ordinary fleet_policy failover budget. A reaped
              lease can never be committed afterwards (commit-after-reap
              rejects, the replay wins).

Every transition is audit-visible: leases are a first-class holder class in
`PagedKVPool.check_consistency`, so a mid-handoff page (pinned, mapped by
no table) audits clean and a forged lease audits dirty.

`disagg_fleet_factory` builds the role-split topology: ONE shared
`PagedKVPool` + ONE shared device scope (weights and KV pools), engines
wrapped in per-owner `OwnedPoolView`s, prefill engines in `prefill_only`
mode and decode engines without a prefix cache (they never prefill).

Knobs: FLAGS_disagg_lease_ttl_s (x FLAGS_watchdog_scale),
FLAGS_disagg_prefill_replicas. Metrics: fleet.lease.* / fleet.handoff.*.
"""
from __future__ import annotations

import itertools
import threading
import time

from ... import observability as obs
from ...resilience.faults import InjectedFault, fault_point
from ...resilience.watchdog import watchdog_scale
from ..kv_cache import PagedKVPool

__all__ = ["HandoffManager", "KVLease", "HandoffError", "LeaseExpired",
           "PREPARED", "COMMITTED", "REAPED", "disagg_fleet_factory"]

PREPARED, COMMITTED, REAPED = "prepared", "committed", "reaped"


class HandoffError(RuntimeError):
    """A commit that cannot proceed: unknown lease, double commit, or a
    draining/dead adopter bouncing the job. The router replays the prompt
    under the fleet failover budget."""


class LeaseExpired(HandoffError):
    """The commit lost the race against the reaper's clock (or arrived
    after the reap): the pin is reclaimed exactly once, on this side of
    the rejection, and the replay owns the request from here."""


class KVLease:
    """One in-transit request: the published transfer state plus the lease
    lifecycle. `payload` is ServingEngine.extract_for_handoff's dict (token
    history, page table, sampling, deadline); `pages` is the pinned table
    the pool tracks under `lease_id`."""

    __slots__ = ("lease_id", "fid", "payload", "state", "t_prepare",
                 "expiry")

    def __init__(self, lease_id: str, fid: int, payload: dict,
                 expiry: float):
        self.lease_id = lease_id
        self.fid = fid
        self.payload = payload
        self.state = PREPARED
        self.t_prepare = time.perf_counter()
        self.expiry = expiry

    @property
    def pages(self) -> list[int]:
        return list(self.payload["pages"])


class HandoffManager:
    """The lease table over ONE shared `PagedKVPool`.

    Thread-safe (threaded pumps prepare/commit concurrently), but the pool
    mutations ride the caller's pump thread — disaggregated fleets run the
    inline pump so the shared pool keeps its single-writer discipline.
    `clock` is injectable for deterministic reaper tests; production uses
    time.monotonic. The TTL is FLAGS_disagg_lease_ttl_s widened by
    FLAGS_watchdog_scale (slow CI must not reap healthy handoffs).
    """

    def __init__(self, pool: PagedKVPool, ttl_s: float | None = None,
                 clock=time.monotonic):
        from ... import flags

        self.pool = pool
        self.ttl_s = float(flags.get_flag("disagg_lease_ttl_s")
                           if ttl_s is None else ttl_s) * watchdog_scale()
        self._clock = clock
        self._lock = threading.Lock()
        self.leases: dict[str, KVLease] = {}
        self._latest: dict[int, str | None] = {}  # fid -> newest lease id
        self._next = 0
        self.stats = {"granted": 0, "committed": 0, "reaped": 0,
                      "expired_at_commit": 0, "commit_failed": 0}

    # -- lifecycle -----------------------------------------------------------
    def prepare(self, fid: int, payload: dict) -> str:
        """Publish one request under a fresh TTL'd lease; pins the page
        table in the shared pool. Returns the lease id."""
        with self._lock:
            lid = f"lease-{self._next}"
            self._next += 1
            self.pool.lease_grant(lid, payload["pages"])
            self.leases[lid] = KVLease(lid, fid, payload,
                                       self._clock() + self.ttl_s)
            self._latest[fid] = lid
            self._count("lease.granted")
            self._gauges_locked()
        obs.event("fleet.handoff", {"lease": lid, "fid": fid,
                                    "phase": PREPARED,
                                    "pages": len(payload["pages"])})
        return lid

    def commit(self, lease_id: str) -> KVLease:
        """Adopt a PREPARED lease: its pin's refcount transfers to the
        caller (who must record it via OwnedPoolView.adopt_transferred —
        ServingEngine.adopt_request does). Raises HandoffError on unknown/
        double commits and LeaseExpired when the reaper's clock won."""
        with self._lock:
            lease = self.leases.get(lease_id)
            if lease is None:
                self._count("handoff.commit_failed")
                raise HandoffError(f"commit of unknown lease {lease_id!r}")
            if lease.state == COMMITTED:
                self._count("handoff.commit_failed")
                raise HandoffError(f"double commit of lease {lease_id!r}")
            if lease.state == REAPED:
                self._count("handoff.commit_failed")
                raise LeaseExpired(
                    f"commit after reap of lease {lease_id!r}")
            try:
                # chaos: the reaper's clock wins the expiry race exactly
                # as the commit arrives
                fault_point("disagg_lease_expire_race")
            except InjectedFault:
                lease.expiry = float("-inf")
            if self._clock() > lease.expiry:
                self._reap_locked(lease)
                self._count("lease.expired_at_commit", "expired_at_commit")
                self._count("handoff.commit_failed")
                raise LeaseExpired(
                    f"lease {lease_id!r} expired before commit "
                    f"(ttl {self.ttl_s:.3f}s)")
            lease.state = COMMITTED
            self.pool.lease_transfer(lease_id)
            self._count("handoff.committed", "committed")
            self._gauges_locked()
        obs.histogram_observe("fleet.handoff.s",
                              time.perf_counter() - lease.t_prepare)
        obs.event("fleet.handoff", {"lease": lease_id, "fid": lease.fid,
                                    "phase": COMMITTED})
        return lease

    def reap_expired(self) -> list[KVLease]:
        """Reclaim every PREPARED lease past its TTL (pin released, state
        REAPED). The router calls this each poll and replays the reaped
        fids; `is_current` filters superseded leases so an old orphan
        never triggers a spurious replay of a request that moved on."""
        now = self._clock()
        reaped = []
        with self._lock:
            for lease in list(self.leases.values()):
                if lease.state == PREPARED and now > lease.expiry:
                    self._reap_locked(lease)
                    reaped.append(lease)
            if reaped:
                self._gauges_locked()
        for lease in reaped:
            obs.event("fleet.handoff",
                      {"lease": lease.lease_id, "fid": lease.fid,
                       "phase": REAPED, "pages": len(lease.pages)},
                      level="warning")
        return reaped

    def abandon(self, lease_id: str) -> bool:
        """Reap one lease NOW regardless of TTL (the router learned it is
        an orphan: the request already failed over elsewhere, or the
        adopter bounced the commit). No-op on committed/reaped leases."""
        with self._lock:
            lease = self.leases.get(lease_id)
            if lease is None or lease.state != PREPARED:
                return False
            self._reap_locked(lease)
            self._gauges_locked()
        obs.event("fleet.handoff", {"lease": lease_id, "fid": lease.fid,
                                    "phase": "abandoned"}, level="warning")
        return True

    def supersede(self, fid: int) -> None:
        """Mark any outstanding lease for `fid` as no longer current (the
        router is replaying the prompt from scratch): the lease still
        reaps at TTL to reclaim its pin, but its reap must not trigger a
        second replay."""
        with self._lock:
            self._latest[fid] = None

    def is_current(self, lease: KVLease) -> bool:
        with self._lock:
            return self._latest.get(lease.fid) == lease.lease_id

    def active(self) -> int:
        with self._lock:
            return sum(1 for l in self.leases.values()
                       if l.state == PREPARED)

    # -- internals -----------------------------------------------------------
    def _reap_locked(self, lease: KVLease) -> None:
        lease.state = REAPED
        self.pool.lease_release(lease.lease_id)
        self._count("lease.reaped", "reaped")

    def _count(self, metric: str, key: str | None = None) -> None:
        obs.counter_inc("fleet." + metric)
        k = key if key is not None else metric.split(".", 1)[1]
        if k in self.stats:
            self.stats[k] += 1

    def _gauges_locked(self) -> None:
        obs.gauge_set("fleet.lease.active",
                      sum(1 for l in self.leases.values()
                          if l.state == PREPARED))
        obs.gauge_set("fleet.lease.pinned_pages", self.pool.leased_page_count)


def disagg_fleet_factory(cfg=None, **engine_kw):
    """Build the role-split engine factory: every engine it returns shares
    ONE `PagedKVPool` (each behind its own `OwnedPoolView`) and ONE device
    scope — identical seeds make the per-engine weight inits bitwise
    no-ops, and the shared KV pools are what makes the handoff a table
    move. `factory(role)` builds a "prefill" engine (prefill_only, keeps
    the prefix cache: shared-prefix absorption happens at the prefill
    stage), a "decode" engine (no prefix cache — it never prefills), or a
    "mixed" co-located engine over the same shared pool.

    The shared pool is exposed as `factory.shared_pool` (the router builds
    its HandoffManager over it). Engine kwargs pass through; `pool_pages`,
    `page_size` and `seed` apply to every role.
    """
    from ...executor import Scope
    from ..engine import ServingEngine

    base_kw = dict(engine_kw)
    pool_pages = base_kw.pop("pool_pages", None)
    page_size = base_kw.pop("page_size", None)
    if pool_pages is None or page_size is None:
        from ... import flags

        pool_pages = pool_pages or flags.get_flag("serving_pool_pages")
        page_size = page_size or flags.get_flag("serving_page_size")
    shared_pool = PagedKVPool(int(pool_pages), int(page_size))
    shared_scope = Scope()
    seq = itertools.count()

    def factory(role: str = "mixed") -> ServingEngine:
        kw = dict(base_kw)
        if role == "prefill":
            kw["prefill_only"] = True
            kw["draft_k"] = 0  # the prefill stage never decodes
        elif role == "decode":
            kw["prefix_cache"] = False
        return ServingEngine(cfg, page_size=page_size,
                             pool_pages=pool_pages,
                             shared_pool=shared_pool,
                             shared_scope=shared_scope,
                             pool_owner=f"{role}{next(seq)}", **kw)

    factory.shared_pool = shared_pool
    factory.shared_scope = shared_scope
    return factory
