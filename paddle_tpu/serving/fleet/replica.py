"""One engine replica as a failure domain.

`EngineReplica` owns one `ServingEngine` (its own KV pool, prefix cache,
compile caches) plus the thin shell the router needs around it: a
thread-safe inbox of placement jobs, an outbox of streamed results, a
heartbeat, and a lifecycle. EVERYTHING that touches the engine happens
inside `pump_once()` — the engine stays single-threaded by construction,
whether the pump runs inline on the router's thread or on the replica's
own worker thread (`FleetRouter(pump="threads")`).

Lifecycle (the three-state contract of ISSUE 16, plus the clean exit):

    HEALTHY  --drain()-->  DRAINING  --(no work left)-->  RETIRED
       |                      |
       +----- kill / hang / crash: beats stop ----->      DEAD
                     (discovered by the router's HeartbeatMonitor)

A DRAINING replica admits nothing: jobs still in its inbox bounce back
("handoff") and engine requests still WAITING (admitted to the engine's
queue but not yet prefilled — including requests the engine preempted
mid-drain) are aborted engine-side and handed off; RUNNING decodes finish
in place. When the engine drains empty the replica RETIRES and stamps its
drain duration — elastic scale-down with zero shed requests.

Death is never announced. The `fleet_replica_kill` site stops the pump
cold (SIGKILL: the engine is never touched again), `fleet_replica_hang`
wedges it (pumps keep arriving, nothing progresses), an engine exception
freezes it (the OOM-kill stand-in) — in every case the only symptom is a
heartbeat that stops, exactly like a preempted TPU host, and the router
must notice via missed beats and replay the replica's in-flight work.

Outbox event shapes (consumed by FleetRouter.poll):
    ("tokens",  fid, start_index, [tok, ...])   streamed generation delta
    ("done",    fid, terminal_engine_state)     request left the engine
    ("reject",  fid, retry_after_s)             engine admission refused
    ("handoff", fid)                            draining replica gave it up
"""
from __future__ import annotations

import threading
import time
from collections import deque

from ... import observability as obs
from ...resilience.faults import InjectedFault, fault_point

__all__ = ["EngineReplica", "HEALTHY", "DRAINING", "DEAD", "RETIRED",
           "STATE_ORDINAL"]

HEALTHY, DRAINING, DEAD, RETIRED = "healthy", "draining", "dead", "retired"
# gauge encoding for the per-replica fleet.replica_state series
STATE_ORDINAL = {HEALTHY: 0, DRAINING: 1, RETIRED: 2, DEAD: 3}

# engine terminal states (mirrors serving.engine._TERMINAL without reaching
# into the engine module's privates)
_ENGINE_TERMINAL = frozenset(
    {"finished", "aborted", "deadline_exceeded", "shed"})


class EngineReplica:
    """One engine + inbox/outbox/heartbeat shell. See the module docstring
    for the lifecycle; the router is the only writer of `state` except for
    the DRAINING->RETIRED transition, which the pump takes itself (only it
    knows when the engine is empty)."""

    def __init__(self, rid: int, engine, monitor, name: str | None = None):
        self.rid = int(rid)
        self.engine = engine
        self.monitor = monitor
        self.name = name or f"replica{rid}"
        self.state = HEALTHY
        self._inbox: deque = deque()
        self._outbox: deque = deque()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._hung = False
        self.crash: BaseException | None = None
        self.t_drain_start: float | None = None
        # stamped at every pump ENTRY: the router's health check compares it
        # to the last beat, so "pumped since the beat yet never beat again"
        # (kill/hang/crash) reads as death while "beat stale because the
        # shared inline thread sat in a neighbor's XLA compile" does not
        self.t_last_pump = time.monotonic()
        # pump-side maps: engine rid -> (fid, tokens already streamed out)
        self._fid_of: dict[int, int] = {}
        self._sent: dict[int, int] = {}
        monitor.register(self.name)

    # -- router-side API (thread-safe) --------------------------------------
    @property
    def alive(self) -> bool:
        return self.state in (HEALTHY, DRAINING)

    def enqueue(self, job: dict) -> None:
        """Queue one placement job ({fid, prompt, max_new_tokens, eos_id,
        sampling, priority, deadline_s}) or control ({abort: fid})."""
        with self._lock:
            self._inbox.append(job)

    def drain_events(self) -> list[tuple]:
        with self._lock:
            out = list(self._outbox)
            self._outbox.clear()
        return out

    def load(self) -> int:
        """Jobs this replica holds that the router still waits on — the
        router-visible placement load (inbox + streamed-but-unfinished)."""
        with self._lock:
            return len(self._inbox) + len(self._fid_of)

    def begin_drain(self) -> None:
        if self.state == HEALTHY:
            self.state = DRAINING
            self.t_drain_start = time.perf_counter()

    def mark_dead(self) -> None:
        self.state = DEAD
        self._stop.set()
        self.monitor.deregister(self.name)

    def sigkill(self) -> None:
        """SIGKILL-equivalent silent death (the chaos/bench trigger): the
        pump stops cold, the engine is never touched again, NOTHING is
        announced — the router must discover it by missed heartbeats. The
        `fleet_replica_kill` fault site lands here too."""
        self._hung = True
        if self.crash is None:
            self.crash = RuntimeError("sigkill")

    # -- worker thread (FleetRouter pump="threads") -------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._worker, name=self.name, daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)

    def _worker(self) -> None:
        while not self._stop.is_set() and self.alive:
            if not self.pump_once():
                # idle (or wedged): yield without burning the core
                time.sleep(0.001)

    # -- the pump (the ONLY code that touches the engine) -------------------
    def pump_once(self) -> bool:
        """One replica iteration: fault sites -> admit inbox -> one engine
        step -> stream deltas -> retire check -> heartbeat. Returns True if
        anything progressed."""
        if not self.alive:
            return False
        self.t_last_pump = time.monotonic()
        try:
            fault_point("fleet_replica_kill")
        except InjectedFault as e:
            # SIGKILL: no cleanup, no announcement — the heartbeat just
            # stops and the router must discover the death by missed beats
            self.crash = e
            self.sigkill()
            return False
        try:
            fault_point("fleet_replica_hang")
        except InjectedFault:
            self._hung = True  # wedged host: pumps arrive, nothing moves
        if self._hung:
            return False
        try:
            progressed = self._pump_inner()
        except Exception as e:  # noqa: BLE001 — a crashed engine IS a death
            self.crash = e
            self._hung = True
            obs.event("fleet.replica",
                      {"rid": self.rid, "state": "crashed",
                       "error": repr(e)}, level="error")
            return False
        # the beat says "this replica made a scheduling decision", even an
        # idle one; the slow-heartbeat site drops ONE stamp (a loaded host)
        try:
            fault_point("fleet_heartbeat_slow")
            self.monitor.beat(self.name)
        except InjectedFault:
            pass
        return progressed

    def _pump_inner(self) -> bool:
        progressed = self._admit_inbox()
        if self.state == DRAINING:
            self._handoff_waiting()
        if self.engine.has_work():
            self.engine.step()
            progressed = True
        self._stream_deltas()
        if (self.state == DRAINING and not self.engine.has_work()
                and not self._inbox):
            self.state = RETIRED
            self._stop.set()
            self.monitor.deregister(self.name)
        return progressed

    def _admit_inbox(self) -> bool:
        with self._lock:
            jobs, self._inbox = list(self._inbox), deque()
        moved = False
        for job in jobs:
            if "abort" in job:
                fid = job["abort"]
                erids = [e for e, f in self._fid_of.items() if f == fid]
                for erid in erids:
                    self.engine.abort(erid)
                moved = True
                continue
            fid = job["fid"]
            if self.state == DRAINING:
                self._emit("handoff", fid)
                continue
            try:
                erid = self.engine.submit(
                    job["prompt"], job["max_new_tokens"],
                    eos_id=job.get("eos_id"),
                    sampling=job.get("sampling"),
                    deadline_s=job.get("deadline_s"),
                    priority=job.get("priority"))
            except Exception as e:  # AdmissionRejected (or a bad request)
                self._emit("reject", fid,
                           getattr(e, "retry_after_s", 0.05))
                continue
            self._fid_of[erid] = fid
            self._sent[erid] = 0
            moved = True
        return moved

    def _handoff_waiting(self) -> None:
        """A draining replica's engine-side WAITING requests (never
        prefilled, or preempted back mid-drain) abort locally and bounce to
        the router for re-placement; RUNNING decodes finish in place."""
        for erid, fid in list(self._fid_of.items()):
            req = self.engine.requests.get(erid)
            if req is not None and req.state == "waiting":
                self.engine.abort(erid)
                # pop ONLY this record — a blanket prune_finished() here
                # would swallow same-step terminals not yet streamed out
                self.engine.pop_result(erid)
                self._fid_of.pop(erid, None)
                self._sent.pop(erid, None)
                self._emit("handoff", fid)

    def _stream_deltas(self) -> None:
        for erid, fid in list(self._fid_of.items()):
            req = self.engine.requests.get(erid)
            if req is None:  # record vanished underneath us: surface it as
                self._emit("done", fid, "aborted")  # lost, never go silent
                self._fid_of.pop(erid, None)
                self._sent.pop(erid, None)
                continue
            sent = self._sent[erid]
            out = req.out_tokens
            if len(out) > sent:
                self._emit("tokens", fid, sent, out[sent:])
                self._sent[erid] = len(out)
            if req.state in _ENGINE_TERMINAL:
                self._emit("done", fid, req.state)
                self.engine.pop_result(erid)
                self._fid_of.pop(erid, None)
                self._sent.pop(erid, None)

    def _emit(self, *event) -> None:
        with self._lock:
            self._outbox.append(tuple(event))
