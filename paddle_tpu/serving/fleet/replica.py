"""One engine replica as a failure domain.

`EngineReplica` owns one `ServingEngine` (its own KV pool, prefix cache,
compile caches) plus the thin shell the router needs around it: a
thread-safe inbox of placement jobs, an outbox of streamed results, a
heartbeat, and a lifecycle. EVERYTHING that touches the engine happens
inside `pump_once()` — the engine stays single-threaded by construction,
whether the pump runs inline on the router's thread or on the replica's
own worker thread (`FleetRouter(pump="threads")`).

Lifecycle (the three-state contract of ISSUE 16, plus the clean exit):

    HEALTHY  --drain()-->  DRAINING  --(no work left)-->  RETIRED
       |                      |
       +----- kill / hang / crash: beats stop ----->      DEAD
                     (discovered by the router's HeartbeatMonitor)

A DRAINING replica admits nothing: jobs still in its inbox bounce back
("handoff") and engine requests still WAITING (admitted to the engine's
queue but not yet prefilled — including requests the engine preempted
mid-drain) are aborted engine-side and handed off; RUNNING decodes finish
in place. When the engine drains empty the replica RETIRES and stamps its
drain duration — elastic scale-down with zero shed requests.

Death is never announced. The `fleet_replica_kill` site stops the pump
cold (SIGKILL: the engine is never touched again), `fleet_replica_hang`
wedges it (pumps keep arriving, nothing progresses), an engine exception
freezes it (the OOM-kill stand-in) — in every case the only symptom is a
heartbeat that stops, exactly like a preempted TPU host, and the router
must notice via missed beats and replay the replica's in-flight work.

Outbox event shapes (consumed by FleetRouter.poll):
    ("tokens",  fid, start_index, [tok, ...])   streamed generation delta
    ("done",    fid, terminal_engine_state)     request left the engine
    ("reject",  fid, retry_after_s)             engine admission refused
    ("handoff", fid)                            draining replica gave it up
    ("prepared", fid, lease_id)                 prefill published a lease
    ("adopted",  fid, lease_id)                 decode committed + adopted
    ("commit_failed", fid, lease_id, why)       commit bounced; replay me

Disaggregation (ISSUE 19): a `role="prefill"` replica never decodes —
after each step it extracts every freshly prefilled RUNNING request,
publishes it under a TTL'd lease (`HandoffManager.prepare`) and emits
"prepared"; the request sits HANDED_OFF (pages pinned) in `_pinned` until
the router's {"release": fid} job confirms the adopting side committed.
A decode-capable replica receives {"commit": lease_id, "fid": fid} jobs:
commit transfers the lease refcount, `engine.adopt_request` resumes the
decode mid-request, and every token streams from here (`_sent` starts at
0, so the prefill-produced first token is delivered by the ADOPTER — the
prefill side streams nothing for handed-off requests). The
`disagg_prefill_kill` fault site SIGKILLs a prefill replica exactly like
`fleet_replica_kill` does a generic one.
"""
from __future__ import annotations

import threading
import time
from collections import deque

from ... import observability as obs
from ...resilience.faults import InjectedFault, fault_point

__all__ = ["EngineReplica", "HEALTHY", "DRAINING", "DEAD", "RETIRED",
           "STATE_ORDINAL"]

HEALTHY, DRAINING, DEAD, RETIRED = "healthy", "draining", "dead", "retired"
# gauge encoding for the per-replica fleet.replica_state series
STATE_ORDINAL = {HEALTHY: 0, DRAINING: 1, RETIRED: 2, DEAD: 3}

# engine terminal states (mirrors serving.engine._TERMINAL without reaching
# into the engine module's privates)
_ENGINE_TERMINAL = frozenset(
    {"finished", "aborted", "deadline_exceeded", "shed"})


class EngineReplica:
    """One engine + inbox/outbox/heartbeat shell. See the module docstring
    for the lifecycle; the router is the only writer of `state` except for
    the DRAINING->RETIRED transition, which the pump takes itself (only it
    knows when the engine is empty)."""

    def __init__(self, rid: int, engine, monitor, name: str | None = None,
                 role: str = "mixed", handoff=None):
        self.rid = int(rid)
        self.engine = engine
        self.monitor = monitor
        self.role = str(role)  # "mixed" | "prefill" | "decode"
        self.handoff = handoff  # shared HandoffManager (disagg fleets only)
        self.name = name or f"replica{rid}"
        self.state = HEALTHY
        self._inbox: deque = deque()
        self._outbox: deque = deque()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._hung = False
        self.crash: BaseException | None = None
        self.t_drain_start: float | None = None
        # stamped at every pump ENTRY: the router's health check compares it
        # to the last beat, so "pumped since the beat yet never beat again"
        # (kill/hang/crash) reads as death while "beat stale because the
        # shared inline thread sat in a neighbor's XLA compile" does not
        self.t_last_pump = time.monotonic()
        # pump-side maps: engine rid -> (fid, tokens already streamed out)
        self._fid_of: dict[int, int] = {}
        self._sent: dict[int, int] = {}
        # prefill-side: fid -> engine rid of a HANDED_OFF request whose
        # prefill pin awaits the router's post-commit {"release": fid}
        self._pinned: dict[int, int] = {}
        monitor.register(self.name)

    # -- router-side API (thread-safe) --------------------------------------
    @property
    def alive(self) -> bool:
        return self.state in (HEALTHY, DRAINING)

    def enqueue(self, job: dict) -> None:
        """Queue one placement job ({fid, prompt, max_new_tokens, eos_id,
        sampling, priority, deadline_s}) or control ({abort: fid})."""
        with self._lock:
            self._inbox.append(job)

    def drain_events(self) -> list[tuple]:
        with self._lock:
            out = list(self._outbox)
            self._outbox.clear()
        return out

    def load(self) -> int:
        """Jobs this replica holds that the router still waits on — the
        router-visible placement load (inbox + streamed-but-unfinished)."""
        with self._lock:
            return len(self._inbox) + len(self._fid_of)

    def begin_drain(self) -> None:
        if self.state == HEALTHY:
            self.state = DRAINING
            self.t_drain_start = time.perf_counter()

    def mark_dead(self) -> None:
        self.state = DEAD
        self._stop.set()
        self.monitor.deregister(self.name)

    def sigkill(self) -> None:
        """SIGKILL-equivalent silent death (the chaos/bench trigger): the
        pump stops cold, the engine is never touched again, NOTHING is
        announced — the router must discover it by missed heartbeats. The
        `fleet_replica_kill` fault site lands here too."""
        self._hung = True
        if self.crash is None:
            self.crash = RuntimeError("sigkill")

    # -- worker thread (FleetRouter pump="threads") -------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._worker, name=self.name, daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)

    def _worker(self) -> None:
        while not self._stop.is_set() and self.alive:
            if not self.pump_once():
                # idle (or wedged): yield without burning the core
                time.sleep(0.001)

    # -- the pump (the ONLY code that touches the engine) -------------------
    def pump_once(self) -> bool:
        """One replica iteration: fault sites -> admit inbox -> one engine
        step -> stream deltas -> retire check -> heartbeat. Returns True if
        anything progressed."""
        if not self.alive:
            return False
        self.t_last_pump = time.monotonic()
        try:
            fault_point("fleet_replica_kill")
        except InjectedFault as e:
            # SIGKILL: no cleanup, no announcement — the heartbeat just
            # stops and the router must discover the death by missed beats
            self.crash = e
            self.sigkill()
            return False
        if self.role == "prefill":
            try:
                # targeted SIGKILL of the prefill stage: any lease this
                # replica already published survives in the SHARED pool and
                # still commits; anything pre-PREPARE replays on a
                # surviving prefill replica
                fault_point("disagg_prefill_kill")
            except InjectedFault as e:
                self.crash = e
                self.sigkill()
                return False
        try:
            fault_point("fleet_replica_hang")
        except InjectedFault:
            self._hung = True  # wedged host: pumps arrive, nothing moves
        if self._hung:
            return False
        try:
            progressed = self._pump_inner()
        except Exception as e:  # noqa: BLE001 — a crashed engine IS a death
            self.crash = e
            self._hung = True
            obs.event("fleet.replica",
                      {"rid": self.rid, "state": "crashed",
                       "error": repr(e)}, level="error")
            return False
        # the beat says "this replica made a scheduling decision", even an
        # idle one; the slow-heartbeat site drops ONE stamp (a loaded host)
        try:
            fault_point("fleet_heartbeat_slow")
            self.monitor.beat(self.name)
        except InjectedFault:
            pass
        return progressed

    def _pump_inner(self) -> bool:
        # the replica's idle gap is a safe actuation boundary (ISSUE 20):
        # before admitting new arrivals, let a staged controller config
        # swap in while nothing is in flight (no-op without one pending)
        self.engine.maybe_adopt_config()
        progressed = self._admit_inbox()
        if self.state == DRAINING:
            self._handoff_waiting()
        if self.engine.has_work():
            self.engine.step()
            progressed = True
        if self.role == "prefill" and self.handoff is not None:
            # BEFORE streaming: extraction removes the request from
            # `_fid_of`, so the prefill-produced first token never streams
            # from here — the adopter delivers it (exactly-once by
            # construction, not by dedup)
            self._extract_prepared()
        self._stream_deltas()
        if (self.state == DRAINING and not self.engine.has_work()
                and not self._inbox):
            self.state = RETIRED
            self._stop.set()
            self.monitor.deregister(self.name)
        return progressed

    def _admit_inbox(self) -> bool:
        with self._lock:
            jobs, self._inbox = list(self._inbox), deque()
        moved = False
        deferred: list[dict] = []
        for job in jobs:
            if "abort" in job:
                fid = job["abort"]
                erids = [e for e, f in self._fid_of.items() if f == fid]
                for erid in erids:
                    self.engine.abort(erid)
                moved = True
                continue
            if "release" in job:
                # post-commit confirmation: the adopter's ledger carries
                # the pages now, drop the prefill pin (idempotent)
                erid = self._pinned.pop(job["release"], None)
                if erid is not None:
                    self.engine.release_handoff(erid)
                moved = True
                continue
            if "commit" in job:
                # backpressure: an adopted request enters RUNNING directly,
                # so the commit waits for a decode slot rather than consume
                # the lease into an overfull batch. The lease keeps aging —
                # if this replica stays saturated past the TTL, the reaper
                # replays the request elsewhere.
                if self.state != DRAINING \
                        and not self.engine.decode_slots_free:
                    deferred.append(job)
                    continue
                self._commit_job(job["commit"], job["fid"])
                moved = True
                continue
            fid = job["fid"]
            if self.state == DRAINING:
                self._emit("handoff", fid)
                continue
            try:
                erid = self.engine.submit(
                    job["prompt"], job["max_new_tokens"],
                    eos_id=job.get("eos_id"),
                    sampling=job.get("sampling"),
                    deadline_s=job.get("deadline_s"),
                    priority=job.get("priority"))
            except Exception as e:  # AdmissionRejected (or a bad request)
                self._emit("reject", fid,
                           getattr(e, "retry_after_s", 0.05))
                continue
            self._fid_of[erid] = fid
            self._sent[erid] = 0
            moved = True
        if deferred:  # retry next pump, ahead of newer jobs
            with self._lock:
                self._inbox.extendleft(reversed(deferred))
        return moved

    def _handoff_waiting(self) -> None:
        """A draining replica's engine-side WAITING requests (never
        prefilled, or preempted back mid-drain) abort locally and bounce to
        the router for re-placement; RUNNING decodes finish in place."""
        for erid, fid in list(self._fid_of.items()):
            req = self.engine.requests.get(erid)
            if req is not None and req.state == "waiting":
                self.engine.abort(erid)
                # pop ONLY this record — a blanket prune_finished() here
                # would swallow same-step terminals not yet streamed out
                self.engine.pop_result(erid)
                self._fid_of.pop(erid, None)
                self._sent.pop(erid, None)
                self._emit("handoff", fid)

    def _extract_prepared(self) -> None:
        """PREPARE: every request this prefill engine finished prefilling
        (state RUNNING — requests that went terminal AT prefill stream
        normally from here) leaves the scheduler HANDED_OFF and its
        transfer state is published under a TTL'd lease. From this emit on
        the request's fate is the lease's: commit adopts it elsewhere,
        reap replays it, and our pin waits for the router's release."""
        for erid, fid in list(self._fid_of.items()):
            req = self.engine.requests.get(erid)
            if req is None or req.state != "running":
                continue
            payload = self.engine.extract_for_handoff(erid)
            lid = self.handoff.prepare(fid, payload)
            self._pinned[fid] = erid
            self._fid_of.pop(erid, None)
            self._sent.pop(erid, None)
            self._emit("prepared", fid, lid)

    def _commit_job(self, lid: str, fid: int) -> None:
        """COMMIT: adopt one leased request into this engine. Every
        failure mode answers with "commit_failed" — silence would strand
        the request until the lease reaper noticed — and the commit/adopt
        pair never half-applies: a commit that throws left the pin with
        the lease; an adopt that throws hands the transferred refcount
        straight back to the shared pool."""
        from .handoff import HandoffError

        if self.state == DRAINING:
            self._emit("commit_failed", fid, lid, "draining")
            return
        try:
            lease = self.handoff.commit(lid)
        except HandoffError as e:
            self._emit("commit_failed", fid, lid, repr(e))
            return
        try:
            erid = self.engine.adopt_request(lease.payload)
        except Exception as e:  # noqa: BLE001 — refcount must not strand
            self.handoff.pool.release(lease.pages)
            self._emit("commit_failed", fid, lid, repr(e))
            return
        self._fid_of[erid] = fid
        self._sent[erid] = 0
        self._emit("adopted", fid, lid)

    def _stream_deltas(self) -> None:
        for erid, fid in list(self._fid_of.items()):
            req = self.engine.requests.get(erid)
            if req is None:  # record vanished underneath us: surface it as
                self._emit("done", fid, "aborted")  # lost, never go silent
                self._fid_of.pop(erid, None)
                self._sent.pop(erid, None)
                continue
            sent = self._sent[erid]
            out = req.out_tokens
            if len(out) > sent:
                self._emit("tokens", fid, sent, out[sent:])
                self._sent[erid] = len(out)
            if req.state in _ENGINE_TERMINAL:
                self._emit("done", fid, req.state)
                self.engine.pop_result(erid)
                self._fid_of.pop(erid, None)
                self._sent.pop(erid, None)

    def _emit(self, *event) -> None:
        with self._lock:
            self._outbox.append(tuple(event))
